"""Pallas kernels (interpret mode on CPU) must match the plain-JAX
reference implementations, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.pallas

from neural_networks_parallel_training_with_mpi_tpu.ops import (
    pallas_kernels as pk,
)
from neural_networks_parallel_training_with_mpi_tpu.ops.pallas_kernels import (
    flash_attention, fused_layernorm,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel.sequence import (
    attention_reference,
)


def _qkv(b=2, t=64, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_attention_matches_dense(causal, block):
    q, k, v = _qkv()
    expected = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, block, block, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_grads_match_dense():
    q, k, v = _qkv(t=32)

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 16, 16, True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_attention_in_transformer():
    """attention='flash' end to end through the model."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    mk = lambda att: Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=t, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention=att))
    params = mk("dense").init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, t)),
                      jnp.int32)
    dense = mk("dense").apply(params, ids)
    flash = mk("flash").apply(params, ids)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_fused_layernorm_matches_reference():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 8, 32)), jnp.float32)
    scale = jnp.asarray(rng.standard_normal((32,)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal((32,)), jnp.float32)

    x32 = np.asarray(x, np.float64)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    expected = ((x32 - mean) / np.sqrt(var + 1e-5)) * np.asarray(scale) \
        + np.asarray(bias)

    got = fused_layernorm(x, scale, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4,
                               atol=1e-5)


def test_pallas_backward_matches_blocked_reference_vjp():
    """The two Mosaic backward kernels (dq; dk+dv) vs autodiff of
    _blocked_attention_reference — the same online-softmax math expressed in
    plain JAX.  This pins the hand-derived ds/dq/dk/dv algebra against an
    independently-differentiated implementation (not just the dense path)."""
    from neural_networks_parallel_training_with_mpi_tpu.ops.pallas_kernels import (
        _blocked_attention_reference,
    )

    q, k, v = _qkv(t=64)
    g = jnp.asarray(
        np.random.default_rng(7).standard_normal(q.shape), jnp.float32)

    out, vjp = jax.vjp(
        lambda q_, k_, v_: _blocked_attention_reference(q_, k_, v_, True, 16),
        q, k, v)
    want = vjp(g)

    def flash(q_, k_, v_):
        return flash_attention(q_, k_, v_, True, 16, 16, True)

    out_fa, vjp_fa = jax.vjp(flash, q, k, v)
    got = vjp_fa(g)

    np.testing.assert_allclose(np.asarray(out_fa), np.asarray(out),
                               rtol=2e-4, atol=2e-5)
    for name, a, b in zip(("dq", "dk", "dv"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4, err_msg=name)


def test_flash_attention_with_lse_value_and_grads():
    """(out, lse) variant: both outputs and BOTH cotangent paths (the lse
    cotangent rides the Mosaic backward as a delta shift) must match a
    plain-JAX attention-with-lse reference."""
    import jax.scipy.special as jsp

    def ref_with_lse(q, k, v, causal):
        d = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * d**-0.5
        if causal:
            t = q.shape[1]
            mask = jnp.arange(t)[None, :] <= jnp.arange(t)[:, None]
            s = jnp.where(mask[None, None], s, -1e30)
        lse = jsp.logsumexp(s, axis=-1)                     # (B, H, T)
        p = jnp.exp(s - lse[..., None])
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        b, t, h, _ = q.shape
        return out, lse.reshape(b * h, t)

    rng = np.random.default_rng(0)
    b, t, h, d = 2, 16, 2, 8
    mk = lambda: jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    for causal in (True, False):
        o1, l1 = pk.flash_attention_with_lse(q, k, v, causal, 16, 16, True)
        o2, l2 = ref_with_lse(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5, atol=1e-5)

        # nonlinear functions of BOTH outputs exercise g_out and g_lse
        def loss(fn):
            def f(q, k, v):
                o, l = fn(q, k, v)
                return (o ** 2).sum() + jnp.sin(l).sum()
            return f

        g1 = jax.grad(loss(lambda q, k, v: pk.flash_attention_with_lse(
            q, k, v, causal, 16, 16, True)), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss(lambda q, k, v: ref_with_lse(q, k, v, causal)),
                      argnums=(0, 1, 2))(q, k, v)
        for a, bb in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=2e-5, atol=2e-5)


def test_flash_attention_rectangular_blocks():
    """block_q != block_k tilings (the flagship sweep tunes block_k
    independently — tools/big_lm_sweep.py) must be numerically identical
    to the dense reference, fwd and bwd."""
    q, k, v = _qkv(t=64)
    expected = attention_reference(q, k, v, causal=True)
    for bq, bk in ((16, 32), (32, 16), (16, 64)):
        got = flash_attention(q, k, v, True, bq, bk, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"bq={bq} bk={bk}")

    def loss(bq, bk):
        return lambda q_, k_, v_: (
            flash_attention(q_, k_, v_, True, bq, bk, True) ** 2).sum()

    g_ref = jax.grad(loss(16, 16), argnums=(0, 1, 2))(q, k, v)
    g_rect = jax.grad(loss(16, 32), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), g_rect, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4, err_msg=name)


def test_flash_block_config_reaches_kernel():
    """TransformerConfig.flash_block_q/flash_block_k thread through
    sequence_sharded_attention to the kernel: a non-default legal tiling
    gives the same forward as the default, and an illegal one (not
    dividing T) raises — proof the values actually arrive."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    mk = lambda **kw: Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=t, n_layers=1, d_model=32, n_heads=4,
        d_ff=64, attention="flash", **kw))
    params = mk().init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, t)),
                      jnp.int32)
    default = mk().apply(params, ids)
    tuned = mk(flash_block_q=16, flash_block_k=8).apply(params, ids)
    np.testing.assert_allclose(np.asarray(tuned), np.asarray(default),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="not divisible"):
        mk(flash_block_k=24).apply(params, ids)
