"""scan_layers: lax.scan over stacked transformer blocks.

XLA traces ONE block body regardless of depth (compile time / program size
stop growing with n_layers — the TPU-idiomatic deep-model layout).  Must be
a pure re-scheduling: same logits, same training trajectory, same decode
output as the per-layer Python loop.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

# integration-heavy: full lane only (core lane: -m 'not slow')
pytestmark = pytest.mark.slow


def _cfgs(n_layers=4):
    base = TransformerConfig(vocab_size=64, max_seq_len=16, n_layers=n_layers,
                             d_model=32, n_heads=4, d_ff=64)
    return base, dataclasses.replace(base, scan_layers=True)


def _stack(blocks):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)


def test_scan_layers_matches_loop_logits():
    cfg_loop, cfg_scan = _cfgs()
    loop = Transformer(cfg_loop)
    scan = Transformer(cfg_scan)
    params = loop.init(prng.init_key(0))
    stacked = dict(params)
    stacked["blocks"] = _stack(params["blocks"])
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)),
                      jnp.int32)
    np.testing.assert_allclose(np.asarray(scan.apply(stacked, ids)),
                               np.asarray(loop.apply(params, ids)),
                               rtol=1e-5, atol=1e-5)


def test_scan_layers_init_is_stacked_and_equal():
    cfg_loop, cfg_scan = _cfgs()
    p_loop = Transformer(cfg_loop).init(prng.init_key(0))
    p_scan = Transformer(cfg_scan).init(prng.init_key(0))
    want = _stack(p_loop["blocks"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        p_scan["blocks"], want)


def test_scan_layers_trains_to_same_trajectory():
    def cfg(scan):
        return TrainConfig(
            nepochs=2, batch_size=32, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                            vocab_size=64),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=64,
                              max_seq_len=16, scan_layers=scan),
            mesh=MeshConfig(data=8),
        )

    r_loop = Trainer(cfg(False)).fit()
    r_scan = Trainer(cfg(True)).fit()
    assert r_scan["final_loss"] == pytest.approx(r_loop["final_loss"],
                                                 rel=1e-5)


def test_scan_layers_generate_matches_loop():
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate,
    )

    cfg_loop, cfg_scan = _cfgs()
    loop = Transformer(cfg_loop)
    scan = Transformer(cfg_scan)
    params = loop.init(prng.init_key(1))
    stacked = dict(params)
    stacked["blocks"] = _stack(params["blocks"])
    prompt = jnp.asarray([[1, 2, 3], [7, 8, 9]], jnp.int32)
    out_loop = generate(loop, params, prompt, 6)
    out_scan = generate(scan, stacked, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out_scan), np.asarray(out_loop))


def test_scan_layers_rejected_on_owned_layouts():
    cfg = TrainConfig(
        nepochs=1, loss="cross_entropy",
        data=DataConfig(dataset="lm", n_samples=64, seq_len=16, vocab_size=64),
        model=ModelConfig(arch="transformer", n_layers=4, d_model=32,
                          n_heads=4, d_ff=64, vocab_size=64, max_seq_len=16,
                          scan_layers=True),
        mesh=MeshConfig(data=4, pipe=2),
    )
    with pytest.raises(ValueError, match="scan_layers"):
        Trainer(cfg)


def test_scan_layers_with_ring_attention_and_remat():
    """scan over layers composes with seq parallelism (ring attention in
    the scan body) and remat (checkpointed body)."""
    cfg = TrainConfig(
        nepochs=1, batch_size=32, full_batch=False, shuffle=False,
        loss="cross_entropy", optimizer="adam", lr=1e-3,
        data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                        vocab_size=64),
        model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                          n_heads=4, d_ff=64, vocab_size=64, max_seq_len=16,
                          scan_layers=True, remat=True, attention="ring"),
        mesh=MeshConfig(data=4, seq=2),
    )
    r = Trainer(cfg).fit()
    assert np.isfinite(r["final_loss"])


@pytest.mark.parametrize("policy", ["full", "dots", "dots_no_batch"])
def test_remat_policies_preserve_semantics(policy):
    """--remat_policy selects WHAT jax.checkpoint saves (models.core
    make_remat); every policy must leave the computation identical —
    only HBM/recompute change."""
    import dataclasses as dc

    from neural_networks_parallel_training_with_mpi_tpu.ops import losses

    base_cfg = TransformerConfig(vocab_size=64, max_seq_len=16, n_layers=2,
                                 d_model=32, n_heads=4, d_ff=64)
    ids = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(np.int32)
    tgt = np.random.default_rng(1).integers(0, 64, (2, 16)).astype(np.int32)

    def grads_for(cfg):
        model = Transformer(cfg)
        params = Transformer(base_cfg).init(prng.init_key(0))

        def loss(p):
            s, c = losses.softmax_cross_entropy(
                model.apply(p, jnp.asarray(ids)), jnp.asarray(tgt))
            return s / c

        return jax.jit(jax.value_and_grad(loss))(params)

    v0, g0 = grads_for(base_cfg)
    v1, g1 = grads_for(dc.replace(base_cfg, remat=True,
                                  remat_policy=policy))
    assert float(v0) == pytest.approx(float(v1), rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-7),
        g0, g1)


def test_make_remat_rejects_unknown_policy():
    from neural_networks_parallel_training_with_mpi_tpu.models.core import (
        make_remat,
    )

    with pytest.raises(ValueError, match="unknown remat policy"):
        make_remat("everything")


def test_scan_layers_on_sp_tp_matches_loop():
    """scan_layers on the seq x tensor path: stacked Megatron blocks run
    as ONE scanned block body; trajectory must match the per-layer-loop
    sp_tp trainer on the same job."""
    def run(scan):
        cfg = TrainConfig(
            nepochs=2, batch_size=32, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                            vocab_size=64),
            model=ModelConfig(arch="transformer", n_layers=4, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=64,
                              max_seq_len=16, attention="ring",
                              scan_layers=scan),
            mesh=MeshConfig(data=2, seq=2, tensor=2),
        )
        t = Trainer(cfg)
        assert t.sp_tp
        r = t.fit()
        params = jax.device_get(t._eval_params())
        blocks = params["blocks"]
        if scan:  # unstack for comparison with the per-layer layout
            leaves = jax.tree_util.tree_leaves(blocks)
            n = leaves[0].shape[0]
            blocks = [jax.tree_util.tree_map(lambda x, i=i: x[i], blocks)
                      for i in range(n)]
        return r["final_loss"], blocks

    loss_loop, blocks_loop = run(False)
    loss_scan, blocks_scan = run(True)
    assert loss_scan == pytest.approx(loss_loop, rel=1e-4)
    # scan vs unrolled loop fuse differently; Adam amplifies the f32
    # reassociation noise to ~1e-5-sized param deltas over 2 epochs (the
    # same LOOSE tolerance story as tests/test_composition.py)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-3, atol=1e-4),
        blocks_scan, blocks_loop)
