"""Pipeline parallelism must be a pure re-scheduling: a DP x PP pipelined
train step produces the same loss and the same updated weights as a
single-device dense step over the identical global batch and params."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops import losses, optim
from neural_networks_parallel_training_with_mpi_tpu.parallel import pipeline as pp
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import make_mesh
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

# integration-heavy: full lane only (core lane: -m 'not slow')
pytestmark = pytest.mark.slow

VOCAB, T = 64, 16


def tiny_model(n_layers=4, attention="dense"):
    return Transformer(TransformerConfig(
        vocab_size=VOCAB, max_seq_len=T, n_layers=n_layers, d_model=32,
        n_heads=4, d_ff=64, attention=attention))


def lm_batch(rows, seed=0):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, VOCAB, (rows, T + 1))
    return {"x": tok[:, :-1].astype(np.int32),
            "y": tok[:, 1:].astype(np.int32),
            "mask": np.ones((rows,), np.float32)}


def reference_step(model, opt, params, batch):
    """Single-device global-mean CE step on the unpipelined model."""
    def scalar(p):
        logits = model.apply(p, jnp.asarray(batch["x"]))
        s, c = losses.softmax_cross_entropy(logits, jnp.asarray(batch["y"]),
                                            jnp.asarray(batch["mask"]))
        return s / c, (s, c)

    (loss, _), grads = jax.value_and_grad(scalar, has_aux=True)(params)
    opt_state = opt.init(params)
    new_params, _ = opt.update(grads, opt_state, params)
    return loss, new_params


def test_stack_unstack_roundtrip():
    model = tiny_model(4)
    params = model.init(prng.init_key(0))
    stacked = pp.stack_blocks(params["blocks"], 2)
    back = pp.unstack_blocks(stacked)
    assert len(back) == 4
    for orig, rt in zip(params["blocks"], back):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            orig, rt)


@pytest.mark.parametrize("pipe,data,n_mb", [(4, 2, 4), (2, 1, 6)])
def test_pipeline_matches_single_device(pipe, data, n_mb):
    devs = jax.devices("cpu")[: pipe * data]
    mesh = make_mesh(MeshConfig(data=data, pipe=pipe), devices=devs)
    model = tiny_model(4)
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=data * n_mb * 2)

    state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                                  n_microbatches=n_mb)

    params = model.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(model, opt, params, batch)

    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)

    got_blocks = pp.unstack_blocks(jax.device_get(state.params["blocks"]))
    ref_blocks = jax.device_get(ref_params["blocks"])
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)
    for name in ("embed", "pos", "ln_f", "head"):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            jax.device_get(state.params[name]), jax.device_get(ref_params[name]))


def test_pipeline_multiple_steps_decrease_loss():
    devs = jax.devices("cpu")[:4]
    mesh = make_mesh(MeshConfig(data=1, pipe=4), devices=devs)
    model = tiny_model(4)
    opt = optim.adam(lr=1e-2)
    batch = lm_batch(rows=8)

    state = pp.init_pipeline_state(model, opt, prng.init_key(0), 4)
    state = pp.shard_pipeline_state(state, mesh, opt)
    from jax.sharding import NamedSharding, PartitionSpec as P
    placed = {k: jax.device_put(jnp.asarray(v),
                                NamedSharding(mesh, P(("data", "fsdp"))))
              for k, v in batch.items()}
    step = pp.make_pipeline_train_step(model, opt, mesh, n_microbatches=4,
                                       donate=False)
    state, first = step(state, placed)
    for _ in range(10):
        state, loss = step(state, placed)
    assert float(loss) < float(first)
    assert int(state.step) == 11


def test_bubble_fraction_accounting():
    """More microbatches -> smaller bubble; accounting matches the scan
    length the step actually runs (n_mb + n_stages - 1 ticks)."""
    assert pp.schedule_ticks(4, 4) == 7
    assert pp.bubble_fraction(4, 4) == pytest.approx(3 / 7)
    # accum_steps folding (Trainer: n_mb = n_stages * accum) shrinks it
    assert pp.bubble_fraction(4, 16) == pytest.approx(3 / 19)
    assert pp.bubble_fraction(4, 16) < pp.bubble_fraction(4, 4)
    assert pp.bubble_fraction(2, 64) < 0.02


@pytest.mark.parametrize("pipe,data,v,n_mb", [(2, 2, 2, 2), (2, 1, 2, 4),
                                              (4, 1, 2, 4)])
def test_interleaved_matches_single_device(pipe, data, v, n_mb):
    """Virtual-stage interleaving is a pure re-scheduling: loss and updated
    weights match the single-device dense step exactly (same bar as the
    plain GPipe ring)."""
    devs = jax.devices("cpu")[: pipe * data]
    mesh = make_mesh(MeshConfig(data=data, pipe=pipe), devices=devs)
    model = tiny_model(pipe * v)  # one layer per virtual stage
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=data * n_mb * 2)

    state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                                  n_microbatches=n_mb, interleave=v)

    params = model.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(model, opt, params, batch)

    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    got_blocks = pp.unstack_blocks(jax.device_get(state.params["blocks"]),
                                   stack_ndims=3)
    ref_blocks = jax.device_get(ref_params["blocks"])
    assert len(got_blocks) == len(ref_blocks)
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)


def test_interleaved_with_tensor_matches_single_device():
    """Interleave composes with the pipeline's Megatron tensor axis
    (DP x TP x PP with virtual stages): still a pure re-scheduling."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    pipe, tp, v, n_mb = 2, 2, 2, 2
    devs = jax.devices("cpu")[: pipe * tp * 2]
    mesh = make_mesh(MeshConfig(data=2, pipe=pipe, tensor=tp), devices=devs)
    model = tiny_model(pipe * v)
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=2 * n_mb * 2)

    state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                                  n_microbatches=n_mb, interleave=v)

    params = model.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(model, opt, params, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)

    got_stack = megatron.permute_qkv(
        jax.device_get(state.params["blocks"]), model.cfg.d_model,
        model.cfg.n_heads, tp, inverse=True)
    got_blocks = pp.unstack_blocks(got_stack, stack_ndims=3)
    ref_blocks = jax.device_get(ref_params["blocks"])
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)


def test_interleaved_matches_gpipe_trajectory():
    """interleave=2 and the plain ring compute the SAME math (GPipe
    semantics) — multi-step trajectories agree to float tolerance."""
    devs = jax.devices("cpu")[:2]
    mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=devs)
    model = tiny_model(4)
    opt = optim.adam(lr=1e-2)
    batch = lm_batch(rows=8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    placed = {k: jax.device_put(jnp.asarray(v),
                                NamedSharding(mesh, P(("data", "fsdp"))))
              for k, v in batch.items()}
    losses_by_v = {}
    for v in (1, 2):
        state = pp.init_pipeline_state(model, opt, prng.init_key(0), 2,
                                       interleave=v)
        state = pp.shard_pipeline_state(state, mesh, opt, interleave=v)
        step = pp.make_pipeline_train_step(model, opt, mesh,
                                           n_microbatches=4, donate=False,
                                           interleave=v)
        traj = []
        for _ in range(4):
            state, loss = step(state, placed)
            traj.append(float(loss))
        losses_by_v[v] = traj
    np.testing.assert_allclose(losses_by_v[1], losses_by_v[2], rtol=1e-5)


def test_interleaved_eval_matches_dense():
    devs = jax.devices("cpu")[:2]
    mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=devs)
    model = tiny_model(4)
    opt = optim.sgd(lr=0.1)
    batch = lm_batch(rows=8, seed=3)
    state = pp.init_pipeline_state(model, opt, prng.init_key(1), 2,
                                   interleave=2)
    state = pp.shard_pipeline_state(state, mesh, opt, interleave=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    placed = {k: jax.device_put(jnp.asarray(v),
                                NamedSharding(mesh, P(("data", "fsdp"))))
              for k, v in batch.items()}
    ev = pp.make_pipeline_eval_step(model, mesh, with_accuracy=True,
                                    n_microbatches=2, interleave=2)
    got = ev(state.params, placed)

    params = model.init(prng.init_key(1))
    logits = model.apply(params, jnp.asarray(batch["x"]))
    s, c = losses.softmax_cross_entropy(logits, jnp.asarray(batch["y"]),
                                        jnp.asarray(batch["mask"]))
    np.testing.assert_allclose(float(got["loss"]), float(s / c), rtol=1e-5)
    assert float(got["count"]) == float(c)


def test_interleaved_bubble_shrinks_at_constant_microbatches():
    """The r2 item 5 claim: v virtual stages divide the warmup/drain bubble
    at CONSTANT microbatch count — (S-1)/(vM+S-1) — refuting the earlier
    'only more microbatches can' note; ticks match the scan length."""
    assert pp.schedule_ticks(4, 8, interleave=2) == 19
    assert pp.bubble_fraction(4, 8, interleave=2) == pytest.approx(3 / 19)
    assert (pp.bubble_fraction(4, 8, interleave=2)
            < pp.bubble_fraction(4, 8))
    assert (pp.bubble_fraction(4, 8, interleave=4)
            < pp.bubble_fraction(4, 8, interleave=2))
    # v=1 reduces to the plain accounting
    assert pp.bubble_fraction(4, 8, interleave=1) == pp.bubble_fraction(4, 8)


def test_interleaved_rejects_ragged_groups():
    devs = jax.devices("cpu")[:2]
    mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=devs)
    model = tiny_model(4)
    opt = optim.sgd(lr=0.1)
    with pytest.raises(ValueError, match="groups of n_stages"):
        pp.make_pipeline_train_step(model, opt, mesh, n_microbatches=3,
                                    interleave=2)


def test_pipeline_eval_matches_dense_eval():
    """The forward-only ring schedule on pipe-sharded params must produce
    the same loss/accuracy as the dense model on gathered params."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
    )

    model = tiny_model(4)
    mesh = make_mesh(MeshConfig(data=2, pipe=2),
                     devices=jax.devices("cpu")[:4])
    opt = optim.sgd(lr=1e-2)
    state = pp.init_pipeline_state(model, opt, prng.init_key(0), 2)
    state = pp.shard_pipeline_state(state, mesh, opt)
    batch = lm_batch(8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    placed = {k: jax.device_put(jnp.asarray(v),
                                NamedSharding(mesh, P(("data", "fsdp"))))
              for k, v in batch.items()}
    eval_step = pp.make_pipeline_eval_step(model, mesh, "cross_entropy",
                                           with_accuracy=True)
    got = jax.device_get(eval_step(state.params, placed))

    dense_params = dict(jax.device_get(state.params))
    dense_params["blocks"] = pp.unstack_blocks(dense_params["blocks"])
    dense_eval = dp.make_eval_step(model, mesh, "cross_entropy",
                                   with_accuracy=True)
    rep = jax.device_put(dense_params, NamedSharding(mesh, P()))
    want = jax.device_get(dense_eval(rep, placed))

    assert float(got["count"]) == float(want["count"])
    np.testing.assert_allclose(float(got["loss"]), float(want["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(got["accuracy"]),
                               float(want["accuracy"]), rtol=1e-5)


def test_pipeline_remat_matches_no_remat():
    """cfg.remat re-materializes stage activations in the backward; the
    trajectory must be identical to the stored-activation path."""
    import dataclasses as dc

    mesh = make_mesh(MeshConfig(data=2, pipe=2),
                     devices=jax.devices("cpu")[:4])
    batch = lm_batch(8)
    results = []
    for remat in (False, True):
        model = Transformer(dc.replace(tiny_model(4).cfg, remat=remat))
        opt = optim.sgd(lr=1e-2)
        state, loss = pp.run_one_step(model, opt, mesh, batch,
                                      prng.init_key(0))
        results.append((float(jax.device_get(loss)),
                        jax.device_get(state.params)))
    assert results[0][0] == pytest.approx(results[1][0], rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a, np.float32),
                                                np.asarray(b, np.float32),
                                                rtol=1e-6, atol=1e-7),
        results[0][1], results[1][1])


def test_pipeline_eval_pads_non_divisible_batch():
    """A validation batch whose per-shard rows don't divide into the
    schedule's microbatches is padded with mask-0 rows — same metrics as the
    dense eval on the unpadded batch (the small-val-set case that must not
    crash: VERDICT r1 review)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
    )

    model = tiny_model(4)
    mesh = make_mesh(MeshConfig(data=2, pipe=2),
                     devices=jax.devices("cpu")[:4])
    opt = optim.sgd(lr=1e-2)
    state = pp.init_pipeline_state(model, opt, prng.init_key(0), 2)
    state = pp.shard_pipeline_state(state, mesh, opt)
    batch = lm_batch(6)  # per data-shard: 3 rows, n_mb=2 -> pad 1
    placed = {k: jax.device_put(jnp.asarray(v),
                                NamedSharding(mesh, P(("data", "fsdp"))))
              for k, v in batch.items()}
    eval_step = pp.make_pipeline_eval_step(model, mesh, "cross_entropy",
                                           with_accuracy=True)
    got = jax.device_get(eval_step(state.params, placed))

    dense_params = dict(jax.device_get(state.params))
    dense_params["blocks"] = pp.unstack_blocks(dense_params["blocks"])
    rep = jax.device_put(dense_params, NamedSharding(mesh, P()))
    dense_eval = dp.make_eval_step(model, mesh, "cross_entropy",
                                   with_accuracy=True)
    want = jax.device_get(dense_eval(rep, placed))

    assert float(got["count"]) == float(want["count"])  # pads not counted
    np.testing.assert_allclose(float(got["loss"]), float(want["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(got["accuracy"]),
                               float(want["accuracy"]), rtol=1e-5)

def test_pipeline_tensor_flash_matches_single_device():
    """PP x TP with flash attention (VERDICT r3 item 4): the Pallas flash
    kernel runs over each tensor rank's LOCAL heads inside the Megatron
    stage body — the composed step must still be a pure re-scheduling of
    the single-device flash model (loss + updated blocks match)."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    pipe, tp, v, n_mb = 2, 2, 2, 2
    devs = jax.devices("cpu")[: pipe * tp * 2]
    mesh = make_mesh(MeshConfig(data=2, pipe=pipe, tensor=tp), devices=devs)
    model = tiny_model(pipe * v, attention="flash")
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=2 * n_mb * 2)

    state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                                  n_microbatches=n_mb, interleave=v)

    params = model.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(model, opt, params, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)

    got_stack = megatron.permute_qkv(
        jax.device_get(state.params["blocks"]), model.cfg.d_model,
        model.cfg.n_heads, tp, inverse=True)
    got_blocks = pp.unstack_blocks(got_stack, stack_ndims=3)
    ref_blocks = jax.device_get(ref_params["blocks"])
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)


def test_pipeline_rejects_seq_sharded_attention():
    """ring/striped/ulysses need a 'seq' mesh axis the pipe mesh does not
    bind; the guard must fire for tp=1 too (previously only tp>1 was
    checked and tp=1 failed at trace time with an unbound-axis error)."""
    devs = jax.devices("cpu")[:2]
    mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=devs)
    model = tiny_model(4, attention="ring")
    with pytest.raises(NotImplementedError, match="seq-sharded"):
        pp.make_pipeline_train_step(model, optim.sgd(0.1), mesh)

def test_pipeline_seq_matches_single_device():
    """PP x SP (round 4): ring attention over 'seq' inside pipeline stages
    — activations rotate over 'pipe' while each stage's attention rings
    over the sequence shards.  Ring attention is exact, so the composed
    step must match the single-device dense model on the same weights."""
    pipe, sp, n_mb = 2, 2, 2
    devs = jax.devices("cpu")[: pipe * sp * 2]
    mesh = make_mesh(MeshConfig(data=2, pipe=pipe, seq=sp), devices=devs)
    model = tiny_model(4, attention="ring")
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=2 * n_mb * 2)

    state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                                  n_microbatches=n_mb)

    # oracle: the DENSE model with the same params (ring == dense math;
    # init is attention-independent)
    dense = tiny_model(4, attention="dense")
    params = dense.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(dense, opt, params, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    got_blocks = pp.unstack_blocks(jax.device_get(state.params["blocks"]))
    ref_blocks = jax.device_get(ref_params["blocks"])
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)
    for name in ("embed", "pos", "ln_f", "head"):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            jax.device_get(state.params[name]),
            jax.device_get(ref_params[name]))


def test_pipeline_seq_requires_seq_axis_match():
    """Seq-sharded attention without a 'seq' mesh axis, and a seq axis
    with dense attention, both get specific errors."""
    devs = jax.devices("cpu")[:2]
    mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=devs)
    with pytest.raises(NotImplementedError, match="'seq' mesh axis"):
        pp.make_pipeline_train_step(tiny_model(4, attention="ring"),
                                    optim.sgd(0.1), mesh)
    mesh_sp = make_mesh(MeshConfig(pipe=2, seq=2),
                        devices=jax.devices("cpu")[:4])
    with pytest.raises(ValueError, match="not seq-sharded"):
        pp.make_pipeline_train_step(tiny_model(4, attention="dense"),
                                    optim.sgd(0.1), mesh_sp)


def test_pipeline_seq_tensor_matches_single_device():
    """PP x SP x TP (round 4): ring attention over 'seq' inside
    Megatron-sharded pipeline stages (heads over 'tensor') while
    activations rotate over 'pipe' — three model axes in one program.
    Ring attention is exact, so the composed step must match the
    single-device dense model on the same weights."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    pipe, sp, tp, n_mb = 2, 2, 2, 2
    devs = jax.devices("cpu")[: pipe * sp * tp]
    mesh = make_mesh(MeshConfig(data=1, pipe=pipe, seq=sp, tensor=tp),
                     devices=devs)
    model = tiny_model(4, attention="ring")
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=2 * n_mb)

    state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                                  n_microbatches=n_mb)

    dense = tiny_model(4, attention="dense")
    params = dense.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(dense, opt, params, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    got_stack = megatron.permute_qkv(
        jax.device_get(state.params["blocks"]), model.cfg.d_model,
        model.cfg.n_heads, tp, inverse=True)
    got_blocks = pp.unstack_blocks(got_stack)
    ref_blocks = jax.device_get(ref_params["blocks"])
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)
    for name in ("embed", "pos", "ln_f", "head"):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            jax.device_get(state.params[name]),
            jax.device_get(ref_params[name]))


def test_pipeline_seq_expert_matches_dense():
    """PP x SP x EP — GPipe ring x ring attention x all_to_all experts in
    one shard_map program (8 devices = 2x2x2).  Generous capacity keeps
    routing drop-free, so one step matches the single-device dense-MoE
    model (aux_weight=0 — per-shard aux means differ from the global
    mean by design, as in every MoE layout-parity pin)."""
    pipe, sp, ep_, n_mb = 2, 2, 2, 2
    rows = 4 * ep_
    capacity = rows * T
    devs = jax.devices("cpu")[:8]
    mesh = make_mesh(MeshConfig(data=1, pipe=pipe, seq=sp, expert=ep_),
                     devices=devs)
    model = Transformer(TransformerConfig(
        vocab_size=VOCAB, max_seq_len=T, n_layers=4, d_model=32,
        n_heads=4, d_ff=64, attention="ring", moe_experts=4,
        moe_capacity=capacity, moe_expert_axis="expert"))
    opt = optim.sgd(lr=0.1, momentum=0.9)
    batch = lm_batch(rows=rows)

    state = pp.init_pipeline_state(model, opt, prng.init_key(0), pipe)
    state = pp.shard_pipeline_state(state, mesh, opt)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rows_spec = ("data", "fsdp", "expert")
    placed = {k: jax.device_put(
        jnp.asarray(v), NamedSharding(
            mesh, P(rows_spec, "seq") if k != "mask" else P(rows_spec)))
        for k, v in batch.items()}
    step = pp.make_pipeline_train_step(model, opt, mesh,
                                       n_microbatches=n_mb, donate=False,
                                       aux_weight=0.0)
    state, loss = step(state, placed)

    dense = Transformer(TransformerConfig(
        vocab_size=VOCAB, max_seq_len=T, n_layers=4, d_model=32,
        n_heads=4, d_ff=64, attention="dense", moe_experts=4,
        moe_capacity=capacity))
    params = dense.init(prng.init_key(0))
    ref_loss, ref_params = reference_step(dense, opt, params, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    got_blocks = pp.unstack_blocks(jax.device_get(state.params["blocks"]))
    ref_blocks = jax.device_get(ref_params["blocks"])
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)


def test_pipeline_four_axis_pp_sp_ep_tp_subprocess():
    """The FULL four-model-axis composition — pipe x seq x expert x tensor
    in one shard_map program — needs 16 devices, so it runs in a
    subprocess with its own virtual-device count (same pattern as the
    multi-process tests).  One step must match the single-device
    dense-MoE model (ring attention is exact; ample capacity keeps
    routing drop-free)."""
    import json
    import os
    import subprocess
    import sys

    script = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops import losses, optim
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    megatron, pipeline as pp,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
    make_mesh,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

V, T = 64, 8
rows = 8
capacity = rows * T
mesh = make_mesh(MeshConfig(data=1, pipe=2, seq=2, expert=2, tensor=2),
                 devices=jax.devices("cpu")[:16])
model = Transformer(TransformerConfig(
    vocab_size=V, max_seq_len=T, n_layers=2, d_model=32, n_heads=4,
    d_ff=64, attention="ring", moe_experts=4, moe_capacity=capacity,
    moe_expert_axis="expert"))
opt = optim.sgd(lr=0.1, momentum=0.9)
rng = np.random.default_rng(0)
tok = rng.integers(0, V, (rows, T + 1))
batch = {"x": tok[:, :-1].astype(np.int32),
         "y": tok[:, 1:].astype(np.int32),
         "mask": np.ones((rows,), np.float32)}

state, loss = pp.run_one_step(model, opt, mesh, batch, prng.init_key(0),
                              n_microbatches=2)

dense = Transformer(TransformerConfig(
    vocab_size=V, max_seq_len=T, n_layers=2, d_model=32, n_heads=4,
    d_ff=64, attention="dense", moe_experts=4, moe_capacity=capacity))
params = dense.init(prng.init_key(0))

def scalar(p):
    logits = dense.apply(p, jnp.asarray(batch["x"]))
    s, c = losses.softmax_cross_entropy(
        logits, jnp.asarray(batch["y"]), jnp.asarray(batch["mask"]))
    return s / c

ref_loss_val = scalar(params)
grads = jax.grad(scalar)(params)
ref_params, _ = opt.update(grads, opt.init(params), params)

np.testing.assert_allclose(float(loss), float(ref_loss_val),
                           rtol=1e-5, atol=1e-6)
got_stack = megatron.permute_qkv(
    jax.device_get(state.params["blocks"]), 32, 4, 2, inverse=True)
got_blocks = pp.unstack_blocks(got_stack)
ref_blocks = jax.device_get(ref_params["blocks"])
# four stacked collective reductions (pipe + expert + seq psums, ring
# online-softmax) reassociate more f32 sums than any pairwise layout;
# tolerances match the MoE layout-parity pins (tests/test_moe.py)
for got, ref in zip(got_blocks, ref_blocks):
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4),
        got, ref)
print(json.dumps({"ok": True, "loss": float(loss)}))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=900,
                         env=env, cwd=os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, (out.stderr or "")[-3000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["ok"] and np.isfinite(rec["loss"])
