"""Telemetry subsystem (train.telemetry, DESIGN.md §7): on-device step
metrics, flight recorder, MFU accounting, run-health heartbeat.

The load-bearing properties:

* metrics are PURE OBSERVATION — params are bitwise-identical with
  telemetry on vs off (including under the skip guard, whose norm
  reduction the metrics path shares via ``Optimizer.update_with_norm``);
* the flight recorder dumps a postmortem on every abnormal event
  (rollback with straddling records, SIGTERM, crash), so a relaunch can
  read what the run was doing when it died;
* the analytic FLOPs the MFU divides by match a hand count;
* the heartbeat is fresh while the run lives and the supervisor kills a
  child whose heartbeat goes stale.
"""

import dataclasses
import json
import math
import os
import pathlib
import subprocess
import sys

import jax
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig, build_argparser,
    config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.train import (
    telemetry as telemetry_lib,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
    Trainer,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _cfg(**kw):
    base = dict(nepochs=2, full_batch=False, batch_size=8, lr=1e-3,
                momentum=0.0, data=DataConfig(n_samples=32),
                mesh=MeshConfig(data=8), metrics_every=1)
    base.update(kw)
    return TrainConfig(**base)


def _records(telemetry_dir):
    with open(os.path.join(telemetry_dir, "metrics.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------- metrics + heartbeat


def test_metrics_stream_heartbeat_and_summary(tmp_path, mesh8, capsys):
    """Acceptance core: a run with --telemetry_dir emits per-step metrics
    JSONL containing grad_norm/param_norm/update_ratio/loss/mfu, plus a
    fresh final heartbeat — and tools/metrics_summary.py renders it."""
    d = str(tmp_path / "telem")
    t = Trainer(_cfg(telemetry_dir=d), mesh=mesh8)
    result = t.fit()
    recs = _records(d)
    assert len(recs) == result["steps"] == 8
    step_recs = [r for r in recs if r.get("kind") == "step"]
    for key in telemetry_lib.METRIC_KEYS:       # loss, grad_norm, ...
        assert all(key in r for r in step_recs), key
    assert all(math.isfinite(r["grad_norm"]) and r["grad_norm"] > 0
               for r in step_recs)
    assert all(r["param_norm"] > 0 for r in step_recs)
    assert all(0 <= r["update_ratio"] for r in step_recs)
    # mfu + step_time appear once dispatch-to-dispatch time exists
    timed = [r for r in step_recs if "step_time_ms" in r]
    assert timed and all("mfu" in r and r["mfu"] >= 0 for r in timed)
    assert "mfu" in result and result["mfu"] > 0
    hb = telemetry_lib.read_heartbeat(os.path.join(d, "heartbeat.json"))
    assert hb["step"] == 8 and hb["final"] is True
    assert telemetry_lib.heartbeat_age_s(
        os.path.join(d, "heartbeat.json")) < 60
    # no abnormal event -> no postmortem
    assert not os.path.exists(os.path.join(d, "postmortem.json"))
    # the summary CLI renders percentiles from the same artifacts
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import metrics_summary
    finally:
        sys.path.pop(0)
    assert metrics_summary.main([d]) == 0
    out = capsys.readouterr().out
    assert "grad_norm" in out and "heartbeat: step 8" in out


def test_params_bitwise_identical_telemetry_on_off(tmp_path, mesh8):
    """Acceptance: metrics are pure observation.  With the skip guard ON,
    the metrics path hands its norm to the guard (update_with_norm) — the
    trajectory must still be bitwise-equal to the telemetry-off run."""
    def fit_params(telem, guard):
        cfg = _cfg(lr=1e-2, momentum=0.9, skip_nonfinite=guard,
                   telemetry_dir=str(tmp_path / f"t{telem}{guard}")
                   if telem else None)
        t = Trainer(cfg, mesh=mesh8)
        t.fit()
        return jax.device_get(t.state.params)

    for guard in (False, True):
        a, b = fit_params(False, guard), fit_params(True, guard)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_metrics_on_gspmd_layout(tmp_path, mesh8):
    """The GSPMD (fsdp) path carries the same metrics vector, computed in
    global view."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )

    d = str(tmp_path / "telem")
    mesh = make_mesh(MeshConfig(data=4, fsdp=2), devices=mesh8.devices.ravel())
    t = Trainer(_cfg(mesh=MeshConfig(data=4, fsdp=2), telemetry_dir=d),
                mesh=mesh)
    assert t.gspmd and t.telemetry_metrics
    t.fit()
    recs = [r for r in _records(d) if r.get("kind") == "step"]
    assert recs and all(k in recs[-1] for k in telemetry_lib.METRIC_KEYS)


def test_metrics_with_multi_step_dispatch(tmp_path, mesh8):
    """steps_per_dispatch=3: one record per dispatch boundary crossing,
    carrying the dispatch's LAST step's metrics."""
    d = str(tmp_path / "telem")
    t = Trainer(_cfg(steps_per_dispatch=3, telemetry_dir=d), mesh=mesh8)
    result = t.fit()
    recs = [r for r in _records(d) if r.get("kind") == "step"]
    # 4 steps/epoch at k=3 -> dispatches end at steps 3, 4, 7, 8
    assert [r["step"] for r in recs] == [3, 4, 7, 8]
    assert result["steps"] == 8
    assert all("grad_norm" in r for r in recs)
    # skip visibility: a nan fault poisons the WHOLE first k=3 dispatch
    # (fault granularity is the dispatch); all 3 skip fires must reach
    # the stream even though only the dispatch's LAST step's other
    # metrics are reported (the skip count sums over the scan axis)
    d2 = str(tmp_path / "telem2")
    t2 = Trainer(_cfg(steps_per_dispatch=3, skip_nonfinite=True,
                      faults="nan@0?max=1", telemetry_dir=d2), mesh=mesh8)
    t2.fit()
    recs2 = [r for r in _records(d2) if r.get("kind") == "step"]
    assert recs2[0]["step"] == 3 and recs2[0]["skipped"] == 3.0
    assert t2.telemetry.skipped_total == 3


def test_sparse_metrics_cannot_lose_skip_fires(tmp_path, mesh8):
    """metrics_every=4 with a nan at step 2 (never a sampled boundary):
    the cumulative counter carried by the step-4 record still surfaces
    the fire as a differenced skip event."""
    d = str(tmp_path / "telem")
    t = Trainer(_cfg(metrics_every=4, skip_nonfinite=True,
                     faults="nan@1?max=1", telemetry_dir=d), mesh=mesh8)
    t.fit()
    steps = [r for r in _records(d) if r.get("kind") == "step"]
    assert [r["step"] for r in steps] == [4, 8]
    assert steps[0]["skipped"] == 1.0          # cumulative at step 4
    assert t.telemetry.skipped_total == 1
    # the differenced fire reached the flight recorder as a skip event
    assert any(r.get("event") == "skip" and r.get("fires") == 1
               for r in t.telemetry.recorder.records)


def test_zero1_rides_the_full_metrics_stream(tmp_path, mesh8):
    """zero1 used to fall back to the loss-only stream (its update
    consumes a scattered gradient shard); since the update-sharding
    layer it computes the GLOBAL grad norm from the shards via one
    extra psum, so the full metrics vector rides along — and params
    stay bitwise-identical with metrics on vs off
    (tests/test_update_sharding.py pins that half)."""
    d = str(tmp_path / "telem")
    t = Trainer(_cfg(update_sharding="zero1", optimizer="adam",
                     telemetry_dir=d), mesh=mesh8)
    assert t.telemetry_metrics and t.telemetry.enabled
    t.fit()
    recs = [r for r in _records(d) if r.get("kind") == "step"]
    assert recs and all("loss" in r and "grad_norm" in r
                        and "update_ratio" in r for r in recs)


def test_heartbeat_only_mode_final_step(tmp_path, mesh8):
    """metrics_every=0: no metrics stream, but the heartbeat still tracks
    the run and the FINAL beat carries the real step (not 0 — no record
    ever carried one to fall back on)."""
    d = str(tmp_path / "telem")
    t = Trainer(_cfg(metrics_every=0, telemetry_dir=d), mesh=mesh8)
    result = t.fit()
    assert not t.telemetry_metrics  # no on-device metrics wired
    assert not os.path.exists(os.path.join(d, "metrics.jsonl")) or \
        _records(d) == []
    hb = telemetry_lib.read_heartbeat(os.path.join(d, "heartbeat.json"))
    assert hb["step"] == result["steps"] == 8 and hb["final"] is True


def test_cli_flags_plumbed():
    args = build_argparser().parse_args(
        ["--telemetry_dir", "/tmp/x", "--metrics_every", "5",
         "--flight_recorder", "32"])
    cfg = config_from_args(args)
    assert (cfg.telemetry_dir, cfg.metrics_every, cfg.flight_recorder) == \
        ("/tmp/x", 5, 32)
    dflt = TrainConfig()
    assert dflt.telemetry_dir is None and dflt.metrics_every == 1


# ------------------------------------------------------------ flight recorder


def test_postmortem_on_rollback_straddles(tmp_path, mesh8):
    """Acceptance: under an injected step-N nan fault the postmortem's
    last records STRADDLE the rollback — pre-rollback step records and
    skip events, the rollback event, and >= 1 post-rollback record."""
    d = str(tmp_path / "telem")
    cfg = _cfg(nepochs=6, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=2, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=4, faults="nan@10-12?max=3",
               telemetry_dir=d)
    result = Trainer(cfg, mesh=mesh8).fit()
    assert result["rollbacks"] == 1
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert pm["reason"] == "rollback"
    kinds = [(r.get("kind"), r.get("event")) for r in pm["records"]]
    ri = [i for i, r in enumerate(pm["records"])
          if r.get("event") == "rollback"]
    assert ri, kinds
    assert any(r.get("kind") == "step" for r in pm["records"][:ri[0]])
    assert any(r.get("kind") == "step" for r in pm["records"][ri[0] + 1:])
    assert any(r.get("event") == "skip" for r in pm["records"])


def test_postmortem_on_sigterm(tmp_path, mesh8):
    d = str(tmp_path / "telem")
    cfg = _cfg(nepochs=10, checkpoint_dir=str(tmp_path / "ck"),
               faults="sigterm@7", telemetry_dir=d)
    result = Trainer(cfg, mesh=mesh8).fit()
    assert result.get("preempted") is True
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert pm["reason"].startswith("sigterm")
    assert any(r.get("event") == "sigterm" for r in pm["records"])


def test_postmortem_on_crash_exception(tmp_path, mesh8):
    """An unhandled exception escaping the step loop dumps a crash
    postmortem from fit's finally (the in-process 'segfault stand-in';
    the os._exit fault is covered by the supervised CLI test below)."""
    d = str(tmp_path / "telem")
    t = Trainer(_cfg(nepochs=4, telemetry_dir=d), mesh=mesh8)
    real_step, calls = t.train_step, []

    def exploding(state, batch):
        calls.append(1)
        if len(calls) == 6:
            raise RuntimeError("synthetic device loss")
        return real_step(state, batch)

    t.train_step = exploding
    with pytest.raises(RuntimeError, match="synthetic"):
        t.fit()
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert pm["reason"].startswith("crash: RuntimeError")
    assert any(r.get("kind") == "step" for r in pm["records"])


def test_postmortem_on_anomaly_abort(tmp_path, mesh8):
    from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
        AnomalyAbort,
    )

    d = str(tmp_path / "telem")
    cfg = _cfg(nepochs=8, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=0, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=2, faults="nan@7-999", telemetry_dir=d)
    with pytest.raises(AnomalyAbort):
        Trainer(cfg, mesh=mesh8).fit()
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert pm["reason"] == "anomaly_abort"


def test_flight_recorder_ring_is_bounded(tmp_path, mesh8):
    d = str(tmp_path / "telem")
    cfg = _cfg(nepochs=4, flight_recorder=5, faults="sigterm@14",
               telemetry_dir=d)
    Trainer(cfg, mesh=mesh8).fit()
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert pm["n_records"] <= 5  # the ring dropped older records


# ------------------------------------------------------------ MFU accounting


def test_train_step_flops_hand_counted_transformer():
    """The analytic FLOPs the MFU divides by, against a literal hand
    count: B=3, T=4, d=8, H=2, ff=16, V=13, 1 layer, gelu."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )

    B, T, d, ff, V = 3, 4, 8, 16, 13
    m = Transformer(TransformerConfig(vocab_size=V, max_seq_len=T,
                                      n_layers=1, d_model=d, n_heads=2,
                                      d_ff=ff))
    qkv = 2 * B * T * d * (3 * d)          # fused qkv projection
    attn_out = 2 * B * T * d * d           # output projection
    scores_values = 2 * (2 * B * T * T * d)  # QK^T and attn @ V
    ffn = 2 * (2 * B * T * d * ff)         # ff_in + ff_out
    head = 2 * B * T * d * V               # LM head (the CE logits)
    fwd = qkv + attn_out + scores_values + ffn + head
    assert m.fwd_flops((B, T)) == fwd
    assert telemetry_lib.train_step_flops(m, (B, T)) == 3.0 * fwd


def test_train_step_flops_gqa_swiglu_moe_variants():
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )

    B, T, d, ff, V = 2, 4, 8, 16, 13
    base = dict(vocab_size=V, max_seq_len=T, n_layers=1, d_model=d,
                n_heads=2, d_ff=ff)
    # GQA: 1 of 2 KV heads -> qkv width d + 2 * 1 * (d/2) = 2d (vs 3d)
    gqa = Transformer(TransformerConfig(n_kv_heads=1, **base))
    full = Transformer(TransformerConfig(**base))
    assert full.fwd_flops((B, T)) - gqa.fwd_flops((B, T)) == \
        2 * B * T * d * d
    # SwiGLU adds the third (d, ff) gate matmul
    swi = Transformer(TransformerConfig(activation="swiglu", **base))
    assert swi.fwd_flops((B, T)) - full.fwd_flops((B, T)) == \
        2 * B * T * d * ff
    # MoE top-2 over 4 experts: 2x the FFN matmuls + the router
    moe = Transformer(TransformerConfig(moe_experts=4, moe_top_k=2, **base))
    ffn = 2 * (2 * B * T * d * ff)
    router = 2 * B * T * d * 4
    assert moe.fwd_flops((B, T)) - full.fwd_flops((B, T)) == ffn + router


def test_train_step_flops_mlp_and_peak_table():
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import MLP

    m = MLP(in_features=2, hidden=(3,), out_features=1)
    assert m.fwd_flops((5, 2)) == 2 * 5 * (2 * 3 + 3 * 1)
    assert telemetry_lib.train_step_flops(m, (5, 2)) == 3.0 * 2 * 5 * 9
    # the peak table is the single source bench.py re-exports
    import bench

    assert bench.peak_flops("TPU v5e") == 197e12
    assert bench.peak_flops("TPU v4") == 275e12
    assert bench.peak_flops("cpu") is None
    assert telemetry_lib.telemetry_peak_flops("cpu", "cpu") == \
        telemetry_lib.NOMINAL_CPU_PEAK_FLOPS
    assert telemetry_lib.telemetry_peak_flops("TPU v4", "tpu") == 275e12


# ----------------------------------------------------- heartbeat + supervisor


def test_supervise_kills_stale_heartbeat_child(tmp_path):
    """External hang detection: a child that beats once (arming the
    monitor) and then stalls is killed and reported as EXIT_HANG (retry
    class).  A PRE-EXISTING heartbeat from a previous run must NOT arm
    the monitor — the compile-exempt arming discipline."""
    from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
        EXIT_HANG, supervise,
    )

    hb = tmp_path / "heartbeat.json"
    hb.write_text("{}")  # stale leftover: does not arm on its own
    child = ("import pathlib, time\n"
             "time.sleep(0.3)\n"  # 'compile': no beat yet, no kill
             f"pathlib.Path({str(hb)!r}).write_text('{{}}')\n"
             "time.sleep(60)\n")
    logs = []
    rc = supervise([sys.executable, "-c", child],
                   max_restarts=0, backoff=0.0, log=logs.append,
                   heartbeat_path=str(hb), heartbeat_timeout=1.0,
                   _sleep=lambda s: None)
    assert rc == EXIT_HANG
    assert any("heartbeat stale" in m for m in logs)


def test_supervise_fresh_heartbeat_child_completes(tmp_path):
    """A healthy child refreshing its heartbeat is NOT killed."""
    from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
        supervise,
    )

    hb = tmp_path / "heartbeat.json"
    child = ("import time, pathlib\n"
             f"p = pathlib.Path({str(hb)!r})\n"
             "for _ in range(8):\n"
             "    p.write_text('{}')\n"
             "    time.sleep(0.25)\n")
    rc = supervise([sys.executable, "-c", child], max_restarts=0,
                   backoff=0.0, heartbeat_path=str(hb),
                   heartbeat_timeout=1.5, _sleep=lambda s: None)
    assert rc == 0


def test_supervise_compile_phase_exempt_from_heartbeat_kill(tmp_path):
    """A child whose FIRST heartbeat write takes longer than the timeout
    (first-step compile) must not be killed: the monitor arms only at
    the first write, like the in-process watchdog's first pat()."""
    from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
        supervise,
    )

    hb = tmp_path / "heartbeat.json"
    child = ("import time, pathlib\n"
             "time.sleep(2.5)\n"  # 'compile' > heartbeat_timeout
             f"pathlib.Path({str(hb)!r}).write_text('{{}}')\n")
    rc = supervise([sys.executable, "-c", child], max_restarts=0,
                   backoff=0.0, heartbeat_path=str(hb),
                   heartbeat_timeout=1.0, _sleep=lambda s: None)
    assert rc == 0


def _clean_env():
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        faults as faults_lib,
        platform as plat,
    )

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop(faults_lib.ENV_VAR, None)
    plat.force_host_device_count(None, env=env)
    return env


def test_crash_fault_dump_and_supervisor_pointer(tmp_path):
    """Acceptance e2e: an injected os._exit crash leaves a postmortem
    (utils.faults' emergency hook), the supervisor's relaunch log points
    at it, the relaunch resumes and completes, and the heartbeat is fresh
    under the supervisor with the final step."""
    d = tmp_path / "telem"
    out = subprocess.run(
        [sys.executable, "-m", "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "2", "--dataset", "regression",
         "--n_samples", "32", "--batch_size", "8", "--no-full-batch",
         "--nepochs", "4", "--checkpoint_dir", str(tmp_path / "ck"),
         "--checkpoint_every", "3", "--telemetry_dir", str(d),
         "--faults", f"crash@9?once={tmp_path / 'crashed'}",
         "--supervise", "2", "--supervise_backoff", "0.1"],
        capture_output=True, text=True, timeout=240, env=_clean_env(),
        cwd=str(REPO))
    text = out.stdout + out.stderr
    assert out.returncode == 0, text[-3000:]
    assert "injected crash at step 9" in text
    assert "child left a postmortem" in text
    pm = json.load(open(d / "postmortem.json"))
    assert pm["reason"].startswith("crash@9")
    hb = telemetry_lib.read_heartbeat(str(d / "heartbeat.json"))
    assert hb is not None and hb["step"] == 16 and hb.get("final") is True


# ------------------------------------------------------------------ overhead


@pytest.mark.slow
def test_telemetry_happy_path_overhead(tmp_path, mesh8):
    """Telemetry adds the metrics-vector norms inside the step plus the
    lag-2 fetch per dispatch.  At the CPU bench's transformer scale the
    measured overhead is ~0.6% (see DESIGN.md §7); this micro-model run
    asserts loosely (the fixed norm passes are proportionally larger
    here) and prints the measured number as the record."""
    import time

    def steptime(telem):
        cfg = _cfg(nepochs=1, batch_size=32,
                   telemetry_dir=str(tmp_path / "t") if telem else None,
                   data=DataConfig(dataset="lm", n_samples=64, seq_len=64,
                                   vocab_size=64),
                   model=ModelConfig(arch="transformer", n_layers=2,
                                     d_model=64, n_heads=4, d_ff=128,
                                     vocab_size=64, max_seq_len=64,
                                     attention="dense"),
                   loss="cross_entropy")
        t = Trainer(cfg, mesh=mesh8)
        t.init_state()
        batch = next(iter(t.loader.epoch(0)))
        state = t.state
        state, out = t.train_step(state, batch)  # compile
        jax.block_until_ready(out)
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            state, out = t.train_step(state, batch)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n

    # INTERLEAVED min-of-k pairs: the test host is a single shared core,
    # and grouping all base runs before all telemetry runs lets one load
    # spike masquerade as overhead (observed a 1.3x phantom that way)
    base = telem = None
    for _ in range(3):
        b, t_ = steptime(False), steptime(True)
        base = b if base is None else min(base, b)
        telem = t_ if telem is None else min(telem, t_)
    ratio = telem / base
    print(f"\ntelemetry overhead: {base * 1e3:.2f}ms -> "
          f"{telem * 1e3:.2f}ms ({(ratio - 1) * 100:+.1f}%)")
    assert ratio < 1.4, f"telemetry overhead {ratio:.2f}x"
