"""Weights-only int8 PTQ (ops.quant): quantization error bounds, the
scale-commutes-through-the-matmul identity Linear.apply relies on, full
transformer forward parity, the KV-cache decode path end to end, and the
CLI flag.  The reference has no inference path at all (its eval blocks
are dead code, dataParallelTraining_NN_MPI.py:213-236); this is a
TPU-serving extension, so the tests pin the numerics contract the bench
decode rows will lean on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.core import Linear
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
    dequantize_array, quantize_array, quantize_params, quantized_bytes,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

pytestmark = pytest.mark.quant


def test_quantize_array_roundtrip_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    q, scale = quantize_array(w)
    assert q.dtype == jnp.int8 and scale.shape == (48,)
    assert int(jnp.min(q)) >= -127  # symmetric: -128 never used
    err = np.abs(np.asarray(dequantize_array(q, scale)) - np.asarray(w))
    # per-element error <= scale/2 by rounding
    assert np.all(err <= np.asarray(scale)[None, :] / 2 + 1e-7)


def test_quantize_array_zero_column():
    w = jnp.zeros((8, 4), jnp.float32)
    q, scale = quantize_array(w)
    np.testing.assert_array_equal(np.asarray(q), 0)
    np.testing.assert_array_equal(np.asarray(scale), 1.0)  # no div-by-0


def test_quantize_array_mixed_zero_columns():
    """A kernel with SOME all-zero output columns: the zero columns get
    scale 1 (no divide-by-zero) while the live columns round-trip within
    their own scale/2 bound — one poisoned column cannot distort its
    neighbours' scales."""
    rng = np.random.default_rng(7)
    w = np.asarray(rng.standard_normal((16, 6)), np.float32)
    w[:, 1] = 0.0
    w[:, 4] = 0.0
    q, scale = quantize_array(jnp.asarray(w))
    assert np.asarray(scale)[1] == 1.0 and np.asarray(scale)[4] == 1.0
    np.testing.assert_array_equal(np.asarray(q)[:, 1], 0)
    err = np.abs(np.asarray(dequantize_array(q, scale)) - w)
    assert np.all(err <= np.asarray(scale)[None, :] / 2 + 1e-7)


def test_quantize_array_nondefault_axis():
    """axis= names the CONTRACTION dim the scale must not span: axis=-1
    on a (out, in)-layout kernel keeps per-row scales, and the
    reconstruction bound holds with the scale expanded on that axis."""
    rng = np.random.default_rng(8)
    w = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
    q, scale = quantize_array(w, axis=-1)
    assert scale.shape == (6,)
    recon = dequantize_array(q, scale, axis=-1)
    err = np.abs(np.asarray(recon) - np.asarray(w))
    assert np.all(err <= np.asarray(scale)[:, None] / 2 + 1e-7)
    # and the two layouts agree: quantizing w.T with the default axis is
    # the same codes transposed
    qt, st = quantize_array(w.T)
    np.testing.assert_array_equal(np.asarray(qt).T, np.asarray(q))
    np.testing.assert_allclose(np.asarray(st), np.asarray(scale))


def test_quantize_params_expert_dict_zero_and_gate():
    """Expert-dict leaves as a first-class walk target (previously only
    exercised through the full-model tests): an expert whose w_out is
    all zero still quantizes safely (scale 1), w_gate (SwiGLU experts)
    rides along, and the router gate stays untouched."""
    from neural_networks_parallel_training_with_mpi_tpu.models.moe import MoEFFN

    moe = MoEFFN(16, 32, 2, activation="swiglu")
    params = moe.init(prng.init_key(0))
    params["experts"]["w_out"] = jnp.zeros_like(params["experts"]["w_out"])
    q = quantize_params({"moe": params})["moe"]
    assert q["experts"]["w_in"].dtype == jnp.int8
    assert q["experts"]["w_gate"].dtype == jnp.int8
    assert q["experts"]["w_out"].dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(q["experts"]["w_out"]), 0)
    np.testing.assert_array_equal(
        np.asarray(q["experts"]["w_out_scale"]), 1.0)
    assert q["gate"]["w"].dtype == jnp.float32  # router stays exact


def test_quantized_bytes_accounting_pin():
    """Closed-form accounting: quantized_bytes must equal the exact sum
    of as-stored leaf bytes — int8 kernels 1 byte/elt, their f32 scales
    4, untouched f32 leaves 4 (the quantity decode bandwidth streams)."""
    lin = Linear(32, 16)
    params = lin.init(prng.init_key(0))
    full = quantized_bytes(params)
    assert full == (32 * 16 + 16) * 4
    q = quantize_params(params)
    assert quantized_bytes(q) == 32 * 16 * 1 + 16 * 4 + 16 * 4


def test_quantize_array_stacked_blocks():
    """ndim-3 scan-stacked kernels (n_layers, in, out) keep per-layer
    scales on axis -2's removal -> (n_layers, out)."""
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((3, 16, 8)), jnp.float32)
    q, scale = quantize_array(w)
    assert scale.shape == (3, 8)
    err = np.abs(np.asarray(dequantize_array(q, scale)) - np.asarray(w))
    assert np.all(err <= np.asarray(scale)[:, None, :] / 2 + 1e-7)


def test_linear_apply_consumes_quantized():
    """y_q == x @ dequant(W) + b exactly (the out-channel scale commutes
    through the contraction — ops/quant.py module docstring)."""
    rng = np.random.default_rng(2)
    lin = Linear(32, 16)
    params = lin.init(prng.init_key(0))
    x = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
    qparams = quantize_params(params)
    assert qparams["w"].dtype == jnp.int8
    got = lin.apply(qparams, x)
    want = (x @ dequantize_array(qparams["w"], qparams["w_scale"])
            + params["b"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # and close to the full-precision layer (PTQ error only)
    full = lin.apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                               rtol=0.05, atol=0.05)


def _tiny_lm(**kw):
    return Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=32, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, **kw))


def test_quantize_params_walk():
    """Kernels quantize; LayerNorms, biases, embedding/pos tables do not;
    the transform is idempotent; `skip` keeps named subtrees exact."""
    model = _tiny_lm()
    params = model.init(prng.init_key(0))
    q = quantize_params(params, skip=("head",))
    blk = q["blocks"][0]
    assert blk["qkv"]["w"].dtype == jnp.int8
    assert blk["ff_in"]["w"].dtype == jnp.int8
    assert blk["qkv"]["b"].dtype == jnp.float32
    assert blk["ln1"]["scale"].dtype == jnp.float32
    assert q["embed"]["table"].dtype == jnp.float32
    assert q["head"]["w"].dtype == jnp.float32  # skipped
    assert quantize_params(q, skip=("head",))["blocks"][0]["qkv"][
        "w"].dtype == jnp.int8  # idempotent, no double-scale
    assert quantized_bytes(q) < quantized_bytes(params)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_transformer_forward_parity(scan_layers):
    """Full-model logits with int8 weights stay close to full precision
    (training-free PTQ bound on a random-init model)."""
    model = _tiny_lm(scan_layers=scan_layers)
    params = model.init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)),
                      jnp.int32)
    full = model.apply(params, ids)
    q = model.apply(quantize_params(params), ids)
    assert np.asarray(jnp.abs(q - full)).max() < 0.15
    # rank agreement where it matters: greedy tokens mostly identical
    agree = (np.asarray(jnp.argmax(q, -1))
             == np.asarray(jnp.argmax(full, -1))).mean()
    assert agree > 0.8, agree


def test_kv_cache_decode_with_quantized_params():
    """models.generate's jitted KV-cache loop consumes quantized params
    transparently (greedy decode, logits-level parity is pinned above —
    here the whole program must compile and emit valid ids)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate,
    )

    model = _tiny_lm()
    params = model.init(prng.init_key(0))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    full = generate(model, params, prompt, 8)
    q = generate(model, quantize_params(params), prompt, 8)
    assert q.shape == full.shape
    assert int(q.min()) >= 0 and int(q.max()) < 64
    # greedy decode from the same params: most steps pick the same token
    agree = (np.asarray(q[0, 3:]) == np.asarray(full[0, 3:])).mean()
    assert agree >= 0.5, (np.asarray(q), np.asarray(full))


@pytest.mark.slow
def test_cli_generate_quantized(tmp_path, capsys):
    """--quantize int8 end to end through the CLI (fresh-init decode)."""
    from neural_networks_parallel_training_with_mpi_tpu.cli import main

    rc = main(["--dataset", "lm", "--generate", "1,2,3",
               "--max_new_tokens", "4", "--seq_len", "32",
               "--quantize", "int8", "--quantize_skip", "head"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    toks = [int(t) for t in out[-1].split(",")]
    assert len(toks) == 3 + 4


def test_moe_quantization():
    """The MoE router gate's matmul consumes w RAW (models/moe.py::_route
    — no Linear.apply, a w_scale would be silently dropped), so the walk
    must leave it full-precision.  The expert FFN kernels — the bulk of
    an MoE model's parameter bytes — DO quantize, with per-(expert,
    column) scales folded back in by _experts_ffn; routing decisions stay
    exact, so quantized-model logits must stay within the dense-model
    parity bound and the transform stays idempotent."""
    model = _tiny_lm(moe_experts=4, moe_top_k=1)
    params = model.init(prng.init_key(0))
    q = quantize_params(params)
    blk = q["blocks"][0]
    assert blk["moe"]["gate"]["w"].dtype == jnp.float32  # routing exact
    assert blk["moe"]["experts"]["w_in"].dtype == jnp.int8
    assert blk["moe"]["experts"]["w_out"].dtype == jnp.int8
    assert blk["moe"]["experts"]["w_in_scale"].shape == (4, 64)  # (E, f)
    assert blk["moe"]["experts"]["w_out_scale"].shape == (4, 32)  # (E, d)
    assert blk["moe"]["experts"]["b_in"].dtype == jnp.float32
    assert blk["qkv"]["w"].dtype == jnp.int8  # attention still quantizes
    assert quantize_params(q)["blocks"][0]["moe"]["experts"][
        "w_in"].dtype == jnp.int8  # idempotent
    assert quantized_bytes(q) < quantized_bytes(params)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)),
                      jnp.int32)
    full = model.apply(params, ids)
    quant = model.apply(q, ids)
    assert np.asarray(jnp.abs(quant - full)).max() < 0.15


@pytest.mark.slow  # lane budget (round 5): heaviest in module; core coverage kept by the sibling tests
def test_int8_kv_cache_decode():
    """generate(kv_quant=True): int8 cache + per-(b, pos, head) scales.
    Both scales commute exactly through the attention contractions (K
    through the logit column, V through the softmax weights), so the
    only error is the int8 rounding of k/v rows — greedy tokens must
    track the f32-cache decode closely on MHA and GQA models, and the
    cache pytree must actually be int8."""
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate, init_kv_cache,
    )

    for kw in ({}, {"n_kv_heads": 2}):
        model = _tiny_lm(**kw)
        params = model.init(prng.init_key(0))
        cache = init_kv_cache(model, batch=1, max_len=8, quant=True)
        assert cache[0]["k"].dtype == jnp.int8
        assert cache[0]["k_scale"].shape == (1, 8, model.cfg.kv_heads)

        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        full = generate(model, params, prompt, 12)
        kv8 = generate(model, params, prompt, 12, kv_quant=True)
        assert kv8.shape == full.shape
        agree = (np.asarray(kv8[0, 3:]) == np.asarray(full[0, 3:])).mean()
        assert agree >= 0.75, (kw, np.asarray(kv8), np.asarray(full))


def test_int8_kv_cache_prefill_logits_close():
    """Prefill-path logits with the quantized cache stay within the PTQ
    bound of the exact ones (single forward chunk, positionwise)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        _forward_chunk, init_kv_cache,
    )

    model = _tiny_lm()
    params = model.init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                      jnp.int32)
    lf, _ = _forward_chunk(model, params, init_kv_cache(model, 2, 8),
                           ids, 0)
    lq, caches = _forward_chunk(model, params,
                                init_kv_cache(model, 2, 8, quant=True),
                                ids, 0)
    assert caches[0]["k"].dtype == jnp.int8
    assert np.asarray(jnp.abs(lq - lf)).max() < 0.2


def test_int8_kv_cache_sharded_decode():
    """kv_quant plumbs through generate_sharded's cached jitted program
    (the batch-parallel serving path where cache bandwidth matters most):
    rows decode to the same tokens as the single-stream kv_quant path."""
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate, generate_sharded,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        mesh as mesh_lib,
    )

    model = _tiny_lm()
    params = model.init(prng.init_key(0))
    mesh = mesh_lib.make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    sharded = generate_sharded(model, params, prompt, mesh, 6,
                               kv_quant=True)
    single = generate(model, params, prompt, 6, kv_quant=True)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))
