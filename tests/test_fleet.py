"""Serving fleet (serve/fleet.py + train.resilience.GroupSupervisor).

Pins, by acceptance criterion:

* **group supervision**: per-child exit contracts (no-retry stops, a
  crash relaunches under that child's backoff/budget, the budget ends
  in ``gave_up``), a stale per-child heartbeat kills as a hang, and a
  relaunch never disturbs siblings (their pids are untouched).
* **router admission uses live replica rollups**: saturating one
  replica (through its own scheduler, invisible to the router's
  dispatch ledger) shifts placement to the idle one — the signal is
  ``Scheduler.load_report()``, the serialized utils/sketches rollup
  record, not private state.
* **overload rejects at the ROUTER**: one bounded fleet queue; replica
  local queues stay shallow (``replica_queue_cap``).  SLO-infeasible
  requests can be rejected up front from the TTFT rollup.
* **replica death drains cleanly**: in-flight requests requeue at the
  router and complete on siblings with tokens byte-identical to an
  undisturbed reference (greedy decode is deterministic); no request
  starves.  The subprocess version (SIGKILL mid-load under the group
  supervisor, relaunch included) is the chaos e2e.
* **tensor-parallel replica**: one replica spanning a 2-device mesh
  through ``generate_tp`` emits tokens identical to the single-device
  paged replica (core-lane pin).

Cheap in-process pins run in the budgeted core lane; the multi-process
e2e is slow/chaos.  ``-m fleet`` runs the lane alone.
"""

import json
import math
import os
import pathlib
import signal
import sys
import time

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    FleetRouter, InprocReplica, LoadSignal, Scheduler, ServeConfig,
    TPGenerateReplica, launch_fleet, make_requests,
    run_fleet_closed_loop,
)
from neural_networks_parallel_training_with_mpi_tpu.serve.fleet import (
    FleetRequest, ReplicaHandle,
)
from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
    EXIT_HANG, ChildSpec, GroupSupervisor,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng
from neural_networks_parallel_training_with_mpi_tpu.utils.sketches import (
    QuantileSketch,
)

pytestmark = pytest.mark.fleet

REPO = pathlib.Path(__file__).resolve().parent.parent
V = 64


@pytest.fixture(scope="module")
def lm():
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    return model, model.init(prng.init_key(0))


def _sched(model, params, *, slots=4, queue_depth=16, replica=None,
           num_blocks=None, **kw):
    return Scheduler(model, params, ServeConfig(
        slots=slots, num_blocks=num_blocks or (1 + slots * 4),
        block_size=16, prefill_chunk=16, queue_depth=queue_depth,
        replica=replica, **kw))


def _reference_tokens(model, params, plan):
    """Every request of a client-major plan through ONE scheduler —
    the undisturbed greedy reference."""
    out = {}
    sched = _sched(model, params, slots=4, queue_depth=256,
                   num_blocks=64)
    try:
        rids = {}
        for ci, reqs in enumerate(plan):
            for i, r in enumerate(reqs):
                rid = sched.submit(r["prompt"], r["max_new"])
                assert rid is not None
                rids[(ci, i)] = rid
        sched.run_until_drained()
        for key, rid in rids.items():
            out[key] = sched.result(rid)
    finally:
        sched.close()
    return out


# ---------------------------------------------------------------------------
# group supervisor (stdlib children: fast enough for the core lane)
# ---------------------------------------------------------------------------

def _pump_group(g, until, timeout_s=15.0):
    evs = []
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        evs += g.poll()
        if until(evs):
            return evs
        time.sleep(0.02)
    raise AssertionError(f"condition never met; events={evs}")


def test_group_supervisor_per_child_exit_contracts():
    crash = ChildSpec(name="crash",
                      cmd=[sys.executable, "-c", "raise SystemExit(7)"],
                      max_restarts=2, backoff=0.05, backoff_cap=0.1)
    clean = ChildSpec(name="clean",
                      cmd=[sys.executable, "-c", "raise SystemExit(0)"],
                      max_restarts=2, backoff=0.05)
    noretry = ChildSpec(name="anomaly",
                        cmd=[sys.executable, "-c",
                             "raise SystemExit(44)"],
                        max_restarts=2, backoff=0.05)
    g = GroupSupervisor([crash, clean, noretry], log=lambda m: None)
    g.start()
    evs = _pump_group(g, lambda evs: not g.running())
    kinds = {(e["child"], e["event"]) for e in evs}
    assert ("clean", "stopped") in kinds       # exit 0: no-retry
    assert ("anomaly", "stopped") in kinds     # exit 44: no-retry
    assert ("crash", "gave_up") in kinds       # budget exhausted
    n_relaunch = sum(1 for e in evs
                     if (e["child"], e["event"]) == ("crash",
                                                     "relaunch"))
    assert n_relaunch == 2
    assert g.done("crash") == 7
    assert g.done("clean") == 0
    assert g.done("anomaly") == 44


def test_group_supervisor_relaunch_leaves_siblings_undisturbed():
    crash = ChildSpec(name="crash",
                      cmd=[sys.executable, "-c", "raise SystemExit(1)"],
                      max_restarts=1, backoff=0.05, backoff_cap=0.1)
    steady = ChildSpec(name="steady",
                       cmd=[sys.executable, "-c",
                            "import time; time.sleep(60)"],
                       max_restarts=1)
    g = GroupSupervisor([crash, steady], log=lambda m: None)
    g.start()
    steady_pid = g.proc("steady").pid
    try:
        evs = _pump_group(
            g, lambda evs: any(e["child"] == "crash"
                               and e["event"] == "relaunch"
                               for e in evs))
        # the sibling's process is the SAME pid — probe-and-relaunch
        # touched only the dead child
        assert g.proc("steady").pid == steady_pid
        assert g.alive("steady")
        assert not any(e["child"] == "steady" for e in evs
                       if e["event"] in ("exit", "relaunch"))
    finally:
        g.terminate_all()


def test_group_supervisor_heartbeat_hang_kill(tmp_path):
    hb = tmp_path / "heartbeat-serve-p0.json"
    # the child beats once then wedges: the per-child monitor must arm
    # on that first write and kill at staleness, reporting EXIT_HANG
    src = (f"import pathlib, time; "
           f"pathlib.Path({str(hb)!r}).write_text('{{}}'); "
           "time.sleep(120)")
    spec = ChildSpec(name="wedged", cmd=[sys.executable, "-c", src],
                     heartbeat_path=str(hb), heartbeat_timeout=0.5,
                     max_restarts=0, backoff=0.05)
    g = GroupSupervisor([spec], log=lambda m: None)
    g.start()
    try:
        evs = _pump_group(
            g, lambda evs: any(e["event"] == "hang_kill" for e in evs),
            timeout_s=30.0)
        kills = [e for e in evs if e["event"] == "hang_kill"]
        assert kills, evs
        # max_restarts=0: the hang spends the budget -> gave_up with
        # the EXIT_HANG classification
        _pump_group(g, lambda evs: not g.running(), timeout_s=10.0)
        assert g.done("wedged") == EXIT_HANG
    finally:
        g.terminate_all()


# ---------------------------------------------------------------------------
# router policy (in-process replicas; the budgeted core-lane shape)
# ---------------------------------------------------------------------------

def test_load_report_is_the_rollup_record(lm):
    """The router's placement signal IS the telemetry rollup document:
    kind/sketches/now parse into a LoadSignal without any scheduler
    internals."""
    model, params = lm
    sched = _sched(model, params)
    try:
        rid = sched.submit([1, 2, 3], 4)
        assert rid is not None
        sched.tick()
        rec = sched.load_report()
        assert rec["kind"] == "rollup" and rec["role"] == "serve"
        assert "queue_depth" in rec["now"]
        sig = LoadSignal.from_report(rec)
        assert sig.in_flight == 1
        assert sig.slots == 4 and sig.free_slots == 3
        assert 0.0 <= sig.block_utilization <= 1.0
        sched.run_until_drained()
        sched.result(rid)
        done = sched.load_report()
        assert json.dumps(done)    # wire-serializable as-is
        sig2 = LoadSignal.from_report(done)
        assert sig2.in_flight == 0
        assert sig2.ttft_p50_ms is not None   # sketches carried over
    finally:
        sched.close()


def test_router_places_on_idle_replica(lm):
    """ACCEPTANCE: saturate one replica and placement shifts to the
    idle one, driven by the live rollup (queue depth / occupancy), not
    by the router's own dispatch ledger (the saturating load bypasses
    the router entirely)."""
    model, params = lm
    hot = InprocReplica(_sched(model, params, replica=0), name="hot")
    idle = InprocReplica(_sched(model, params, replica=1), name="idle")
    # saturate 'hot' BEHIND the router's back: fill every slot + queue
    for _ in range(6):
        assert hot.sched.submit([1, 2, 3, 4], 8) is not None
    hot.sched.tick()
    assert LoadSignal.from_report(hot.sched.load_report()).occupancy > 0
    router = FleetRouter([hot, idle], queue_depth=32)
    rids = [router.submit([5, 6, 7], 4) for _ in range(4)]
    assert all(r is not None for r in rids)
    for _ in range(200):
        router.pump()
        if all(router.done(r) for r in rids):
            break
    assert all(router.done(r) for r in rids)
    placed = router.per_replica_completed()
    assert placed["idle"] == 4 and placed["hot"] == 0, placed
    hot.close()
    idle.close()


def test_router_rejects_overload_at_router_not_blind(lm):
    """One bounded FLEET queue sheds overload; replica-local queues
    stay shallow (slots + replica_queue_cap), so waiting work remains
    re-placeable at the router."""
    model, params = lm
    a = InprocReplica(_sched(model, params, replica=0), name="a")
    b = InprocReplica(_sched(model, params, replica=1), name="b")
    router = FleetRouter([a, b], queue_depth=4, replica_queue_cap=1)
    rids = [router.submit([1, 2], 4) for _ in range(40)]
    accepted = [r for r in rids if r is not None]
    router.pump()   # one dispatch pass, no replica progress yet
    assert router.rejected >= 40 - (4 + 2 * (4 + 1))
    assert router.rejected == sum(1 for r in rids if r is None)
    for h in (a, b):
        # local backlog bounded by slots + cap
        assert len(h.assigned()) <= 4 + 1
    # everything accepted eventually completes (no starvation)
    for _ in range(500):
        router.pump()
        if all(router.done(r) for r in accepted):
            break
    assert all(router.done(r) for r in accepted)
    a.close()
    b.close()


class _StubReplica(ReplicaHandle):
    """A load-signal stub for admission-policy pins (never serves)."""

    def __init__(self, name, ttft_p50_ms, slots=4):
        self.name = name
        sk = QuantileSketch()
        sk.add(ttft_p50_ms)
        self._rec = {"kind": "rollup", "role": "serve",
                     "sketches": {"ttft_ms": sk.to_dict()},
                     "now": {"queue_depth": 0, "in_flight": 0,
                             "free_slots": slots, "slots": slots,
                             "queue_cap": 16, "free_blocks": 100,
                             "block_utilization": 0.0}}

    def alive(self):
        return True

    def accepting(self):
        return True

    def load(self):
        return LoadSignal.from_report(self._rec)

    def submit(self, req):
        return False

    def pump(self):
        return []

    def assigned(self):
        return []

    def take_assigned(self):
        return []


def test_router_slo_infeasible_rejection():
    """With reject_infeasible, a deadline no replica's TTFT rollup can
    plausibly meet is rejected at admission (counted separately);
    feasible deadlines and SLO-less requests still admit."""
    slow = _StubReplica("slow", ttft_p50_ms=500.0)
    router = FleetRouter([slow], queue_depth=8,
                         reject_infeasible=True,
                         feasibility_margin=1.0)
    assert router.submit([1, 2], 4, slo_ms=10.0) is None
    assert router.rejected_infeasible == 1
    assert router.submit([1, 2], 4, slo_ms=10_000.0) is not None
    assert router.submit([1, 2], 4) is not None     # no SLO: admits
    assert router.rejected == 1


def test_router_requeues_dead_replica_tokens_exact(lm):
    """In-process death: the failed replica's in-flight requests
    requeue at the router and complete on the sibling with tokens
    byte-identical to the undisturbed reference; no request starves."""
    model, params = lm
    plan = make_requests(4, 2, vocab_size=V, prompt_lens=(3, 10),
                         max_new=(4, 8), seed=11)
    ref = _reference_tokens(model, params, plan)
    a = InprocReplica(_sched(model, params, replica=0), name="a")
    b = InprocReplica(_sched(model, params, replica=1), name="b")
    router = FleetRouter([a, b], queue_depth=32)
    rids = {}
    for ci, reqs in enumerate(plan):
        for i, r in enumerate(reqs):
            rid = router.submit(r["prompt"], r["max_new"])
            assert rid is not None
            rids[(ci, i)] = rid
    for _ in range(3):   # part-way: some prefill/decode on both
        router.pump()
    assert a.assigned() or b.assigned()
    victim, survivor = (a, b) if a.assigned() else (b, a)
    n_inflight = len(victim.assigned())
    victim.fail()
    for _ in range(2000):
        router.pump()
        if all(router.done(r) for r in rids.values()):
            break
    assert all(router.done(r) for r in rids.values())   # no starvation
    assert router.requeued >= n_inflight > 0
    assert router.replica_deaths == 1
    for key, rid in rids.items():
        assert router.result(rid) == ref[key], key
    survivor.close()


def test_scheduler_drain_feeds_router_requeue(lm):
    """Graceful shrink: drain() hands the in-flight set back in
    submission order; re-submission through the router reproduces the
    same tokens on another replica."""
    model, params = lm
    donor = _sched(model, params, replica=0)
    sink = InprocReplica(_sched(model, params, replica=1), name="sink")
    router = FleetRouter([sink], queue_depth=32)
    subs = [([1 + i, 2 + i, 3 + i], 5) for i in range(4)]
    for p, n in subs:
        assert donor.submit(p, n) is not None
    for _ in range(3):
        donor.tick()
    drained = donor.drain()
    donor.server.allocator.assert_drained()
    assert [d["prompt"] for d in drained] == [p for p, _ in subs]
    rids = [router.submit(d["prompt"], d["max_new"],
                          slo_ms=d["slo_ms"]) for d in drained]
    for _ in range(500):
        router.pump()
        if all(router.done(r) for r in rids):
            break
    ref = _reference_tokens(
        model, params, [[{"prompt": p, "max_new": n}] for p, n in subs])
    for i, rid in enumerate(rids):
        assert router.result(rid) == ref[(i, 0)]
    donor.close()
    sink.close()


class _RacyHandle(ReplicaHandle):
    """A handle whose completion events buffer like a subprocess pipe:
    lets a test stage 'completed, then died, events still queued'."""

    def __init__(self, name="racy"):
        self.name = name
        self._assigned = {}
        self.events = []
        self._alive = True

    def alive(self):
        return self._alive

    def accepting(self):
        return self._alive

    def load(self):
        return None

    def submit(self, req):
        if not self._alive:
            return False
        self._assigned[req.rid] = req
        return True

    def pump(self):
        out, self.events = self.events, []
        for rec in out:
            self._assigned.pop(int(rec["rid"]), None)
        return out

    def assigned(self):
        return list(self._assigned)

    def take_assigned(self):
        rids = list(self._assigned)
        self._assigned.clear()
        return rids


def test_raced_completion_on_death_is_honored_not_requeued():
    """A completion event that raced the replica's death (buffered on
    the pipe when the supervisor reports the exit) must be honored —
    surfacing from the next pump — never requeued into a duplicate
    execution."""
    racy = _RacyHandle()
    router = FleetRouter([racy], queue_depth=8)
    rid = router.submit([1, 2], 2)
    router.pump()                      # dispatched to racy
    assert racy.assigned() == [rid]
    # the worker finished the request and THEN died: the done event is
    # still queued when the death notice arrives
    racy.events.append({"ev": "done", "rid": rid,
                        "tokens": [1, 2, 9, 9], "ttft_ms": 1.0,
                        "itl_ms": 1.0})
    racy._alive = False
    router.on_replica_down(racy.name)
    assert router.requeued == 0        # honored, not re-run
    done = router.pump()
    assert done == [rid]
    assert router.result(rid) == [1, 2, 9, 9]
    assert len(router.queue) == 0


# ---------------------------------------------------------------------------
# tensor-parallel replica (core-lane acceptance pin)
# ---------------------------------------------------------------------------

def test_tp_replica_tokens_identical_to_single_device(lm):
    """ACCEPTANCE: one replica spanning a 2-device tensor-parallel mesh
    through generate_tp serves the same requests as the single-device
    paged replica with IDENTICAL tokens (greedy)."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
        mesh as mesh_lib,
    )

    model, params = lm
    mesh = mesh_lib.make_mesh(MeshConfig(data=1, tensor=2),
                              devices=np.asarray(jax.devices()[:2]))
    ptp = dict(params)
    ptp["blocks"] = megatron.permute_qkv(
        params["blocks"], model.cfg.d_model, model.cfg.n_heads, 2,
        kv_heads=model.cfg.kv_heads)
    tp = TPGenerateReplica(model, ptp, mesh, batch=4, name="tp")
    paged = InprocReplica(_sched(model, params, queue_depth=32),
                          name="paged")
    plan = make_requests(4, 2, vocab_size=V, prompt_lens=(3, 10),
                         max_new=(4, 8), seed=7)
    reqs = [r for client in plan for r in client]
    got = {"tp": {}, "paged": {}}
    for i, r in enumerate(reqs):
        for h in (tp, paged):
            assert h.submit(FleetRequest(i, list(r["prompt"]),
                                         r["max_new"], None, 0.0,
                                         math.inf))
    for _ in range(500):
        for name, h in (("tp", tp), ("paged", paged)):
            for rec in h.pump():
                got[name][rec["rid"]] = rec["tokens"]
        if all(len(got[n]) == len(reqs) for n in got):
            break
    assert all(len(got[n]) == len(reqs) for n in got)
    for i in range(len(reqs)):
        assert got["tp"][i] == got["paged"][i], i
    paged.close()


def test_tp_replica_routes_in_a_mixed_fleet(lm):
    """A TP replica is just another ReplicaHandle: a mixed fleet
    (1 paged + 1 TP) drains a closed loop with exact fleet-level token
    accounting."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
        mesh as mesh_lib,
    )

    model, params = lm
    mesh = mesh_lib.make_mesh(MeshConfig(data=1, tensor=2),
                              devices=np.asarray(jax.devices()[:2]))
    ptp = dict(params)
    ptp["blocks"] = megatron.permute_qkv(
        params["blocks"], model.cfg.d_model, model.cfg.n_heads, 2,
        kv_heads=model.cfg.kv_heads)
    tp = TPGenerateReplica(model, ptp, mesh, batch=2, name="tp")
    paged = InprocReplica(_sched(model, params), name="paged")
    router = FleetRouter([paged, tp], queue_depth=32)
    row = run_fleet_closed_loop(router, 4, 2, vocab_size=V,
                                prompt_lens=(3, 10), max_new=(4, 8),
                                seed=13)
    assert row["requests"] == 8
    assert row["tokens_out"] > 0
    assert sum(row["per_replica_completed"].values()) == 8
    paged.close()


# ---------------------------------------------------------------------------
# loadgen seed partitioning (satellite)
# ---------------------------------------------------------------------------

def test_make_requests_stream_partitions_seed_space():
    base = make_requests(2, 3, vocab_size=V, seed=5)
    again = make_requests(2, 3, vocab_size=V, seed=5, stream=0)
    assert base == again            # stream=0 keeps historical draws
    r1 = make_requests(2, 3, vocab_size=V, seed=5, stream=1)
    r2 = make_requests(2, 3, vocab_size=V, seed=5, stream=2)
    assert r1 != base and r2 != base and r1 != r2
    # determinism per stream
    assert r1 == make_requests(2, 3, vocab_size=V, seed=5, stream=1)


def test_scheduler_flow_prefix_unique_per_replica(lm):
    model, params = lm
    s0 = _sched(model, params, replica=0)
    s1 = _sched(model, params, replica=1)
    try:
        assert s0._flow_prefix != s1._flow_prefix
        assert "R1-" in s1._flow_prefix
    finally:
        s0.close()
        s1.close()


# ---------------------------------------------------------------------------
# obs_agg per-replica breakdown (satellite)
# ---------------------------------------------------------------------------

def test_obs_agg_breakdown_rows(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_fleet_obs_agg", str(REPO / "tools" / "obs_agg.py"))
    agg_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(agg_mod)

    def rollup(role, p, replica, ttft, q):
        sk = QuantileSketch()
        for v in ttft:
            sk.add(v)
        rec = {"kind": "rollup", "role": role, "step": 10,
               "t_unix": time.time(), "p": p, "run": "r", "inc": 0,
               "sketches": {"ttft_ms": sk.to_dict()},
               "counters": {"completed": len(ttft)},
               "gauges": {}, "now": {"queue_depth": q}}
        if replica is not None:
            rec["replica"] = replica
        return rec

    for k, (ttfts, q) in enumerate([([5.0, 6.0], 0),
                                    ([50.0, 60.0], 7)]):
        d = tmp_path / f"replica-{k}"
        d.mkdir()
        with open(d / "metrics.jsonl", "w") as f:
            f.write(json.dumps(rollup("serve", k, k, ttfts, q)) + "\n")
    rd = tmp_path / "router"
    rd.mkdir()
    with open(rd / "metrics.jsonl", "w") as f:
        f.write(json.dumps(rollup("router", 0, None, [7.0, 70.0], 1))
                + "\n")
    doc = agg_mod.aggregate([str(tmp_path / "replica-0"),
                             str(tmp_path / "replica-1"), str(rd)])
    rows = {(r["role"], r["replica"]): r for r in doc["breakdown"]}
    assert rows[("serve", 1)]["queue_depth"] == 7     # the hot replica
    assert rows[("serve", 0)]["ttft_ms_p50"] < \
        rows[("serve", 1)]["ttft_ms_p50"]
    assert ("router", 0) in rows                       # router row too
    text = agg_mod.render_text(doc)
    assert "per-writer" in text and "serve r1 p1" in text


# ---------------------------------------------------------------------------
# multi-process e2e (slow/chaos: subprocess replicas + SIGKILL)
# ---------------------------------------------------------------------------

MODEL_FLAGS = dict(vocab=V, seq=64, layers=2, d_model=32, heads=4,
                   d_ff=64, init_seed=0)
SERVE_FLAGS = dict(slots=4, num_blocks=17, block_size=16,
                   prefill_chunk=16, queue_depth=16)


@pytest.mark.slow
def test_worker_protocol_roundtrip(tmp_path):
    """One subprocess replica: ready -> submit -> done with tokens
    matching the in-process scheduler, status events carrying the
    rollup record, clean drain on exit."""
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    params = model.init(prng.init_key(0))
    plan = make_requests(2, 2, vocab_size=V, prompt_lens=(3, 10),
                         max_new=(4, 8), seed=3)
    ref = _reference_tokens(model, params, plan)
    fleet = launch_fleet(1, model=MODEL_FLAGS, serve=SERVE_FLAGS,
                         telemetry_root=str(tmp_path),
                         log=lambda m: None)
    try:
        fleet.wait_ready(300)
        rids = {}
        for ci, reqs in enumerate(plan):
            for i, r in enumerate(reqs):
                rid = fleet.submit(r["prompt"], r["max_new"])
                assert rid is not None
                rids[(ci, i)] = rid
        t0 = time.time()
        while time.time() - t0 < 120:
            fleet.pump()
            if all(fleet.done(r) for r in rids.values()):
                break
            time.sleep(0.005)
        assert all(fleet.done(r) for r in rids.values())
        for key, rid in rids.items():
            assert fleet.result(rid) == ref[key], key
        # the live load signal arrived over the wire as a rollup record
        sig = fleet.handles[0].load()
        assert sig is not None and sig.slots == 4
        # replica telemetry landed in its own dir under its identity
        mpath = tmp_path / "replica-0" / "metrics.jsonl"
        assert mpath.exists()
    finally:
        fleet.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_chaos_kill_replica_mid_load(tmp_path):
    """ACCEPTANCE e2e: SIGKILL one subprocess replica mid-load under
    the group supervisor — every in-flight request completes after
    requeue with tokens byte-identical to the undisturbed reference,
    no request starves, the supervisor relaunches the dead replica and
    the sibling keeps serving throughout (its pid never changes)."""
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    params = model.init(prng.init_key(0))
    clients, per_client = 6, 3
    plan = make_requests(clients, per_client, vocab_size=V,
                         prompt_lens=(3, 10), max_new=(6, 10), seed=5)
    ref = _reference_tokens(model, params, plan)
    fleet = launch_fleet(2, model=MODEL_FLAGS, serve=SERVE_FLAGS,
                         telemetry_root=str(tmp_path),
                         backoff=0.2, backoff_cap=1.0,
                         log=lambda m: None)
    try:
        fleet.wait_ready(300)
        sibling_pid = fleet.supervisor.proc("replica-1").pid
        rids = {}
        next_i = {ci: 0 for ci in range(clients)}
        outstanding = {ci: None for ci in range(clients)}
        killed = False
        t0 = time.time()
        while time.time() - t0 < 300:
            for ci in range(clients):
                if outstanding[ci] is not None or \
                        next_i[ci] >= per_client:
                    continue
                r = plan[ci][next_i[ci]]
                rid = fleet.submit(r["prompt"], r["max_new"])
                if rid is None:
                    continue
                rids[(ci, next_i[ci])] = rid
                outstanding[ci] = rid
                next_i[ci] += 1
            for rid in fleet.pump():
                for ci in range(clients):
                    if outstanding[ci] == rid:
                        outstanding[ci] = None
            n_done = sum(1 for r in rids.values() if fleet.done(r))
            if not killed and n_done >= 3:
                # mid-load: some requests done, others in flight
                victim = fleet.supervisor.proc("replica-0")
                os.kill(victim.pid, signal.SIGKILL)
                killed = True
            if len(rids) == clients * per_client and \
                    all(v is None for v in outstanding.values()):
                break
            time.sleep(0.002)
        assert killed, "load finished before the kill could land"
        assert len(rids) == clients * per_client
        assert all(fleet.done(r) for r in rids.values())  # no starvation
        # byte-identical to the undisturbed reference, requeues included
        for key, rid in rids.items():
            assert fleet.result(rid) == ref[key], key
        assert fleet.router.replica_deaths >= 1
        assert fleet.router.requeued >= 1
        # supervisor relaunches ONLY the dead replica (the load can
        # drain before the backoff elapses — wait the relaunch out)
        t0 = time.time()
        while time.time() - t0 < 60:
            fleet.pump()
            if any(e["child"] == "replica-0"
                   and e["event"] == "relaunch" for e in fleet.events):
                break
            time.sleep(0.02)
        evs = [(e["child"], e["event"]) for e in fleet.events]
        assert ("replica-0", "exit") in evs
        assert ("replica-0", "relaunch") in evs
        assert ("replica-1", "relaunch") not in evs
        assert fleet.supervisor.proc("replica-1").pid == sibling_pid
        assert fleet.per_replica_completed()["replica-1"] > 0
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_crash_at_request_fault_injection(tmp_path):
    """The worker's --crash-at-request fault hook: replica 0 dies on
    its 2nd submit, the fleet still completes everything exactly."""
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    params = model.init(prng.init_key(0))
    plan = make_requests(4, 2, vocab_size=V, prompt_lens=(3, 10),
                         max_new=(4, 8), seed=9)
    ref = _reference_tokens(model, params, plan)
    fleet = launch_fleet(2, model=MODEL_FLAGS, serve=SERVE_FLAGS,
                         backoff=0.2, backoff_cap=1.0,
                         crash_at_request=2, log=lambda m: None)
    try:
        fleet.wait_ready(300)
        row = run_fleet_closed_loop(fleet, 4, 2, vocab_size=V,
                                    prompt_lens=(3, 10),
                                    max_new=(4, 8), seed=9)
        assert row["requests"] == 8
        assert row["requeued"] >= 1
        # tokens_sha256 is over (client, idx, tokens) — compare against
        # the reference digest computed the same way
        import hashlib

        h = hashlib.sha256()
        for key in sorted(ref):
            h.update(repr((key[0], key[1], ref[key])).encode())
        assert row["tokens_sha256"] == h.hexdigest()
    finally:
        fleet.close()
