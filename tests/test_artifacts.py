"""Committed bench artifacts stay parseable and honest.

Every BENCH_*.json in the repo root is a claim the README links to;
this lane pins that (a) each one parses, (b) dict artifacts carry the
keys their consumers (bench_diff, the README tables) read, (c) emitter-
stamped ``_meta`` blocks are internally consistent — the honesty flags
must agree with the measurement they describe (a ``platform: cpu``
artifact may not claim real-chip numbers), and (d) the goodput artifact
satisfies its acceptance gates as COMMITTED, not just at generation
time: categories sum to the covered wall-clock, the injected crash is
priced, params/tokens are bitwise-identical accounting on vs off, and
the interleaved-pair overhead is within its stated gate.
"""

import glob
import json
import pathlib

import pytest

pytestmark = pytest.mark.goodput

REPO = pathlib.Path(__file__).resolve().parent.parent
ARTIFACTS = sorted(glob.glob(str(REPO / "BENCH_*.json")))


def _docs():
    for path in ARTIFACTS:
        with open(path) as f:
            yield path, json.load(f)


def test_artifacts_exist():
    assert ARTIFACTS, "no committed BENCH_*.json artifacts found"


@pytest.mark.parametrize("path", ARTIFACTS,
                         ids=[pathlib.Path(p).name for p in ARTIFACTS])
def test_artifact_parses(path):
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc, (dict, list)), path


def test_meta_blocks_are_consistent():
    """Artifacts written by bench.py's ``_emit_artifact`` stamp a
    ``_meta`` block; wherever one exists it must be self-consistent.
    (Artifacts predating the emitter are exempt from carrying one —
    re-running their bench upgrades them — but may not carry a broken
    one.)"""
    stamped = 0
    for path, doc in _docs():
        if not isinstance(doc, dict) or "_meta" not in doc:
            continue
        stamped += 1
        meta = doc["_meta"]
        assert meta["schema"] >= 1, path
        assert meta["generated_unix"] > 0, path
        assert isinstance(meta.get("host"), str) and meta["host"], path
        honesty = meta["honesty"]
        if "platform" in doc:
            assert honesty["cpu_fallback"] == (doc["platform"] == "cpu"), \
                f"{path}: honesty.cpu_fallback contradicts platform"
        if "interleaved" in honesty and honesty["interleaved"]:
            assert "interleaved" in str(doc.get("note", "")), path
    assert stamped >= 1, "no _meta-stamped artifact committed"


def test_goodput_artifact_acceptance_gates():
    path = REPO / "BENCH_GOODPUT.json"
    assert path.exists(), "BENCH_GOODPUT.json not committed"
    with open(path) as f:
        doc = json.load(f)
    assert doc["metric"] == "goodput_accounting_ab"
    assert doc["_meta"]["schema"] >= 1

    # 100% of the chaos run's wall-clock is classified
    chaos = doc["chaos"]
    assert chaos["sum_ok_all_processes"] and chaos["fleet_sum_ok"]
    assert abs(sum(chaos["categories"].values())
               - chaos["covered_s"]) < 2e-5
    # the injected crash is priced, not dropped
    assert chaos["relaunches"] >= 1
    assert chaos["relaunch_gap_s"] > 0.0
    assert chaos["retrain_rollback_s"] > 0.0

    # bitwise pins: accounting on vs off changes nothing it measures
    assert doc["params_bitwise_identical"] is True
    assert doc["serve"]["tokens_bitwise_identical"] is True
    assert doc["meter_sum_ok"] is True

    # the interleaved-pair overhead honors its own stated gate
    assert doc["overhead_pair_median_pct"] <= doc["overhead_gate_pct"]
    assert "interleaved" in doc["note"]

    # per-role goodput fraction survives to the Prometheus export
    merged = doc["fleet_merge"]
    assert merged["prometheus_families_present"] is True
    assert any(ln.startswith("nnpt_goodput_fraction{role=")
               for ln in merged["prometheus_fraction_lines"])
