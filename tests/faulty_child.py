"""Child for the distributed fault-injection test (SURVEY.md §5.3).

Two processes form a jax.distributed world and train in lockstep.  The
VICTIM (process 1) dies abruptly mid-training (os._exit, no cleanup — the
moral equivalent of a crashed MPI rank).  The SURVIVOR (process 0) must
FAIL FAST: either its next collective raises (exit 43) or, if the runtime
blocks instead, the step-hang watchdog fires (exit 42).  What must NOT
happen is the reference's behavior — hanging forever in a collective
(dataParallelTraining_NN_MPI.py:185's gather is a barrier with no timeout;
README.md:10 notes the cluster path was never even run).

Usage: faulty_child.py <process_id> <port>
"""

import json
import os
import sys


def main() -> int:
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import MLP
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh, world_setup,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng
    from neural_networks_parallel_training_with_mpi_tpu.utils.watchdog import (
        HangWatchdog,
    )

    world_setup(coordinator_address=f"127.0.0.1:{port}", num_processes=2,
                process_id=pid, timeout_s=60)
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices())

    rng = np.random.default_rng(0)
    batch = shd.shard_batch(mesh, {
        "x": rng.standard_normal((32, 4)).astype(np.float32),
        "y": rng.standard_normal((32, 1)).astype(np.float32),
        "mask": np.ones((32,), np.float32)})
    model = MLP(4, (8,), 1)
    opt = optim.sgd(lr=1e-2)
    state = dp.replicate_state(
        TrainState.create(model, opt, prng.init_key(0)), mesh)
    step = dp.make_train_step(model, opt, mesh, "mse", "global_mean")

    victim = pid == 1
    watchdog = HangWatchdog(8.0)
    with watchdog:
        for i in range(10_000):
            if victim and i == 20:
                # die like a crashed MPI rank: no shutdown, no goodbye
                os._exit(1)
            try:
                state, loss = step(state, batch)
                # the blocking readback is what stalls when the peer dies
                float(jax.device_get(loss))
            except Exception as e:  # noqa: BLE001 — fail-fast path A
                print(json.dumps({"pid": pid, "error_step": i,
                                  "error": f"{type(e).__name__}"}),
                      flush=True)
                os._exit(43)
            watchdog.pat()
    return 0


if __name__ == "__main__":
    sys.exit(main())
