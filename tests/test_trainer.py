"""End-to-end Trainer tests: the reference's whole ``dist_train`` behavior
(dataParallelTraining_NN_MPI.py:56-236) plus the extensions (real batch_size,
checkpoint/resume, eval)."""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer


def _cfg(**kw):
    cfg = TrainConfig(
        mesh=MeshConfig(data=8),
        data=DataConfig(),
        model=ModelConfig(),
        **kw,
    )
    return cfg


def test_reference_defaults_run(mesh8):
    """The reference's default job: 3 epochs, full-batch, SGD(0.001, 0.9)."""
    t = Trainer(_cfg(), mesh=mesh8)
    result = t.fit()
    assert result["steps"] == 3  # 3 epochs x 1 full-batch step (:150, :146)
    assert np.isfinite(result["final_loss"])


def test_real_batch_size(mesh8):
    """--batch_size is honored (reference bug B1: parsed but unused)."""
    t = Trainer(_cfg(full_batch=False, batch_size=8, nepochs=2), mesh=mesh8)
    result = t.fit()
    assert result["steps"] == 4  # 16 samples / 8 per batch x 2 epochs


def test_uneven_batch_padding(mesh8):
    cfg = _cfg(full_batch=False, batch_size=6, nepochs=1)
    t = Trainer(cfg, mesh=mesh8)
    result = t.fit()
    # ceil(16/6) = 3 steps, final partial batch padded+masked
    assert result["steps"] == 3


def test_drop_remainder(mesh8):
    cfg = _cfg(full_batch=False, batch_size=6, nepochs=1)
    cfg.data.remainder = "drop"
    t = Trainer(cfg, mesh=mesh8)
    result = t.fit()
    assert result["steps"] == 2


def test_training_reduces_loss(mesh8):
    # lr=0.005: at lr=0.01 this job (momentum-0.9 SGD on the RAW-scale
    # regression targets, std ~50) converges for ~30 epochs and then
    # diverges back to the mean-predictor fixed point — a real instability
    # of the reference's hyperparameters, not a framework bug (and exactly
    # the loss-spike shape train.resilience's rollback exists to catch)
    t = Trainer(_cfg(nepochs=200, lr=0.005, shuffle=False), mesh=mesh8)
    t.init_state()
    first = t.evaluate()["loss"]
    result = t.fit()
    final = t.evaluate()["loss"]
    assert final < first * 0.5


def test_checkpoint_resume(mesh8, tmp_path):
    ck = str(tmp_path / "ckpt")
    t1 = Trainer(_cfg(nepochs=2, checkpoint_dir=ck), mesh=mesh8)
    t1.fit()
    t2 = Trainer(_cfg(nepochs=4, checkpoint_dir=ck, resume=True), mesh=mesh8)
    t2.init_state()
    assert t2.maybe_resume() == 2  # global step, 2 epochs x 1 step
    result = t2.fit()
    assert result["steps"] == 4


def test_resume_equals_uninterrupted(mesh8, tmp_path):
    """Interrupted-and-resumed training ends bit-identical to an
    uninterrupted run (same per-epoch shuffle order, no replayed steps)."""
    import jax

    t_gold = Trainer(_cfg(full_batch=False, batch_size=4, nepochs=2,
                          shuffle=True), mesh=mesh8)
    t_gold.fit()

    ck = str(tmp_path / "ck2")
    t1 = Trainer(_cfg(full_batch=False, batch_size=4, nepochs=1,
                      checkpoint_dir=ck), mesh=mesh8)
    t1.fit()
    t2 = Trainer(_cfg(full_batch=False, batch_size=4, nepochs=2,
                      checkpoint_dir=ck, resume=True), mesh=mesh8)
    t2.init_state()
    assert t2.maybe_resume() == 4  # 1 epoch x 4 steps done
    result = t2.fit()
    assert result["steps"] == 8
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(t_gold.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(t2.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_midepoch_start_step_skips_batches(mesh8):
    """loader.epoch(e, start_step=k) must yield exactly the batches k..end
    of the same epoch order — the no-replay guarantee for mid-epoch resume."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.data.datasets import (
        regression_dataset,
    )
    from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
        ShardedLoader,
    )

    data = regression_dataset()
    loader = ShardedLoader(mesh8, data, 4, shuffle=True, seed=7)
    full = [jax.device_get(b["x"]) for b in loader.epoch(3)]
    tail = [jax.device_get(b["x"]) for b in loader.epoch(3, start_step=2)]
    assert len(full) == 4 and len(tail) == 2
    np.testing.assert_array_equal(full[2], tail[0])
    np.testing.assert_array_equal(full[3], tail[1])
    assert loader.batch_rows(3) == 4
    uneven = ShardedLoader(mesh8, regression_dataset(n_samples=14), 4,
                           shuffle=False)
    assert uneven.batch_rows(3) == 2  # final partial batch: real rows only


def test_checkpoint_rejects_wrong_model(mesh8, tmp_path):
    import pytest as _pytest

    ck = str(tmp_path / "ck3")
    t1 = Trainer(_cfg(nepochs=1, checkpoint_dir=ck), mesh=mesh8)
    t1.fit()
    cfg = _cfg(nepochs=2, checkpoint_dir=ck, resume=True)
    cfg.model = ModelConfig(arch="mlp", in_features=2, hidden=(7,),
                            out_features=1)
    t2 = Trainer(cfg, mesh=mesh8)
    t2.init_state()
    with _pytest.raises(ValueError, match="shape|structure"):
        t2.maybe_resume()


def test_eval_accuracy_classification(mesh8):
    cfg = _cfg(loss="cross_entropy", nepochs=1)
    cfg.data = DataConfig(dataset="mnist", n_samples=64)
    cfg.model = ModelConfig(arch="mlp", in_features=784, hidden=(32,),
                            out_features=10)
    t = Trainer(cfg, mesh=mesh8)
    t.init_state()
    metrics = t.evaluate()
    assert 0.0 <= metrics["accuracy"] <= 1.0

def test_trainer_rejects_ablation_grad_reduction():
    """grad_reduction='local' is bench.py's collective-cost ablation
    (replicas diverge); the Trainer must refuse it even though
    data_parallel.make_train_step accepts it for the measurement path."""
    import dataclasses

    import pytest

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    cfg = TrainConfig(nepochs=1, batch_size=8,
                      data=DataConfig(dataset="regression", n_samples=16),
                      model=ModelConfig(arch="mlp"),
                      mesh=MeshConfig(data=8))
    cfg = dataclasses.replace(cfg, grad_reduction="local")
    with pytest.raises(ValueError, match="not a training semantic"):
        Trainer(cfg)


def test_trainer_rejects_ce_chunk_off_dp_path():
    """--ce_chunk is consulted only by data_parallel.make_loss_fn; on any
    other layout it would be silently ignored (full logits materialized
    anyway), so the Trainer fails loudly instead."""
    cfg = TrainConfig(nepochs=1, batch_size=8,
                      data=DataConfig(dataset="lm", seq_len=16,
                                      vocab_size=64),
                      model=ModelConfig(arch="transformer", ce_chunk=4,
                                        max_seq_len=64, vocab_size=64),
                      mesh=MeshConfig(data=4, tensor=2))
    with pytest.raises(ValueError, match="ce_chunk.*data-parallel"):
        Trainer(cfg)


def test_trainer_runs_ce_chunk_on_dp(mesh8):
    """The fused chunked-CE path trains end-to-end under the Trainer on
    the pure-DP layout (loss finite, steps counted)."""
    cfg = TrainConfig(nepochs=1, batch_size=16, loss="cross_entropy",
                      data=DataConfig(dataset="lm", n_samples=32,
                                      seq_len=16, vocab_size=64),
                      model=ModelConfig(arch="transformer", ce_chunk=4,
                                        n_layers=1, d_model=16, n_heads=2,
                                        d_ff=32, max_seq_len=64,
                                        vocab_size=64),
                      mesh=MeshConfig(data=8))
    t = Trainer(cfg, mesh=mesh8)
    result = t.fit()
    assert result["steps"] >= 1
    assert np.isfinite(result["final_loss"])
