"""Fused paged attention: the Pallas kernel family
(``ops.pallas_kernels.paged_attention``) and its serving dispatch seam
(``serve/paged_kv.py``, ``attn_impl='fused'``).

Three layers of pins:

* **kernel vs. plain-numpy reference** — decode (width 1), chunked
  prefill (width > 1, per-row causal), GQA head folding, int8
  dequant-on-load, and the inactive-lane (``length 0``) zero-output
  convention, all in interpret mode on CPU (the ``_interpret_default``
  seam — CPU lanes never need a flag).
* **fused == gathered tokens** — the serving contract: swapping the
  attention dispatch must not move a single token.  The gathered path is
  pinned against dense ``DecodeServer``/``generate()`` by
  tests/test_serve_paged.py, so these pins chain the fused kernel to the
  eager reference without re-paying it.
* **the recompile invariant** — block tables and lengths are traced
  scalar-prefetch operands: admission, growth, eviction and re-admission
  re-run ONE compiled step program (``_cache_size`` pinned).

Core-lane budget note: one pinned-geometry parity scenario (plus the
cheap kernel-reference pins) runs in the budgeted core lane; per-variant
fresh compiles (GQA / int8 / scan_layers / rope) are in the slow lane,
and random-geometry scheduler fuzz under the fused path rides the
``serve`` lane in tests/test_serve_sched.py."""

import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops.pallas_kernels import (
    paged_attention,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    PagedDecodeServer,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

pytestmark = pytest.mark.pallas

VOCAB = 64


def _model(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=64, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    base.update(kw)
    return Transformer(TransformerConfig(**base))


def _drain(srv, rid, prefill_width=16):
    while not srv.prefill_step(rid, prefill_width):
        pass
    while not srv.done(rid):
        srv.step()
    return srv.result(rid)


# ---------------------------------------------------------------------------
# kernel vs. plain-numpy reference
# ---------------------------------------------------------------------------

def _np_reference(q, kp, vp, tables, lens, starts, ks=None, vs=None):
    """The paged-attention math in plain numpy: gather each stream's live
    blocks, truncate to its true length, per-row causal softmax."""
    s_n, w, n_heads, hd = q.shape
    _, bs, kv_heads, _ = kp.shape
    g = n_heads // kv_heads
    out = np.zeros((s_n, w, n_heads, hd), np.float32)
    for s in range(s_n):
        ln = int(lens[s])
        if ln == 0:
            continue
        nb = -(-ln // bs)
        gat = lambda pool: np.concatenate(                 # noqa: E731
            [np.asarray(pool, np.float32)[tables[s, j]] for j in range(nb)],
            axis=0)[:ln]
        k, v = gat(kp), gat(vp)
        if ks is not None:
            k = k * gat(ks)[..., None]
            v = v * gat(vs)[..., None]
        for col in range(w):
            q_pos = int(starts[s]) + col
            for h in range(n_heads):
                c = h // g
                sc = (np.asarray(q, np.float32)[s, col, h]
                      @ k[:, c].T) / np.sqrt(hd)
                sc = np.where(np.arange(ln) <= q_pos, sc, -1e30)
                p = np.exp(sc - sc.max())
                p /= p.sum()
                out[s, col, h] = p @ v[:, c]
    return out


def _pool_fixture(seed=0, nb=10, bs=4, kv=2, hd=8):
    rng = np.random.default_rng(seed)
    kp = jnp.asarray(rng.normal(size=(nb, bs, kv, hd)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(nb, bs, kv, hd)), jnp.float32)
    tables = np.zeros((3, 5), np.int32)
    tables[0, :3] = [1, 4, 7]
    tables[1, :2] = [2, 9]
    tables[2, :1] = [5]
    return rng, kp, vp, tables


def test_kernel_decode_matches_reference():
    """Width-1 (decode) against the numpy reference: ragged lengths, a
    block-straddling stream, and an INACTIVE length-0 lane that must
    contribute exactly nothing (output 0, zero blocks walked)."""
    rng, kp, vp, tables = _pool_fixture()
    lens = np.asarray([11, 6, 0], np.int32)
    starts = np.maximum(lens - 1, 0).astype(np.int32)
    q = jnp.asarray(rng.normal(size=(3, 1, 4, 8)), jnp.float32)
    got = paged_attention(q, kp, vp, jnp.asarray(tables),
                          jnp.asarray(lens), jnp.asarray(starts))
    want = _np_reference(q, kp, vp, tables, lens, starts)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-6)
    assert np.all(np.asarray(got)[2] == 0.0)      # inactive lane: nothing


def test_kernel_prefill_chunk_causal_gqa():
    """Width-4 chunk (the prefill variant) at nonzero start positions:
    per-row causal masking against absolute positions, with GQA folding
    (4 query heads over 2 kv heads)."""
    rng, kp, vp, tables = _pool_fixture(seed=1)
    lens = np.asarray([11, 6, 4], np.int32)
    starts = np.asarray([7, 2, 0], np.int32)
    q = jnp.asarray(rng.normal(size=(3, 4, 4, 8)), jnp.float32)
    got = paged_attention(q, kp, vp, jnp.asarray(tables),
                          jnp.asarray(lens), jnp.asarray(starts))
    want = _np_reference(q, kp, vp, tables, lens, starts)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-6)


def test_kernel_int8_dequant_on_load():
    """int8 pools with per-(position, head) f32 scales dequantize inside
    the kernel — same numbers as dequantizing before the reference."""
    rng, _, _, tables = _pool_fixture(seed=2)
    kq = rng.integers(-127, 127, (10, 4, 2, 8)).astype(np.int8)
    vq = rng.integers(-127, 127, (10, 4, 2, 8)).astype(np.int8)
    ks = rng.uniform(0.01, 0.1, (10, 4, 2)).astype(np.float32)
    vs = rng.uniform(0.01, 0.1, (10, 4, 2)).astype(np.float32)
    lens = np.asarray([9, 3, 12], np.int32)
    tables[2, :3] = [3, 6, 8]
    starts = np.maximum(lens - 1, 0).astype(np.int32)
    q = jnp.asarray(rng.normal(size=(3, 1, 4, 8)), jnp.float32)
    got = paged_attention(q, jnp.asarray(kq), jnp.asarray(vq),
                          jnp.asarray(tables), jnp.asarray(lens),
                          jnp.asarray(starts), k_scale=jnp.asarray(ks),
                          v_scale=jnp.asarray(vs))
    want = _np_reference(q, kq.astype(np.float32), vq.astype(np.float32),
                         tables, lens, starts, ks, vs)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


def test_kernel_validates_shapes():
    rng, kp, vp, tables = _pool_fixture()
    lens = jnp.zeros((3,), jnp.int32)
    q = jnp.zeros((3, 1, 3, 8), jnp.float32)      # 3 heads over 2 kv
    with pytest.raises(ValueError):
        paged_attention(q, kp, vp, jnp.asarray(tables), lens, lens)
    q = jnp.zeros((3, 1, 4, 8), jnp.float32)
    with pytest.raises(ValueError):               # one scale, not both
        paged_attention(q, kp, vp, jnp.asarray(tables), lens, lens,
                        k_scale=jnp.ones((10, 4, 2)))


# ---------------------------------------------------------------------------
# fused == gathered through the serving surface (the token contract)
# ---------------------------------------------------------------------------

def _staggered_scenario(srv):
    """Staggered ragged admissions incl. an 11-token prompt prefilled in
    width-4 chunks straddling the 8-position block boundary — the
    gathered parity suite's scenario, reused verbatim."""
    reqs = []
    a = srv.try_admit([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11], 12)
    while not srv.prefill_step(a, 4):
        pass
    reqs.append(a)
    srv.step(); srv.step()
    b = srv.try_admit([7, 8], 6)
    while not srv.prefill_step(b, 16):
        pass
    reqs.append(b)
    srv.step()
    c = srv.try_admit([5, 9, 11, 13], 9)
    while not srv.prefill_step(c, 16):
        pass
    reqs.append(c)
    for _ in range(40):
        srv.step()
        if all(srv.done(r) for r in reqs):
            break
    out = [srv.result(r) for r in reqs]
    srv.allocator.assert_drained()                # no leak on the kernel path
    return out


def test_fused_matches_gathered_staggered_straddling():
    """The core-lane parity pin: same staggered block-straddling scenario
    through both attention impls — token-identical, allocator drained.
    (gathered == dense DecodeServer == generate() is pinned by
    tests/test_serve_paged.py, so this chains fused to the reference.)"""
    model = _model()
    params = model.init(prng.init_key(0))
    outs = {}
    for impl in ("gathered", "fused"):
        srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                                block_size=8, attn_impl=impl)
        outs[impl] = _staggered_scenario(srv)
    assert outs["fused"] == outs["gathered"]


def test_fused_evict_readmit_reproduces_tokens():
    """Mid-stream eviction discards device state; the fused path's greedy
    re-run after re-admission must land the same tokens the gathered
    path produces end to end (same geometry as the parity pin, so the
    core lane pays steps, not a fresh compile)."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8, attn_impl="fused")
    rid = srv.try_admit([4, 5, 6], 10)
    while not srv.prefill_step(rid, 16):
        pass
    srv.step(); srv.step(); srv.step()            # mid-flight
    prompt, max_new = srv.evict(rid)
    srv.allocator.assert_drained()
    rid2 = srv.try_admit(prompt, max_new)
    got = _drain(srv, rid2)
    ref_srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                                block_size=8, attn_impl="gathered")
    ref = _drain(ref_srv, ref_srv.try_admit([4, 5, 6], 10))
    assert got == ref
    srv.allocator.assert_drained()


def test_block_table_churn_never_recompiles():
    """The recompile invariant (acceptance criterion): tables and lengths
    are traced scalar-prefetch operands, so admission, on-demand block
    growth, eviction and re-admission all re-run ONE compiled decode
    step; prefill compiles per pow2 bucket width, never per table.
    (The jitted programs are lru-shared across equal-geometry servers,
    so the pin is "no growth after churn", measured on this process's
    shared cache.)"""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8, attn_impl="fused")
    a = srv.try_admit([1] * 12, 12)               # bucket 16 + growth
    while not srv.prefill_step(a, 16):
        pass
    for _ in range(4):
        srv.step()
    # the jitted programs are lru-shared across servers, and OTHER
    # geometries (slots / pool size) legitimately add cache entries in a
    # shared pytest process — the invariant is zero growth from here on
    n_step = srv._step_fn._cache_size()
    n_prefill = srv._prefill_fn._cache_size()
    # churn: a second stream (new table rows, new lengths), growth across
    # a block boundary, an eviction (table zeroed to the sink), and a
    # re-admission — same bucket widths, so NOTHING may recompile
    b = srv.try_admit([9] * 11, 8)
    while not srv.prefill_step(b, 16):
        pass
    srv.step()
    srv.evict(b)
    c = srv.try_admit([3] * 9, 6)
    while not srv.prefill_step(c, 16):
        pass
    while not (srv.done(a) and srv.done(c)):
        srv.step()
    srv.result(a), srv.result(c)
    srv.allocator.assert_drained()
    assert srv._step_fn._cache_size() == n_step
    assert srv._prefill_fn._cache_size() == n_prefill


def test_donation_audit_fused_decode_program():
    """The donation audit extended to the fused serving decode program:
    it donates the KV pools, the token slab and the position vector
    (donate_argnums=(1, 2, 4)) — every donated leaf must alias in/out
    (an unaliased pool leaf would copy the whole block pool per decoded
    token)."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.utils.profiling import (
        donation_report,
    )

    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=24,
                            block_size=8, attn_impl="fused")
    masked = np.where(srv.active[:, None], srv.tables, 0)
    comp = srv._step_fn.lower(
        srv.params, srv.pools, srv.tokens, jnp.asarray(masked), srv.pos,
        jnp.asarray(srv.active), srv.key).compile()
    rep = donation_report(comp)
    donated = len(jax.tree_util.tree_leaves(srv.pools)) + 2  # + tokens, pos
    assert rep["n_aliased"] == donated, rep
    assert rep["unaliased_donors"] == 0, rep


# ---------------------------------------------------------------------------
# model-variant parity (full lane: each variant is a fresh compile)
# ---------------------------------------------------------------------------

def _ab_tokens(model, params, prompt, n, prefill_width=16, **srv_kw):
    outs = []
    for impl in ("gathered", "fused"):
        srv = PagedDecodeServer(model, params, slots=2, num_blocks=20,
                                block_size=8, attn_impl=impl, **srv_kw)
        rid = srv.try_admit(prompt, n)
        outs.append(_drain(srv, rid, prefill_width))
        srv.allocator.assert_drained()
    return outs


@pytest.mark.slow
def test_gqa_fused_exact():
    model = _model(n_kv_heads=2)
    params = model.init(prng.init_key(0))
    g, f = _ab_tokens(model, params, [1, 2, 3], 8)
    assert f == g


@pytest.mark.slow
def test_int8_kv_fused_exact():
    """int8 pools: the kernel dequantizes on load from the same
    per-(position, head) scales the gathered path applies to its
    logits/probs — chunked prefill splitting blocks included."""
    model = _model()
    params = model.init(prng.init_key(0))
    g, f = _ab_tokens(model, params, [1, 2, 3, 4, 5, 6, 7, 8, 9, 10], 8,
                      prefill_width=4, kv_quant=True)
    assert f == g


@pytest.mark.slow
def test_scan_layers_fused_exact():
    model = _model(scan_layers=True)
    params = model.init(prng.init_key(0))
    g, f = _ab_tokens(model, params, [9, 8, 7], 6)
    assert f == g


@pytest.mark.slow
def test_rope_fused_exact():
    """RoPE rotates at absolute positions; the kernel's q_pos/start
    plumbing must agree with the gathered path's rotation windows."""
    model = _model(pos_encoding="rope")
    params = model.init(prng.init_key(0))
    g, f = _ab_tokens(model, params, [1, 2, 3, 4, 5, 6, 7, 8, 9], 8,
                      prefill_width=4)
    assert f == g
