"""Held-out validation — the reference's dead validation/test code
(dataParallelTraining_NN_MPI.py:213-236, SURVEY.md C10) made functional."""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.data.datasets import (
    regression_dataset, train_val_split,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer


def test_split_is_deterministic_and_disjoint():
    data = regression_dataset(n_samples=100)
    tr1, va1 = train_val_split(data, 0.2, seed=7)
    tr2, va2 = train_val_split(data, 0.2, seed=7)
    assert va1["x"].shape[0] == 20 and tr1["x"].shape[0] == 80
    np.testing.assert_array_equal(tr1["x"], tr2["x"])
    np.testing.assert_array_equal(va1["x"], va2["x"])
    # disjoint and exhaustive: every original row appears exactly once
    all_rows = np.concatenate([tr1["x"], va1["x"]])
    assert all_rows.shape == data["x"].shape
    orig = {tuple(r) for r in data["x"].round(6)}
    got = {tuple(r) for r in all_rows.round(6)}
    assert orig == got


def test_split_zero_fraction_is_noop():
    data = regression_dataset(n_samples=16)
    tr, va = train_val_split(data, 0.0)
    assert tr is data and va == {}


def test_split_rejects_bad_fractions():
    data = regression_dataset(n_samples=4)
    with pytest.raises(ValueError):
        train_val_split(data, 1.0)
    with pytest.raises(ValueError):
        train_val_split(data, -0.1)


def test_trainer_reports_validation_metrics(tmp_path):
    cfg = TrainConfig(
        nepochs=2, eval_every=1,
        data=DataConfig(dataset="regression", n_samples=64, val_fraction=0.25),
        mesh=MeshConfig(data=8),
        metrics_jsonl=str(tmp_path / "m.jsonl"),
    )
    t = Trainer(cfg)
    assert t.loader.n == 48 and t.val_data["x"].shape[0] == 16
    result = t.fit()
    assert "val_loss" in result and np.isfinite(result["val_loss"])
    # per-epoch eval wrote val_ metrics lines too
    lines = (tmp_path / "m.jsonl").read_text().strip().splitlines()
    assert any("val_loss" in ln for ln in lines)


def test_trainer_validation_accuracy_for_classification():
    cfg = TrainConfig(
        nepochs=1, batch_size=32, full_batch=False, loss="cross_entropy",
        optimizer="adam", lr=1e-3,
        data=DataConfig(dataset="mnist", n_samples=256, val_fraction=0.25),
        mesh=MeshConfig(data=8),
    )
    import dataclasses

    cfg.model = dataclasses.replace(
        cfg.model, arch="mlp", in_features=784, hidden=(32,), out_features=10)
    t = Trainer(cfg)
    result = t.fit()
    assert "val_accuracy" in result
    assert 0.0 <= result["val_accuracy"] <= 1.0


def test_digits_real_dataset():
    """sklearn load_digits is REAL data (bundled, zero egress): right
    shapes, all 10 classes present, deterministic under seed."""
    from neural_networks_parallel_training_with_mpi_tpu.data.datasets import (
        digits_dataset,
    )

    d1 = digits_dataset(seed=3)
    d2 = digits_dataset(seed=3)
    assert d1["x"].shape == (1797, 64) and d1["y"].shape == (1797,)
    assert set(np.unique(d1["y"])) == set(range(10))
    np.testing.assert_array_equal(d1["x"], d2["x"])
    # standardized: globally ~zero-mean unit-ish variance (fix of ref bug B4)
    assert abs(float(d1["x"].mean())) < 1e-4


@pytest.mark.slow  # lane budget (round 5): heaviest in module; core coverage kept by the sibling tests
def test_lm_validation_reports_perplexity():
    cfg = TrainConfig(
        nepochs=1, batch_size=32, full_batch=False, optimizer="adam",
        lr=1e-3, loss="cross_entropy", eval_every=1,
        data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                        vocab_size=64, val_fraction=0.25),
        model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                          n_heads=4, d_ff=64, vocab_size=64, max_seq_len=16),
        mesh=MeshConfig(data=8),
    )
    r = Trainer(cfg).fit()
    assert "val_ppl" in r
    np.testing.assert_allclose(r["val_ppl"], np.exp(r["val_loss"]),
                               rtol=1e-6)
    # an untrained 64-vocab LM sits near uniform: ppl ~ vocab size
    assert 20.0 < r["val_ppl"] < 100.0


def test_text_dataset_windows(tmp_path):
    """dataset='text': byte-level windows over a local file, x/y shifted."""
    from neural_networks_parallel_training_with_mpi_tpu.data.datasets import (
        text_dataset,
    )

    p = tmp_path / "corpus.txt"
    payload = bytes(range(256)) * 4  # 1024 known bytes
    p.write_bytes(payload)
    d = text_dataset(str(p), seq_len=16, vocab_size=256)
    assert d["x"].shape == (1024 // 17, 16)
    np.testing.assert_array_equal(d["x"][0], np.arange(16))
    np.testing.assert_array_equal(d["y"][0], np.arange(1, 17))
    # y is x shifted by one within each window
    np.testing.assert_array_equal(d["x"][:, 1:], d["y"][:, :-1])

    import pytest as _pytest

    with _pytest.raises(FileNotFoundError):
        text_dataset(str(tmp_path / "missing.txt"), seq_len=16)
    with _pytest.raises(ValueError, match="one window"):
        small = tmp_path / "small.txt"
        small.write_bytes(b"hi")
        text_dataset(str(small), seq_len=16)
