"""Child process for the 2-process jax.distributed integration test.

Each child is one "host" of a 2-process CPU world (2 virtual devices per
process -> a 4-device global mesh), formed exactly the way a TPU pod slice
forms its world: ``jax.distributed.initialize`` via ``world_setup``.  This
is the role one ``mpiexec`` rank plays for the reference
(dataParallelTraining_NN_MPI.py:61-63) — but exercised for real, across OS
processes, unlike the single-process degrade mode the rest of the suite
uses.

Covers: world formation, barrier, broadcast_host_array, per-host data
loading into a global mesh, a jitted DP train step over the 2-host mesh,
replica-consistency assertion, an orbax shard-parallel checkpoint
save + restore round trip, and cross-host SP (ring-attention ppermute),
TP (partitioner all-reduces), and EP (MoE all_to_all) steps whose
collectives span the process boundary.

Usage: distributed_child.py <process_id> <num_processes> <port> <tmpdir>
Prints one JSON line with per-phase results.
"""

import json
import os
import sys


def main() -> int:
    pid, n, port, tmp = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                         sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import MLP
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        distributed,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh, world_setup,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    report = {"pid": pid}

    # ---- world formation (reference :61-63 / mpiexec) --------------------
    idx, cnt = world_setup(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=n, process_id=pid, timeout_s=60)
    report["process_index"] = idx
    report["process_count"] = cnt
    assert idx == pid and cnt == n, (idx, cnt)
    assert distributed.is_multi_host()

    # ---- barrier + host-array broadcast (reference :87/:97 bcast) --------
    distributed.barrier("smoke")
    src = np.arange(8, dtype=np.float64) * 3.5
    got = distributed.broadcast_host_array(
        src if idx == 0 else np.zeros_like(src))
    assert np.array_equal(np.asarray(got), src), got
    report["broadcast_ok"] = True

    # ---- global mesh over both hosts' devices ----------------------------
    devices = jax.devices()
    assert len(devices) == 2 * n, devices
    mesh = make_mesh(MeshConfig(data=2 * n), devices=devices)

    # ---- per-host data loading: each host materializes only its rows -----
    # (unlike the reference, which materializes everything on rank 0, :72)
    rng = np.random.default_rng(0)  # same seed -> same global dataset
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
         + 0.1).astype(np.float32)
    batch = shd.shard_batch(mesh, {
        "x": x, "y": y, "mask": np.ones((32,), np.float32)})

    # ---- jitted SPMD train step over the 2-host mesh ---------------------
    model = MLP(4, (8,), 1)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    state = TrainState.create(model, opt, prng.init_key(0))
    state = dp.replicate_state(state, mesh)
    step = dp.make_train_step(model, opt, mesh, "mse", "global_mean")
    losses = []
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(jax.device_get(loss)))
    report["losses"] = [round(v, 8) for v in losses]
    assert losses[-1] < losses[0], losses  # actually training

    # ---- replica consistency across hosts --------------------------------
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        consistency,
    )

    consistency.assert_replicated(state, what="2-host state")
    report["replicas_ok"] = True

    # ---- checkpoint round trip (orbax shard-parallel for multi-host) -----
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        checkpoint as ckpt,
    )

    ckpt_dir = os.path.join(tmp, "ckpt")
    ckpt.save(ckpt_dir, state)
    distributed.barrier("after-save")
    restored = ckpt.restore(ckpt_dir, state)
    assert restored is not None
    p0 = jax.device_get(jax.tree_util.tree_leaves(state.params)[0])
    r0 = jax.device_get(jax.tree_util.tree_leaves(restored.params)[0])
    assert np.array_equal(np.asarray(p0), np.asarray(r0))
    report["checkpoint_ok"] = True

    # ---- cross-host sequence parallelism: ring attention whose ppermute
    # hops cross the process boundary (the 'seq' axis pairs device k of
    # host 0 with device k of host 1 via an interleaved device order) ----
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import spmd

    inter = np.asarray(devices).reshape(n, 2).T.reshape(-1)  # seq spans hosts
    mesh_sp = make_mesh(MeshConfig(data=2, seq=n), devices=inter)
    seq_len = 16 * n
    model_sp = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=seq_len, n_layers=2, d_model=32,
        n_heads=4, d_ff=64, attention="ring"))
    tok = np.random.default_rng(1).integers(0, 64, (4, seq_len + 1))
    sp_batch = {"x": tok[:, :-1].astype(np.int32),
                "y": tok[:, 1:].astype(np.int32),
                "mask": np.ones((4,), np.float32)}
    state_sp = TrainState.create(model_sp, opt, prng.init_key(0))
    _, loss_sp = spmd.run_one_step(model_sp, opt, mesh_sp, state_sp,
                                   sp_batch, loss_name="cross_entropy")
    report["sp_loss"] = round(float(jax.device_get(loss_sp)), 8)
    assert np.isfinite(report["sp_loss"]), report["sp_loss"]
    report["sp_ok"] = True

    # ---- cross-host tensor parallelism: GSPMD Megatron sharding with the
    # 'tensor' axis spanning the hosts — the partitioner's all-reduces run
    # over the distributed backend ------------------------------------------
    from neural_networks_parallel_training_with_mpi_tpu.parallel import gspmd

    mesh_tp = make_mesh(MeshConfig(data=2, tensor=n), devices=inter)
    model_tp = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=16, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention="dense"))
    opt_tp = optim.adam(lr=1e-3)
    state_tp = TrainState.create(model_tp, opt_tp, prng.init_key(0))
    state_tp = gspmd.shard_state(model_tp, state_tp, opt_tp, mesh_tp)
    tok2 = np.random.default_rng(2).integers(0, 64, (4, 17))
    batch_tp = gspmd.shard_batch(mesh_tp, {
        "x": tok2[:, :-1].astype(np.int32),
        "y": tok2[:, 1:].astype(np.int32),
        "mask": np.ones((4,), np.float32)})
    step_tp = gspmd.make_gspmd_train_step(model_tp, opt_tp, mesh_tp,
                                          "cross_entropy",
                                          example_batch=batch_tp,
                                          donate=False)
    _, loss_tp = step_tp(state_tp, batch_tp)
    report["tp_loss"] = round(float(jax.device_get(loss_tp)), 8)
    assert np.isfinite(report["tp_loss"]), report["tp_loss"]
    report["tp_ok"] = True

    # ---- cross-host expert parallelism: the MoE all_to_all slot exchange
    # crosses the process boundary (the 'expert' axis pairs device k of
    # host 0 with device k of host 1, same interleaved order as seq/tp) --
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        expert as ep_lib,
    )

    mesh_ep = make_mesh(MeshConfig(data=2, expert=n), devices=inter)
    model_ep = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=16, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention="dense", moe_experts=2 * n,
        moe_expert_axis="expert"))
    tok3 = np.random.default_rng(3).integers(0, 64, (4 * n, 17))
    ep_batch = {"x": tok3[:, :-1].astype(np.int32),
                "y": tok3[:, 1:].astype(np.int32),
                "mask": np.ones((4 * n,), np.float32)}
    _, metrics_ep = ep_lib.run_one_step(model_ep, optim.adam(lr=1e-3),
                                        mesh_ep, ep_batch,
                                        prng.init_key(0))
    report["ep_loss"] = round(float(jax.device_get(metrics_ep["loss"])), 8)
    assert np.isfinite(report["ep_loss"]), report["ep_loss"]
    report["ep_ok"] = True

    distributed.barrier("done")
    report["ok"] = True
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
