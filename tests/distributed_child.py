"""Child process for the 2-process jax.distributed integration test.

Each child is one "host" of a 2-process CPU world (2 virtual devices per
process -> a 4-device global mesh), formed exactly the way a TPU pod slice
forms its world: ``jax.distributed.initialize`` via ``world_setup``.  This
is the role one ``mpiexec`` rank plays for the reference
(dataParallelTraining_NN_MPI.py:61-63) — but exercised for real, across OS
processes, unlike the single-process degrade mode the rest of the suite
uses.

Covers: world formation, barrier, broadcast_host_array, per-host data
loading into a global mesh, a jitted DP train step over the 2-host mesh,
replica-consistency assertion, the SDC sweep (detect -> localize -> heal
on an injected bitflip: both the local-shard and the cross-host digest
verdicts, DESIGN.md §9), an orbax shard-parallel checkpoint
save + restore round trip, and cross-host SP (ring-attention ppermute),
TP (partitioner all-reduces), and EP (MoE all_to_all) steps whose
collectives span the process boundary.

Usage: distributed_child.py <process_id> <num_processes> <port> <tmpdir>
Prints one JSON line with per-phase results.
"""

import json
import os
import sys


def main() -> int:
    pid, n, port, tmp = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                         sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import MLP
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        distributed,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh, world_setup,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    report = {"pid": pid}

    # ---- world formation (reference :61-63 / mpiexec) --------------------
    idx, cnt = world_setup(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=n, process_id=pid, timeout_s=60)
    report["process_index"] = idx
    report["process_count"] = cnt
    assert idx == pid and cnt == n, (idx, cnt)
    assert distributed.is_multi_host()

    # ---- barrier + host-array broadcast (reference :87/:97 bcast) --------
    distributed.barrier("smoke")
    src = np.arange(8, dtype=np.float64) * 3.5
    got = distributed.broadcast_host_array(
        src if idx == 0 else np.zeros_like(src))
    assert np.array_equal(np.asarray(got), src), got
    report["broadcast_ok"] = True

    # ---- global mesh over both hosts' devices ----------------------------
    devices = jax.devices()
    assert len(devices) == 2 * n, devices
    mesh = make_mesh(MeshConfig(data=2 * n), devices=devices)

    # ---- per-host data loading: each host materializes only its rows -----
    # (unlike the reference, which materializes everything on rank 0, :72)
    rng = np.random.default_rng(0)  # same seed -> same global dataset
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
         + 0.1).astype(np.float32)
    batch = shd.shard_batch(mesh, {
        "x": x, "y": y, "mask": np.ones((32,), np.float32)})

    # ---- jitted SPMD train step over the 2-host mesh ---------------------
    model = MLP(4, (8,), 1)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    state = TrainState.create(model, opt, prng.init_key(0))
    state = dp.replicate_state(state, mesh)
    step = dp.make_train_step(model, opt, mesh, "mse", "global_mean")
    losses = []
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(jax.device_get(loss)))
    report["losses"] = [round(v, 8) for v in losses]
    assert losses[-1] < losses[0], losses  # actually training

    # ---- replica consistency across hosts --------------------------------
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        consistency,
    )

    consistency.assert_replicated(state, what="2-host state")
    report["replicas_ok"] = True

    # ---- SDC sweep: detect -> localize on an injected bitflip ------------
    # (DESIGN.md §9) — not just the healthy-path assert_replicated.  Both
    # the fingerprint gather and the leaf-digest sweep are collectives, so
    # every phase below runs on BOTH processes with the corruption
    # injected on process 1 only.
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        faults,
    )

    fpr = consistency.Fingerprinter(state, mesh)
    assert fpr.n_leaves > 0
    target = state.params
    flat, _ = jax.tree_util.tree_flatten_with_path(target)
    leaf_name = jax.tree_util.keystr(flat[0][0])

    def with_flip(leaf_fn):
        new_flat = [leaf_fn(leaf) if jax.tree_util.keystr(p) == leaf_name
                    else leaf for p, leaf in flat]
        treedef = jax.tree_util.tree_flatten(target)[1]
        return state._replace(
            params=jax.tree_util.tree_unflatten(treedef, new_flat))

    # phase A: flip one bit in process 1's LOCAL shard 1 -> process 1's
    # devices disagree internally; the gathered digest matrix convicts
    # process 1 ("local"), and process 1's divergence_report names the
    # shard while process 0's stays clean
    bad = (with_flip(lambda l: faults.flip_bit_in_shard(l, 1, 9))
           if idx == 1 else state)
    digests, _folds = consistency.Fingerprinter.fetch(fpr.compute(bad))
    mat = np.asarray(distributed.allgather_host_array(digests))
    verdict = consistency.digest_report(mat)
    assert verdict.get("local") == [1] and verdict.get("cross") == [], (
        verdict)
    local_rep = consistency.divergence_report(bad)
    if idx == 1:
        assert list(local_rep) and local_rep[next(iter(local_rep))][
            "shards"] == [1], local_rep
        healed, _ = consistency.heal_replication(bad, local_rep)
        assert consistency.check_replicas(healed) == {}
    else:
        assert local_rep == {}, local_rep
    report["sdc_local_ok"] = True

    # phase B: flip the SAME bit in BOTH of process 1's shards -> each
    # host internally consistent but the hosts disagree: the digest
    # matrix says "cross", and the leaf-digest sweep names the leaf and
    # the diverging process on EVERY host (the symmetric report the
    # trainer's rollback-heal path branches on)
    bad2 = (with_flip(lambda l: faults.flip_bit_in_shard(
        faults.flip_bit_in_shard(l, 0, 9), 1, 9)) if idx == 1 else state)
    digests2, _ = consistency.Fingerprinter.fetch(fpr.compute(bad2))
    mat2 = np.asarray(distributed.allgather_host_array(digests2))
    verdict2 = consistency.digest_report(mat2)
    assert verdict2.get("cross") == [1] and verdict2.get("local") == [], (
        verdict2)
    assert consistency.divergence_report(bad2) == {}  # locally lockstep
    sweep = distributed.cross_host_report(consistency.leaf_digests(bad2))
    assert sweep, "cross-host sweep missed the diverged leaf"
    assert any(leaf_name in k for k in sweep), (leaf_name, sweep)
    assert all(v["processes"] == [1] for v in sweep.values()), sweep
    report["sdc_cross_ok"] = True

    # ---- checkpoint round trip (orbax shard-parallel for multi-host) -----
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        checkpoint as ckpt,
    )

    ckpt_dir = os.path.join(tmp, "ckpt")
    ckpt.save(ckpt_dir, state)
    distributed.barrier("after-save")
    restored = ckpt.restore(ckpt_dir, state)
    assert restored is not None
    p0 = jax.device_get(jax.tree_util.tree_leaves(state.params)[0])
    r0 = jax.device_get(jax.tree_util.tree_leaves(restored.params)[0])
    assert np.array_equal(np.asarray(p0), np.asarray(r0))
    report["checkpoint_ok"] = True

    # ---- cross-host sequence parallelism: ring attention whose ppermute
    # hops cross the process boundary (the 'seq' axis pairs device k of
    # host 0 with device k of host 1 via an interleaved device order) ----
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import spmd

    inter = np.asarray(devices).reshape(n, 2).T.reshape(-1)  # seq spans hosts
    mesh_sp = make_mesh(MeshConfig(data=2, seq=n), devices=inter)
    seq_len = 16 * n
    model_sp = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=seq_len, n_layers=2, d_model=32,
        n_heads=4, d_ff=64, attention="ring"))
    tok = np.random.default_rng(1).integers(0, 64, (4, seq_len + 1))
    sp_batch = {"x": tok[:, :-1].astype(np.int32),
                "y": tok[:, 1:].astype(np.int32),
                "mask": np.ones((4,), np.float32)}
    state_sp = TrainState.create(model_sp, opt, prng.init_key(0))
    _, loss_sp = spmd.run_one_step(model_sp, opt, mesh_sp, state_sp,
                                   sp_batch, loss_name="cross_entropy")
    report["sp_loss"] = round(float(jax.device_get(loss_sp)), 8)
    assert np.isfinite(report["sp_loss"]), report["sp_loss"]
    report["sp_ok"] = True

    # ---- cross-host tensor parallelism: GSPMD Megatron sharding with the
    # 'tensor' axis spanning the hosts — the partitioner's all-reduces run
    # over the distributed backend ------------------------------------------
    from neural_networks_parallel_training_with_mpi_tpu.parallel import gspmd

    mesh_tp = make_mesh(MeshConfig(data=2, tensor=n), devices=inter)
    model_tp = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=16, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention="dense"))
    opt_tp = optim.adam(lr=1e-3)
    state_tp = TrainState.create(model_tp, opt_tp, prng.init_key(0))
    state_tp = gspmd.shard_state(model_tp, state_tp, opt_tp, mesh_tp)
    tok2 = np.random.default_rng(2).integers(0, 64, (4, 17))
    batch_tp = gspmd.shard_batch(mesh_tp, {
        "x": tok2[:, :-1].astype(np.int32),
        "y": tok2[:, 1:].astype(np.int32),
        "mask": np.ones((4,), np.float32)})
    step_tp = gspmd.make_gspmd_train_step(model_tp, opt_tp, mesh_tp,
                                          "cross_entropy",
                                          example_batch=batch_tp,
                                          donate=False)
    _, loss_tp = step_tp(state_tp, batch_tp)
    report["tp_loss"] = round(float(jax.device_get(loss_tp)), 8)
    assert np.isfinite(report["tp_loss"]), report["tp_loss"]
    report["tp_ok"] = True

    # ---- cross-host expert parallelism: the MoE all_to_all slot exchange
    # crosses the process boundary (the 'expert' axis pairs device k of
    # host 0 with device k of host 1, same interleaved order as seq/tp) --
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        expert as ep_lib,
    )

    mesh_ep = make_mesh(MeshConfig(data=2, expert=n), devices=inter)
    model_ep = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=16, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention="dense", moe_experts=2 * n,
        moe_expert_axis="expert"))
    tok3 = np.random.default_rng(3).integers(0, 64, (4 * n, 17))
    ep_batch = {"x": tok3[:, :-1].astype(np.int32),
                "y": tok3[:, 1:].astype(np.int32),
                "mask": np.ones((4 * n,), np.float32)}
    _, metrics_ep = ep_lib.run_one_step(model_ep, optim.adam(lr=1e-3),
                                        mesh_ep, ep_batch,
                                        prng.init_key(0))
    report["ep_loss"] = round(float(jax.device_get(metrics_ep["loss"])), 8)
    assert np.isfinite(report["ep_loss"]), report["ep_loss"]
    report["ep_ok"] = True

    distributed.barrier("done")
    report["ok"] = True
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
