"""Test harness: 8 virtual CPU devices, no TPU.

The SPMD logic is tested against fake CPU devices
(``--xla_force_host_platform_device_count=8``) exactly as SURVEY.md §4
prescribes — this plays the role ``mpiexec -n N`` plays for the reference on
a laptop (reference README.md:10-12).

Note: this image's sitecustomize registers an 'axon' TPU-tunnel backend and
force-updates ``jax_platforms`` to "axon,cpu" at interpreter start; we must
(a) point XLA_FLAGS at 8 host devices and (b) re-update the config to pure
cpu *before* any JAX backend initialization, or every test process would
claim the (exclusive, single-chip) TPU tunnel.
"""

import os

_N_DEVICES = 8

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEVICES}"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# keep any axon PJRT plugin from being touched in test workers (stash the
# tunnel config so the opt-in TPU smoke test can restore it in a child)
_axon_ips = os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if _axon_ips is not None:
    os.environ["_SAVED_PALLAS_AXON_POOL_IPS"] = _axon_ips

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices("cpu")
    assert len(devs) >= _N_DEVICES, (
        f"expected {_N_DEVICES} virtual CPU devices, got {len(devs)}"
    )
    return devs[:_N_DEVICES]


@pytest.fixture(scope="session")
def mesh8(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig

    return make_mesh(MeshConfig(data=8), devices=devices)


@pytest.fixture(scope="session")
def mesh1(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig

    return make_mesh(MeshConfig(data=1), devices=devices[:1])
