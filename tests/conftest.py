"""Test harness: 8 virtual CPU devices, no TPU.

The SPMD logic is tested against fake CPU devices
(``--xla_force_host_platform_device_count=8``) exactly as SURVEY.md §4
prescribes — this plays the role ``mpiexec -n N`` plays for the reference on
a laptop (reference README.md:10-12).

Note: this image's sitecustomize registers an 'axon' TPU-tunnel backend and
force-updates ``jax_platforms`` to "axon,cpu" at interpreter start; we must
(a) point XLA_FLAGS at 8 host devices and (b) re-update the config to pure
cpu *before* any JAX backend initialization, or every test process would
claim the (exclusive, single-chip) TPU tunnel.
"""

import os

_N_DEVICES = 8

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEVICES}"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# keep any axon PJRT plugin from being touched in test workers (stash the
# tunnel config so the opt-in TPU smoke test can restore it in a child)
_axon_ips = os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if _axon_ips is not None:
    os.environ["_SAVED_PALLAS_AXON_POOL_IPS"] = _axon_ips

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402

# Core-lane wall-clock budget (VERDICT r4 item 8: the lane doubled from ~5
# to ~10 min in one round with no brake).  Every `-m "not slow"` session
# appends its duration to .lane_times.jsonl and FAILS the run if it blew
# the budget — growth now breaks CI loudly instead of compounding
# silently.  Heavyweight additions belong in the full lane (@slow).
CORE_LANE_BUDGET_S = 600.0
_session_t0 = None


def pytest_sessionstart(session):
    global _session_t0
    import time as _time

    _session_t0 = _time.time()


def pytest_sessionfinish(session, exitstatus):
    import json as _json
    import time as _time

    if _session_t0 is None:
        return
    marker = session.config.getoption("-m", default="") or ""
    if "not slow" not in marker:
        return  # full lane / targeted runs are unbudgeted
    elapsed = _time.time() - _session_t0
    n = session.testscollected
    rec = {"t_iso": _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime()),
           "seconds": round(elapsed, 1), "tests": n,
           "budget_s": CORE_LANE_BUDGET_S,
           "over_budget": elapsed > CORE_LANE_BUDGET_S}
    try:
        with open(os.path.join(os.path.dirname(__file__), "..",
                               ".lane_times.jsonl"), "a") as f:
            f.write(_json.dumps(rec) + "\n")
    except OSError:
        pass
    if elapsed > CORE_LANE_BUDGET_S and n > 100:
        # n > 100 guards against budget-failing a filtered subset run
        # that happens to pass -m "not slow"
        session.exitstatus = 1
        print(f"\nCORE LANE OVER BUDGET: {elapsed:.0f}s > "
              f"{CORE_LANE_BUDGET_S:.0f}s — move the heaviest new tests "
              f"to the full lane (@pytest.mark.slow)", flush=True)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices("cpu")
    assert len(devs) >= _N_DEVICES, (
        f"expected {_N_DEVICES} virtual CPU devices, got {len(devs)}"
    )
    return devs[:_N_DEVICES]


@pytest.fixture(scope="session")
def mesh8(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig

    return make_mesh(MeshConfig(data=8), devices=devices)


@pytest.fixture(scope="session")
def mesh1(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig

    return make_mesh(MeshConfig(data=1), devices=devices[:1])
