"""Test harness: 8 virtual CPU devices, no TPU.

The SPMD logic is tested against fake CPU devices
(``--xla_force_host_platform_device_count=8``) exactly as SURVEY.md §4
prescribes — this plays the role ``mpiexec -n N`` plays for the reference on
a laptop (reference README.md:10-12).

Note: this image's sitecustomize registers an 'axon' TPU-tunnel backend and
force-updates ``jax_platforms`` to "axon,cpu" at interpreter start; we must
(a) point XLA_FLAGS at 8 host devices and (b) re-update the config to pure
cpu *before* any JAX backend initialization, or every test process would
claim the (exclusive, single-chip) TPU tunnel.
"""

import os

_N_DEVICES = 8

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEVICES}"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# keep any axon PJRT plugin from being touched in test workers (stash the
# tunnel config so the opt-in TPU smoke test can restore it in a child)
_axon_ips = os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if _axon_ips is not None:
    os.environ["_SAVED_PALLAS_AXON_POOL_IPS"] = _axon_ips

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402

# Core-lane wall-clock budget (VERDICT r4 item 8: the lane doubled from ~5
# to ~10 min in one round with no brake).  Every `-m "not slow"` session
# appends its duration to .lane_times.jsonl.  A single over-budget run only
# WARNS (ADVICE r5: a green run on a temporarily slow/shared machine must
# not exit 1 on elapsed time alone); the run FAILS only when it also blows
# the machine's own rolling median by a wide margin — i.e. the lane itself
# grew, not the host slowed down.  Heavyweight additions belong in the full
# lane (@slow).
CORE_LANE_BUDGET_S = 600.0
# fail threshold: max(budget, this factor x median of recent recorded runs)
CORE_LANE_MEDIAN_FACTOR = 1.4
_LANE_TIMES = os.path.join(os.path.dirname(__file__), "..",
                           ".lane_times.jsonl")
_session_t0 = None


def pytest_sessionstart(session):
    global _session_t0
    import time as _time

    _session_t0 = _time.time()


def _lane_median(n_recent: int = 10):
    """Median duration of the last ``n_recent`` recorded UNDER-BUDGET full
    core-lane runs (None when there is no usable history).  Two filters
    keep the baseline honest: subset runs (tests <= 100) must not drag it
    down, and over-budget runs must not ratchet it up — otherwise steady
    lane growth would raise its own fail threshold forever and the brake
    (VERDICT r4 item 8) would never engage.  The baseline therefore
    freezes at this machine's last healthy level: growth is bounded at
    CORE_LANE_MEDIAN_FACTOR x that."""
    import json as _json
    import statistics as _stats

    try:
        with open(_LANE_TIMES) as f:
            secs = [r["seconds"] for r in map(_json.loads, f)
                    if isinstance(r.get("seconds"), (int, float))
                    and r.get("tests", 0) > 100
                    and not r.get("over_budget")]
    except (OSError, ValueError):
        return None
    return _stats.median(secs[-n_recent:]) if secs else None


def _lane_rate_median(n_recent: int = 10):
    """Median seconds-PER-TEST over the last ``n_recent`` full core-lane
    runs of ANY status (None without history).  Complements
    :func:`_lane_median`: the absolute median freezes at the last healthy
    level (so growth cannot ratchet it), but on this shared single-core
    host the per-test rate swings 1.2-2.2x with ambient load on IDENTICAL
    code (.lane_times.jsonl r7: half the day's runs were over-budget
    before any lane change) — a run in a loaded window would blow the
    absolute threshold with zero lane growth, the exact "green run on a
    temporarily slow machine" ADVICE r5 says must not exit 1.  Including
    over-budget runs here is deliberate: load moves the rate, lane SIZE
    does not, so this baseline adapts to the machine while staying
    size-independent.  Runs under 60s are aborted/degenerate sessions,
    not rate evidence."""
    import json as _json
    import statistics as _stats

    try:
        with open(_LANE_TIMES) as f:
            rates = [r["seconds"] / r["tests"] for r in map(_json.loads, f)
                     if isinstance(r.get("seconds"), (int, float))
                     and r.get("tests", 0) > 100
                     and r["seconds"] >= 60.0]
    except (OSError, ValueError):
        return None
    return _stats.median(rates[-n_recent:]) if rates else None


def pytest_sessionfinish(session, exitstatus):
    import json as _json
    import time as _time

    if _session_t0 is None:
        return
    marker = session.config.getoption("-m", default="") or ""
    if "not slow" not in marker:
        return  # full lane / targeted runs are unbudgeted
    elapsed = _time.time() - _session_t0
    n = session.testscollected
    median = _lane_median()
    # headroom over THIS machine's recent history; without history the
    # budget alone can only warn (a slow machine's first run must not fail)
    fail_at = (max(CORE_LANE_BUDGET_S, CORE_LANE_MEDIAN_FACTOR * median)
               if median is not None else None)
    rec = {"t_iso": _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime()),
           "seconds": round(elapsed, 1), "tests": n,
           "budget_s": CORE_LANE_BUDGET_S,
           "median_s": round(median, 1) if median is not None else None,
           "over_budget": elapsed > CORE_LANE_BUDGET_S}
    try:
        with open(_LANE_TIMES, "a") as f:
            f.write(_json.dumps(rec) + "\n")
    except OSError:
        pass
    if elapsed > CORE_LANE_BUDGET_S and n > 100:
        # n > 100 guards against budget-failing a filtered subset run
        # that happens to pass -m "not slow"
        rate_median = _lane_rate_median()
        # the HARD fail needs evidence the LANE grew, not just that this
        # window's host load was high: the absolute threshold (frozen
        # healthy-median x factor) AND the size-independent per-test
        # rate vs this machine's load-inclusive recent rate.  A loaded
        # window inflates both elapsed and the rate of the UNCHANGED
        # lane identically, so the rate ratio stays ~1 and the run warns
        # instead of failing (ADVICE r5); a genuinely heavier lane
        # raises the rate above its own recent history and still fails.
        rate_grew = (rate_median is None
                     or elapsed / n > CORE_LANE_MEDIAN_FACTOR * rate_median)
        # the rate gate is size-independent, so growth by ADDING
        # average-cost tests could otherwise warn forever — the hard
        # ceiling (2x budget) is the wall-clock bound no load excuse
        # waives
        if elapsed > 2 * CORE_LANE_BUDGET_S:
            rate_grew = True
        if fail_at is not None and elapsed > fail_at and rate_grew:
            session.exitstatus = 1
            print(f"\nCORE LANE OVER BUDGET: {elapsed:.0f}s > "
                  f"{CORE_LANE_BUDGET_S:.0f}s budget AND > "
                  f"{fail_at:.0f}s ({CORE_LANE_MEDIAN_FACTOR}x this "
                  f"machine's {median:.0f}s rolling median), with the "
                  f"per-test rate ({elapsed / n:.2f}s) above "
                  f"{CORE_LANE_MEDIAN_FACTOR}x its recent median — the "
                  "lane grew; move the heaviest new tests to the full "
                  "lane (@pytest.mark.slow)", flush=True)
        elif fail_at is not None and elapsed > fail_at:
            print(f"\nWARNING: core lane over budget ({elapsed:.0f}s > "
                  f"{fail_at:.0f}s fail threshold) but the per-test rate "
                  f"({elapsed / n:.2f}s/test) is within "
                  f"{CORE_LANE_MEDIAN_FACTOR}x this machine's recent "
                  f"rate median ({rate_median:.2f}s/test) — host load, "
                  "not lane growth; not failing the run", flush=True)
        elif median is not None:
            print(f"\nWARNING: core lane over budget ({elapsed:.0f}s > "
                  f"{CORE_LANE_BUDGET_S:.0f}s) but within this machine's "
                  f"rolling-median headroom (median {median:.0f}s) — not "
                  "failing the run", flush=True)
        else:
            print(f"\nWARNING: core lane over budget ({elapsed:.0f}s > "
                  f"{CORE_LANE_BUDGET_S:.0f}s); no .lane_times.jsonl "
                  "history yet — not failing the run", flush=True)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices("cpu")
    assert len(devs) >= _N_DEVICES, (
        f"expected {_N_DEVICES} virtual CPU devices, got {len(devs)}"
    )
    return devs[:_N_DEVICES]


@pytest.fixture(scope="session")
def mesh8(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig

    return make_mesh(MeshConfig(data=8), devices=devices)


@pytest.fixture(scope="session")
def mesh1(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig

    return make_mesh(MeshConfig(data=1), devices=devices[:1])
