"""Rotary position embeddings (ops.rope + TransformerConfig.pos_encoding).

The defining property: after rotating q by R(m) and k by R(n), the score
q·k depends only on m − n.  Everything else follows from where the
rotation is applied — inside sequence_sharded_attention (so every
attention impl and SP layout inherits it with each shard rotating by its
GLOBAL positions) and in the decode chunk (rotated keys are cached)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops.rope import (
    rope_rotate,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
    make_mesh,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

T, VOCAB = 32, 64


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=T, n_layers=2, d_model=32,
                n_heads=4, d_ff=64, pos_encoding="rope")
    base.update(kw)
    return TransformerConfig(**base)


def test_relative_position_invariance():
    """(R(m) q) . (R(n) k) == (R(m+s) q) . (R(n+s) k) for any shift s —
    the property that makes RoPE a position encoding at all."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 8, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 8, 2, 16)), jnp.float32)

    def scores(shift):
        pos = jnp.arange(8) + shift
        qr = rope_rotate(q, pos)
        kr = rope_rotate(k, pos)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(11)),
                               rtol=1e-4, atol=1e-4)


def test_rotation_preserves_norm_and_identity_at_zero():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 6, 3, 8)), jnp.float32)
    r = rope_rotate(x, jnp.arange(6))
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(r), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5, atol=1e-5)
    # position 0 rotates by angle 0 everywhere
    np.testing.assert_allclose(np.asarray(r[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-6, atol=1e-6)
    with pytest.raises(ValueError, match="even head_dim"):
        rope_rotate(x[..., :7], jnp.arange(6))


def test_rope_model_has_no_pos_params_and_trains():
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )

    model = Transformer(_cfg())
    params = model.init(prng.init_key(0))
    assert "pos" not in params
    mesh = make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    opt = optim.sgd(lr=1e-2, momentum=0.0)
    state = dp.replicate_state(TrainState.create(model, opt,
                                                 prng.init_key(0)), mesh)
    step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                              "global_mean")
    rng = np.random.default_rng(0)
    batch = shd.shard_batch(mesh, {
        "x": rng.integers(0, VOCAB, (4, T)).astype(np.int32),
        "y": rng.integers(0, VOCAB, (4, T)).astype(np.int32),
        "mask": np.ones((4,), np.float32)})
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))


def test_rope_is_position_sensitive():
    """Same tokens at different offsets must produce different logits —
    i.e. the rotation really is the position signal (a bug that silently
    dropped it would still pass parity tests)."""
    model = Transformer(_cfg(n_layers=1))
    params = model.init(prng.init_key(0))
    ids = jnp.asarray([[5, 9, 5, 9, 5, 9, 5, 9]], jnp.int32)
    logits = model.apply(params, ids)
    # token 5 at positions 0, 2, 4: attends over different prefixes AND
    # different rotations; if positions were ignored its logits would
    # repeat once the prefix content repeats
    assert not np.allclose(np.asarray(logits[0, 2]),
                           np.asarray(logits[0, 4]), atol=1e-5)


@pytest.mark.parametrize("attention", ["dense", "flash"])
def test_rope_flash_matches_dense(attention):
    """The rotation happens before the impl dispatch, so flash == dense
    on a RoPE model to kernel tolerance."""
    params = Transformer(_cfg()).init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, T)),
                      jnp.int32)
    want = Transformer(_cfg(attention="dense")).apply(params, ids)
    got = Transformer(_cfg(attention=attention)).apply(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_rope_ring_seq_parallel_matches_dense():
    """Each seq shard rotates by its GLOBAL positions (via
    global_positions inside sequence_sharded_attention), so the ring
    model under a seq=4 mesh must equal the dense single-device model."""
    mesh = make_mesh(MeshConfig(data=1, seq=4),
                     devices=jax.devices("cpu")[:4])
    params = Transformer(_cfg()).init(prng.init_key(0))
    ids = np.random.default_rng(0).integers(0, VOCAB, (2, T)).astype(
        np.int32)
    expected = Transformer(_cfg(attention="dense")).apply(
        params, jnp.asarray(ids))
    ring_model = Transformer(_cfg(attention="ring"))
    got = jax.jit(jax.shard_map(
        lambda p, i: ring_model.apply(p, i),
        mesh=mesh, in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_rope_decode_matches_training_forward():
    """The KV-cache path caches ROTATED keys; its prefill logits must
    match the training forward position-for-position, and the
    prefill+scan decode must equal the fully-sequential ragged path
    (same rotations at the same absolute positions)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        _forward_chunk, init_kv_cache,
    )

    model = Transformer(_cfg())
    params = model.init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, 8)),
                      jnp.int32)
    train_logits = model.apply(params, ids)
    cache_logits, _ = _forward_chunk(model, params,
                                     init_kv_cache(model, 2, 8), ids, 0)
    np.testing.assert_allclose(np.asarray(cache_logits),
                               np.asarray(train_logits),
                               rtol=2e-4, atol=2e-4)

    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    fast = generate(model, params, prompt, 10)
    seq = generate(model, params, prompt, 10,
                   prompt_lens=jnp.asarray([3], jnp.int32))
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(seq))


@pytest.mark.slow
def test_rope_composes_with_gqa_kv8_and_server():
    """The full modern-LM stack: RoPE x GQA x int8 weights x int8 KV
    through the continuous-batching server, token-equal to the
    single-stream decode of the same quantized model."""
    from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
        DecodeServer,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    model = Transformer(_cfg(n_kv_heads=2))
    q = quantize_params(model.init(prng.init_key(0)))
    srv = DecodeServer(model, q, slots=2, kv_quant=True)
    rid = srv.submit([1, 2, 3], max_new_tokens=8)
    while not srv.done(rid):
        srv.step()
    want = generate(model, q, jnp.asarray([[1, 2, 3]], jnp.int32), 8,
                    kv_quant=True)
    assert srv.result(rid) == [int(t) for t in np.asarray(want)[0]]


def test_rope_tp_validates():
    """RoPE passes TP validation on every attention impl (round 4: the
    dense branch rotates inside tp_block_apply; seq-sharded impls rotate
    inside their sequence_sharded_attention closures)."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    megatron.validate_tp(_cfg(attention="dense"), tp=2)
    megatron.validate_tp(_cfg(attention="flash"), tp=2)
    megatron.validate_tp(
        TransformerConfig(d_model=32, n_heads=4, d_ff=64), tp=2)


@pytest.mark.slow
def test_rope_pp_tp_trainer_matches_dp():
    """RoPE through the REAL pipe x tensor path (dense attention inside
    tp_block_apply rotates q/k by arange(t) on its local heads): the
    full training trajectory must match plain DP on the same RoPE
    model — a double- or missing rotation diverges at step 1."""
    import dataclasses

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    def cfg(**mesh_kw):
        return TrainConfig(
            nepochs=2, batch_size=32, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                            vocab_size=VOCAB),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=VOCAB,
                              max_seq_len=16, pos_encoding="rope"),
            mesh=MeshConfig(**mesh_kw))

    r_dp = Trainer(cfg(data=8)).fit()
    t_pt = Trainer(cfg(data=2, pipe=2, tensor=2))
    assert t_pt.pipeline
    r_pt = t_pt.fit()
    assert np.isfinite(r_pt["final_loss"])
    assert r_pt["final_loss"] == pytest.approx(r_dp["final_loss"],
                                               rel=2e-4)


def test_cli_pos_encoding_flag():
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        build_argparser, config_from_args,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.registry import (
        build_model,
    )

    args = build_argparser().parse_args(
        ["--dataset", "lm", "--pos_encoding", "rope"])
    model = build_model(config_from_args(args).model)
    assert model.cfg.pos_encoding == "rope"
    assert "pos" not in model.init(prng.init_key(0))


@pytest.mark.slow
def test_rope_pipeline_matches_single_device():
    """RoPE x pipeline: stage-0's embed must skip the (absent) position
    table — RoPE models carry none — and each stage's attention rotates
    q/k itself; the pipelined step must match the unpipelined reference
    step exactly (loss + updated params)."""
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.ops import (
        losses, optim,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        pipeline as pp,
    )

    mesh = make_mesh(MeshConfig(data=1, pipe=2),
                     devices=jax.devices("cpu")[:2])
    model = Transformer(_cfg(n_layers=4))
    opt = optim.sgd(lr=0.1, momentum=0.9)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, VOCAB, (4, T + 1))
    batch = {"x": tok[:, :-1].astype(np.int32),
             "y": tok[:, 1:].astype(np.int32),
             "mask": np.ones((4,), np.float32)}

    state, loss = pp.run_one_step(model, opt, mesh, batch,
                                  prng.init_key(0), n_microbatches=2)

    params = model.init(prng.init_key(0))
    assert "pos" not in params

    def scalar(p):
        logits = model.apply(p, jnp.asarray(batch["x"]))
        s, cnt = losses.softmax_cross_entropy(
            logits, jnp.asarray(batch["y"]), jnp.asarray(batch["mask"]))
        return s / cnt

    ref_loss, grads = jax.value_and_grad(scalar)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)


def test_swiglu_gate_is_tensor_sharded_on_gspmd():
    """transformer_rules must treat ff_gate like ff_in (column-parallel)
    on the GSPMD TP path — the path validate_tp's SwiGLU guard points
    users at."""
    from jax.sharding import PartitionSpec as P

    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        tensor_parallel as tp_rules,
    )

    mesh = make_mesh(MeshConfig(data=1, tensor=2),
                     devices=jax.devices("cpu")[:2])
    model = Transformer(_cfg(pos_encoding="learned", activation="swiglu",
                             d_ff=64))
    params = model.init(prng.init_key(0))
    specs = tp_rules.param_specs(model, params, mesh)
    blk = specs["blocks"][0]
    assert blk["ff_gate"]["w"] == blk["ff_in"]["w"] == P(None, "tensor")
    assert blk["ff_out"]["w"] == P("tensor", None)


def test_serve_rejects_empty_prompt():
    from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
        DecodeServer,
    )

    model = Transformer(_cfg())
    srv = DecodeServer(model, model.init(prng.init_key(0)), slots=2)
    with pytest.raises(ValueError, match="empty prompt"):
        srv.submit([], max_new_tokens=4)
