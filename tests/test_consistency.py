"""Replica-divergence detection (utils.consistency) — the explicit version
of the reference's implicit lockstep invariant (SURVEY.md §5.2,
dataParallelTraining_NN_MPI.py:206-211)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer
from neural_networks_parallel_training_with_mpi_tpu.utils import consistency


def test_healthy_replicated_state_passes(mesh8):
    tree = {"w": jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh8, P())),
            "b": jax.device_put(jnp.zeros((4,)), NamedSharding(mesh8, P()))}
    assert consistency.check_replicas(tree) == {}
    consistency.assert_replicated(tree)  # no raise


def test_sharded_leaves_are_skipped(mesh8):
    x = jax.device_put(jnp.arange(16.0).reshape(16, 1),
                       NamedSharding(mesh8, P(("data", "fsdp"))))
    # data-sharded leaf: shards legitimately differ; must not be flagged
    assert consistency.replica_divergence({"x": x}) == {}


def test_planted_divergence_is_caught(mesh8):
    # a shard_map body whose P() out_spec LIES about replication — exactly
    # the bug class this detector exists for (hidden by check_vma=False)
    liar = jax.jit(jax.shard_map(
        lambda: (jax.lax.axis_index("data").astype(jnp.float32)
                 * jnp.ones((2, 2))),
        mesh=mesh8, in_specs=(), out_specs=P(), check_vma=False))
    bad = liar()
    div = consistency.replica_divergence({"bad": bad})
    assert div["['bad']"] > 0
    with pytest.raises(AssertionError, match="replica divergence"):
        consistency.assert_replicated({"bad": bad})


def test_trainer_flag_runs_checks(mesh8, monkeypatch):
    cfg = TrainConfig(
        nepochs=1, batch_size=16, full_batch=False,
        check_replicas_every=1,
        data=DataConfig(dataset="regression", n_samples=64),
        mesh=MeshConfig(data=8),
    )
    calls = []
    real = consistency.assert_replicated
    monkeypatch.setattr(consistency, "assert_replicated",
                        lambda tree, **kw: calls.append(1) or real(tree, **kw))
    t = Trainer(cfg)
    result = t.fit()  # healthy run: checks pass silently
    assert np.isfinite(result["final_loss"])
    # the flag must actually fire once per step (bug class B1: parsed-but-
    # ignored flags are the reference's signature failure)
    assert len(calls) == result["steps"]


def test_bfloat16_divergence_reports_magnitude(mesh8):
    # bf16 leaves must take the floating branch: a small planted divergence
    # reports its actual magnitude, not inf
    liar = jax.jit(jax.shard_map(
        lambda: (jax.lax.axis_index("data").astype(jnp.bfloat16)
                 * jnp.full((2, 2), 0.125, jnp.bfloat16)),
        mesh=mesh8, in_specs=(), out_specs=P(), check_vma=False))
    div = consistency.replica_divergence({"bad": liar()})
    assert np.isfinite(div["['bad']"])
    assert div["['bad']"] == pytest.approx(0.875)  # 7 * 0.125
