"""Replica-divergence detection (utils.consistency) — the explicit version
of the reference's implicit lockstep invariant (SURVEY.md §5.2,
dataParallelTraining_NN_MPI.py:206-211)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer
from neural_networks_parallel_training_with_mpi_tpu.utils import consistency


def test_healthy_replicated_state_passes(mesh8):
    tree = {"w": jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh8, P())),
            "b": jax.device_put(jnp.zeros((4,)), NamedSharding(mesh8, P()))}
    assert consistency.check_replicas(tree) == {}
    consistency.assert_replicated(tree)  # no raise


def test_sharded_leaves_are_skipped(mesh8):
    x = jax.device_put(jnp.arange(16.0).reshape(16, 1),
                       NamedSharding(mesh8, P(("data", "fsdp"))))
    # data-sharded leaf: shards legitimately differ; must not be flagged
    assert consistency.replica_divergence({"x": x}) == {}


def test_planted_divergence_is_caught(mesh8):
    # a shard_map body whose P() out_spec LIES about replication — exactly
    # the bug class this detector exists for (hidden by check_vma=False)
    liar = jax.jit(jax.shard_map(
        lambda: (jax.lax.axis_index("data").astype(jnp.float32)
                 * jnp.ones((2, 2))),
        mesh=mesh8, in_specs=(), out_specs=P(), check_vma=False))
    bad = liar()
    div = consistency.replica_divergence({"bad": bad})
    assert div["['bad']"] > 0
    with pytest.raises(AssertionError, match="replica divergence"):
        consistency.assert_replicated({"bad": bad})


def test_trainer_flag_runs_checks(mesh8, monkeypatch):
    """--check_replicas_every now rides the SDC fingerprint path
    (DESIGN.md §9): one O(1) on-device digest per boundary, fetched at
    the lag-2 discipline, instead of the old host-side full-state fetch
    that drained the async pipeline."""
    cfg = TrainConfig(
        nepochs=1, batch_size=16, full_batch=False,
        check_replicas_every=1,
        data=DataConfig(dataset="regression", n_samples=64),
        mesh=MeshConfig(data=8),
    )
    computes, fetches = [], []
    real_compute = consistency.Fingerprinter.compute
    real_fetch = consistency.Fingerprinter.fetch
    monkeypatch.setattr(
        consistency.Fingerprinter, "compute",
        lambda self, tree: computes.append(1) or real_compute(self, tree))
    monkeypatch.setattr(
        consistency.Fingerprinter, "fetch",
        staticmethod(lambda fp: fetches.append(1) or real_fetch(fp)))
    t = Trainer(cfg)
    result = t.fit()  # healthy run: checks pass silently
    assert np.isfinite(result["final_loss"])
    # the flag must actually fire once per step (bug class B1: parsed-but-
    # ignored flags are the reference's signature failure), and every
    # queued fingerprint must be fetched (lag-2 + end-of-run drain)
    assert len(computes) == result["steps"]
    assert len(fetches) == result["steps"]
    assert result["sdc_incidents"] == 0


def test_nan_poisoned_replica_reported_diverged(mesh8):
    """Satellite regression: a NaN in the shard diff used to make
    ``np.max`` return NaN and ``max(worst, nan)`` keep 0.0 — a
    NaN-poisoned replica was reported HEALTHY and dropped by the
    ``v > atol`` filter.  It must report inf and be flagged."""
    liar = jax.jit(jax.shard_map(
        lambda: (jnp.where(jax.lax.axis_index("data") == 3,
                           jnp.float32(jnp.nan), jnp.float32(1.0))
                 * jnp.ones((2, 2))),
        mesh=mesh8, in_specs=(), out_specs=P(), check_vma=False))
    div = consistency.replica_divergence({"bad": liar()})
    assert div["['bad']"] == float("inf")
    assert consistency.check_replicas({"bad": liar()})  # not filtered out
    with pytest.raises(AssertionError, match="replica divergence"):
        consistency.assert_replicated({"bad": liar()})


def test_identically_nan_replicas_are_lockstep(mesh8):
    # every shard NaN at the same position: bit-for-bit lockstep, healthy
    bad = jax.device_put(jnp.full((2, 2), jnp.nan),
                         NamedSharding(mesh8, P()))
    assert consistency.check_replicas({"x": bad}) == {}


def test_one_host_copy_per_shard(mesh8, monkeypatch):
    """Satellite micro-test: replica_divergence fetches each shard to the
    host exactly once (the reference shard included — no re-fetch per
    comparison)."""
    tree = {"w": jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh8, P())),
            "b": jax.device_put(jnp.zeros((3,)), NamedSharding(mesh8, P()))}
    calls = []
    real = consistency._to_host
    monkeypatch.setattr(consistency, "_to_host",
                        lambda s: calls.append(1) or real(s))
    consistency.replica_divergence(tree)
    assert len(calls) == 2 * 8  # two leaves x eight shards, exactly


def test_bfloat16_divergence_reports_magnitude(mesh8):
    # bf16 leaves must take the floating branch: a small planted divergence
    # reports its actual magnitude, not inf
    liar = jax.jit(jax.shard_map(
        lambda: (jax.lax.axis_index("data").astype(jnp.bfloat16)
                 * jnp.full((2, 2), 0.125, jnp.bfloat16)),
        mesh=mesh8, in_specs=(), out_specs=P(), check_vma=False))
    div = consistency.replica_divergence({"bad": liar()})
    assert np.isfinite(div["['bad']"])
    assert div["['bad']"] == pytest.approx(0.875)  # 7 * 0.125
