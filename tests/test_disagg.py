"""Disaggregated prefill/decode handoff ledger (serve/fleet.py +
scheduler role seam, DESIGN.md §11).

Pins, by acceptance criterion — a request killed in EACH handoff state
recovers exactly once, with tokens byte-identical to the undisturbed
single-scheduler reference (greedy decode is deterministic, so any
duplicate or lost execution would show up as a token diff or a counter):

* **steady state**: every request through a 1-prefill + 2-decode pool
  commits exactly one handoff and matches the unified reference
  byte-for-byte; both roles' block allocators drain to zero refcounts.
* **killed BEFORE commit**: prefill dies mid-prefill — the router
  requeues to the surviving prefill replica (one requeue, one commit,
  no redecode) or, with no prefill pool left, serves unified on the
  decode pool (degraded mode, zero commits).
* **killed IN FLIGHT**: the inject target accepts the record at the
  wire and never acks — the ledger timeout aborts, retries with
  backoff, and the record commits ONCE (no re-prefill: the payload
  never left the router).
* **killed AFTER commit**: the decode replica dies mid-decode — the
  ledger still holds the exported blocks + first token, so the sibling
  re-decodes from the record (one redecode, prefill never repaid).

All in-process (the core-lane shape); the subprocess versions — SIGKILL
at the Nth handoff under the group supervisor — live in the chaos
campaign's ``fleet_disagg_handoff`` scenario and ``bench.py
--serve-disagg``'s chaos arms.
"""

import time

import pytest

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    FleetRouter, InprocReplica, Scheduler, ServeConfig, make_requests,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

pytestmark = pytest.mark.fleet

V = 64


@pytest.fixture(scope="module")
def lm():
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    return model, model.init(prng.init_key(0))


def _sched(model, params, *, role="unified", slots=4, queue_depth=16,
           replica=None, num_blocks=None, **kw):
    return Scheduler(model, params, ServeConfig(
        slots=slots, num_blocks=num_blocks or (1 + slots * 4),
        block_size=16, prefill_chunk=16, queue_depth=queue_depth,
        replica=replica, role=role, **kw))


def _reference(model, params, jobs):
    """``jobs`` = [(prompt, max_new)] through ONE unified scheduler —
    the undisturbed greedy reference."""
    sched = _sched(model, params, queue_depth=64, num_blocks=64)
    try:
        rids = [sched.submit(p, m) for p, m in jobs]
        assert all(r is not None for r in rids)
        sched.run_until_drained()
        return [sched.result(r) for r in rids]
    finally:
        sched.close()


def _drive(router, rids, *, sleep=0.0, max_iter=20000):
    """Pump until every rid completes; returns nothing (results are
    read off the router)."""
    done = set()
    for _ in range(max_iter):
        done.update(router.pump())
        if all(r in done for r in rids):
            return
        if sleep:
            time.sleep(sleep)
    raise AssertionError(
        f"requests never drained: {sorted(set(rids) - done)} missing; "
        f"phases={[(r, router.reqs[r].phase) for r in rids]}")


def _drive_until(router, cond, *, max_iter=20000):
    for _ in range(max_iter):
        router.pump()
        if cond():
            return
    raise AssertionError("condition never met while pumping")


def _drained(*handles):
    for h in handles:
        h.sched.server.allocator.assert_drained()


def _close(router, *handles):
    router.close()
    for h in handles:
        h.sched.close()


# ---------------------------------------------------------------------------
# steady state: byte identity + exactly one commit per request
# ---------------------------------------------------------------------------

def test_disagg_tokens_byte_identical_to_unified(lm):
    model, params = lm
    plan = make_requests(4, 2, vocab_size=V, prompt_lens=(4, 20),
                         max_new=(4, 12), seed=5)
    jobs = [(r["prompt"], r["max_new"]) for reqs in plan for r in reqs]
    ref = _reference(model, params, jobs)
    pre = InprocReplica(_sched(model, params, role="prefill",
                               replica=0), name="pre-0")
    d0 = InprocReplica(_sched(model, params, role="decode",
                              replica=1), name="dec-0")
    d1 = InprocReplica(_sched(model, params, role="decode",
                              replica=2), name="dec-1")
    router = FleetRouter([pre, d0, d1], queue_depth=64)
    try:
        rids = [router.submit(p, m) for p, m in jobs]
        assert all(r is not None for r in rids)
        _drive(router, rids)
        for rid, want in zip(rids, ref):
            assert router.result(rid) == want
        # every request took the handoff path exactly once; no
        # recovery machinery fired in steady state
        assert router.handoffs == len(jobs)
        assert router.handoff_retries == 0
        assert router.handoff_reprefills == 0
        assert router.redecodes == 0
        assert router.requeued == 0
        assert router.degraded_dispatches == 0
        # both roles' allocators drained: the prefill released every
        # exported block at commit, the decode pools at retire
        _drained(pre, d0, d1)
    finally:
        _close(router, pre, d0, d1)


# ---------------------------------------------------------------------------
# killed BEFORE commit
# ---------------------------------------------------------------------------

def test_prefill_death_before_commit_requeues_to_sibling_prefill(lm):
    model, params = lm
    prompt, max_new = list(range(1, 25)), 8     # 2 prefill chunks
    [want] = _reference(model, params, [(prompt, max_new)])
    pre0 = InprocReplica(_sched(model, params, role="prefill",
                                replica=0), name="pre-0")
    pre1 = InprocReplica(_sched(model, params, role="prefill",
                                replica=1), name="pre-1")
    dec = InprocReplica(_sched(model, params, role="decode",
                               replica=2), name="dec-0")
    router = FleetRouter([pre0, pre1, dec], queue_depth=16)
    try:
        rid = router.submit(prompt, max_new)
        assert rid is not None
        req = router.reqs[rid]
        _drive_until(router, lambda: req.phase == "prefilling")
        victim = next(h for h in (pre0, pre1) if h.name == req.replica)
        survivor = pre1 if victim is pre0 else pre0
        victim.fail()
        router.on_replica_down(victim.name)
        _drive(router, [rid])
        assert router.result(rid) == want
        # one requeue (the pre-commit death), then the normal path:
        # exactly one commit, no redecode, no re-prefill bookkeeping
        # (the record never existed when the prefill died)
        assert router.requeued == 1
        assert router.handoffs == 1
        assert router.redecodes == 0
        assert router.handoff_reprefills == 0
        assert req.prefill_replica == survivor.name
        _drained(survivor, dec)
    finally:
        _close(router, pre0, pre1, dec)


def test_prefill_pool_death_degrades_to_unified_on_decode(lm):
    model, params = lm
    prompt, max_new = list(range(1, 25)), 8
    [want] = _reference(model, params, [(prompt, max_new)])
    pre = InprocReplica(_sched(model, params, role="prefill",
                               replica=0), name="pre-0")
    dec = InprocReplica(_sched(model, params, role="decode",
                               replica=1), name="dec-0")
    router = FleetRouter([pre, dec], queue_depth=16)
    try:
        rid = router.submit(prompt, max_new)
        assert rid is not None
        req = router.reqs[rid]
        _drive_until(router, lambda: req.phase == "prefilling")
        pre.fail()
        router.on_replica_down(pre.name)
        _drive(router, [rid])
        assert router.result(rid) == want
        # no prefill pool left: the decode replica served END-TO-END
        # (degraded mode) — zero commits, and the degraded dispatch
        # is counted so the autopilot/bench can price it
        assert router.requeued == 1
        assert router.handoffs == 0
        assert router.degraded_dispatches >= 1
        assert router.load_report()["now"]["degraded"] is True
        _drained(dec)
    finally:
        _close(router, pre, dec)


# ---------------------------------------------------------------------------
# killed IN FLIGHT: accepted at the wire, never acked
# ---------------------------------------------------------------------------

class _StallOnceReplica(InprocReplica):
    """Accepts the first inject at the wire and swallows it — no ack,
    no stream, the subprocess wedge the ledger timeout exists for."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.swallowed = 0

    def inject(self, req, payload):
        if not self.swallowed:
            self.swallowed = 1
            return True
        return super().inject(req, payload)


def test_handoff_timeout_aborts_and_retries_exactly_once(lm):
    model, params = lm
    prompt, max_new = list(range(1, 13)), 8
    [want] = _reference(model, params, [(prompt, max_new)])
    pre = InprocReplica(_sched(model, params, role="prefill",
                               replica=0), name="pre-0")
    dec = _StallOnceReplica(_sched(model, params, role="decode",
                                   replica=1), name="dec-0")
    router = FleetRouter([pre, dec], queue_depth=16,
                         handoff_timeout_s=0.05)
    try:
        rid = router.submit(prompt, max_new)
        assert rid is not None
        _drive(router, [rid], sleep=0.002)
        assert router.result(rid) == want
        assert dec.swallowed == 1
        # the timeout re-owned the record and re-dispatched it: one
        # commit, >=1 retry, and the payload never left the router so
        # prefill was NOT repaid
        assert router.handoffs == 1
        assert router.handoff_retries >= 1
        assert router.handoff_reprefills == 0
        assert router.redecodes == 0
        _drained(pre, dec)
    finally:
        _close(router, pre, dec)


# ---------------------------------------------------------------------------
# killed AFTER commit: re-decode from the ledger record
# ---------------------------------------------------------------------------

def test_decode_death_after_commit_redecodes_from_ledger(lm):
    model, params = lm
    prompt, max_new = list(range(1, 13)), 10
    [want] = _reference(model, params, [(prompt, max_new)])
    pre = InprocReplica(_sched(model, params, role="prefill",
                               replica=0), name="pre-0")
    d0 = InprocReplica(_sched(model, params, role="decode",
                              replica=1), name="dec-0")
    d1 = InprocReplica(_sched(model, params, role="decode",
                              replica=2), name="dec-1")
    router = FleetRouter([pre, d0, d1], queue_depth=16)
    try:
        rid = router.submit(prompt, max_new)
        assert rid is not None
        req = router.reqs[rid]
        _drive_until(router, lambda: req.phase == "decoding")
        victim = next(h for h in (d0, d1) if h.name == req.replica)
        sibling = d1 if victim is d0 else d0
        victim.fail()
        router.on_replica_down(victim.name)
        _drive(router, [rid])
        assert router.result(rid) == want
        # the ledger record survived the decode death: ONE redecode on
        # the sibling, the original single commit, and never a
        # re-prefill or a generic requeue (prefill is not repaid)
        assert router.redecodes == 1
        assert router.handoffs == 1
        assert router.handoff_reprefills == 0
        assert router.requeued == 0
        assert req.replica == sibling.name
        _drained(pre, sibling)
    finally:
        _close(router, pre, d0, d1)


def test_decode_pool_death_reprefills_unified_on_prefill_pool(lm):
    """A committed ledger record whose decode DUTY disappears entirely
    (no sibling decode, no unified fallback) must not strand: the
    record drops to a unified requeue — re-prefill on the surviving
    pool, the one recovery that repays prefill — and it is counted."""
    model, params = lm
    prompt, max_new = list(range(1, 13)), 10
    [want] = _reference(model, params, [(prompt, max_new)])
    pre = InprocReplica(_sched(model, params, role="prefill",
                               replica=0), name="pre-0")
    dec = InprocReplica(_sched(model, params, role="decode",
                               replica=1), name="dec-0")
    router = FleetRouter([pre, dec], queue_depth=16)
    try:
        rid = router.submit(prompt, max_new)
        assert rid is not None
        req = router.reqs[rid]
        _drive_until(router, lambda: req.phase == "decoding")
        dec.fail()
        router.on_replica_down(dec.name)
        _drive(router, [rid])
        assert router.result(rid) == want
        # death converted the record to a redecode, the dead pool
        # converted the redecode to a counted re-prefill, and the
        # request finished END-TO-END on the prefill pool (degraded)
        assert router.redecodes == 1
        assert router.handoff_reprefills == 1
        assert router.requeued == 1
        assert router.handoffs == 1
        assert router.degraded_dispatches >= 1
        _drained(pre)
    finally:
        _close(router, pre, dec)
