"""Trainer-level pipeline ('pipe') and expert ('expert') parallelism.

The reference has neither (single nn.Sequential, no MoE — SURVEY.md §2.2);
these are TPU-native capabilities, and the Trainer must drive them through
the same config/CLI surface as plain DP.
"""

import dataclasses

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig, build_argparser,
    config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer

# integration-heavy: full lane only (core lane: -m 'not slow')
pytestmark = pytest.mark.slow


def _lm_cfg(**mesh_kw):
    return TrainConfig(
        nepochs=1, batch_size=32, full_batch=False, loss="cross_entropy",
        optimizer="adam", lr=1e-3,
        data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                        vocab_size=64, val_fraction=0.25),
        model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                          n_heads=4, d_ff=64, vocab_size=64, max_seq_len=16),
        mesh=MeshConfig(**mesh_kw),
    )


def test_trainer_pipeline_end_to_end():
    cfg = _lm_cfg(data=4, pipe=2)
    t = Trainer(cfg)
    assert t.pipeline
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    # eval ran the dense model on pipe-gathered params
    assert "val_loss" in result and np.isfinite(result["val_loss"])
    # pipelined blocks remain stage-stacked in the live state
    import jax

    leaf = jax.tree_util.tree_leaves(t.state.params["blocks"])[0]
    assert leaf.shape[0] == 2  # n_stages leading axis


def test_trainer_expert_end_to_end():
    cfg = _lm_cfg(data=4, expert=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert")
    t = Trainer(cfg)
    assert t.expert
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_expert_requires_moe_model():
    cfg = _lm_cfg(data=4, expert=2)  # moe_experts defaults to 0
    with pytest.raises(ValueError, match="moe_experts"):
        Trainer(cfg)


def test_trainer_rejects_unwired_mixed_styles():
    # pipe x fsdp stays unwired (pipe x expert / pipe x seq wired round 4)
    cfg = _lm_cfg(data=2, pipe=2, fsdp=2)
    with pytest.raises(NotImplementedError, match="pipe composes with"):
        Trainer(cfg)
    # seq x tensor, seq x expert, and expert x tensor are wired (round 2);
    # seq x fsdp remains an unwired mix
    cfg2 = _lm_cfg(data=2, seq=2, fsdp=2)
    cfg2.model = dataclasses.replace(cfg2.model, attention="ring")
    with pytest.raises(NotImplementedError, match="wired combinations"):
        Trainer(cfg2)
    # MoE x pipeline without an expert axis stays unwired — clear guard
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        pipeline as pp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )

    import jax as _jax

    moe_model = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=16, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, moe_experts=4))
    mesh_noexp = make_mesh(MeshConfig(data=4, pipe=2),
                           devices=_jax.devices("cpu")[:8])
    with pytest.raises(NotImplementedError, match="expert axis"):
        pp.make_pipeline_train_step(moe_model, optim.sgd(0.1), mesh_noexp)


def test_trainer_pp_ep_end_to_end():
    """DP x PP x EP through the Trainer (VERDICT r3 item 5): MoE blocks
    inside pipeline stages — all_to_all expert dispatch per stage, aux
    load-balance loss threaded through the tick carry."""
    cfg = _lm_cfg(data=2, pipe=2, expert=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert")
    t = Trainer(cfg)
    assert t.pp_ep and t.pipeline and t.expert
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_pp_ep_is_a_pure_rescheduling_of_dp_ep():
    """The pipelined MoE step must be numerically the DP x EP step with
    gradient accumulation: same shards (data x expert rows), same
    contiguous microbatch split, same aux convention
    (Σ_mb s_mb + aux_weight·aux_mb·cnt_mb, reported loss task-only) —
    so loss AND updated params agree.  This is the aux-loss-carried
    proof: both sides include aux_weight=0.01, so a pipeline that
    dropped or mis-gated aux would diverge."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        expert as ep_lib,
        pipeline as pp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    V, T, n_mb = 64, 16, 2
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=T, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention="dense", moe_experts=4,
        moe_expert_axis="expert"))
    opt = optim.sgd(lr=0.1, momentum=0.9)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, V, (16, T + 1))
    batch = {"x": tok[:, :-1].astype(np.int32),
             "y": tok[:, 1:].astype(np.int32),
             "mask": np.ones((16,), np.float32)}

    # --- pipelined: data=2 x pipe=2 x expert=2 ---
    import jax as _jax

    pmesh = make_mesh(MeshConfig(data=2, pipe=2, expert=2),
                      devices=_jax.devices("cpu")[:8])
    state_pp, loss_pp = pp.run_one_step(model, opt, pmesh, batch,
                                        prng.init_key(0),
                                        n_microbatches=n_mb)

    # --- reference: data=2 x expert=2 with accum_steps = n_mb ---
    emesh = make_mesh(MeshConfig(data=2, expert=2),
                      devices=_jax.devices("cpu")[:4])
    state_ep = ep_lib.shard_moe_state(
        TrainState.create(model, opt, prng.init_key(0)), emesh, opt)
    moe_step = ep_lib.make_moe_train_step(model, opt, emesh,
                                          accum_steps=n_mb, donate=False)
    placed = {k: jax.device_put(
        jnp.asarray(v),
        NamedSharding(emesh, P(("data", "fsdp", "expert"))))
        for k, v in batch.items()}
    state_ep, metrics = moe_step(state_ep, placed)

    np.testing.assert_allclose(float(loss_pp), float(metrics["loss"]),
                               rtol=1e-5, atol=1e-6)
    got_blocks = pp.unstack_blocks(
        jax.device_get(state_pp.params["blocks"]))
    ref_blocks = jax.device_get(state_ep.params["blocks"])
    assert len(got_blocks) == len(ref_blocks)
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)
    for name in ("embed", "pos", "ln_f", "head"):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            jax.device_get(state_pp.params[name]),
            jax.device_get(state_ep.params[name]))


def test_cli_ep_flag_wires_moe():
    args = build_argparser().parse_args(
        ["--dataset", "lm", "--ep", "2", "--dp", "4"])
    cfg = config_from_args(args)
    assert cfg.mesh.expert == 2
    assert cfg.model.moe_expert_axis == "expert"
    assert cfg.model.moe_experts == 4  # 2 * ep default


def test_pipeline_grad_clip_keeps_replicas_identical():
    """grad_clip on the pipeline path must clip by the GLOBAL norm (psum of
    pipe-sharded block norms), so pipe-replicated params stay bit-identical
    across devices (the review finding: shard-local norms desynchronize)."""
    import jax
    import numpy as np

    cfg = _lm_cfg(data=4, pipe=2)
    cfg.grad_clip = 0.01  # small enough that clipping definitely engages
    t = Trainer(cfg)
    t.fit()
    # embed/head are replicated over the whole mesh: every device shard of a
    # replicated leaf must hold the identical value after clipped updates
    emb = t.state.params["embed"]["table"]
    shards = [np.asarray(s.data) for s in emb.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_expert_grad_clip_keeps_replicas_identical():
    import jax
    import numpy as np

    cfg = _lm_cfg(data=4, expert=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert")
    cfg.grad_clip = 0.01
    t = Trainer(cfg)
    t.fit()
    emb = t.state.params["embed"]["table"]
    shards = [np.asarray(s.data) for s in emb.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_cli_precision_flags():
    args = build_argparser().parse_args(
        ["--dataset", "mnist", "--dtype", "bfloat16", "--remat"])
    cfg = config_from_args(args)
    assert cfg.model.dtype == "bfloat16"
    assert cfg.model.compute_dtype == "bfloat16"
    assert cfg.model.remat and cfg.model.arch == "mlp"
    args2 = build_argparser().parse_args(
        ["--dataset", "lm", "--dtype", "float32",
         "--compute_dtype", "bfloat16", "--n_layers", "3",
         "--d_model", "64", "--seq_len", "32"])
    cfg2 = config_from_args(args2)
    assert cfg2.model.compute_dtype == "bfloat16"
    assert cfg2.model.n_layers == 3 and cfg2.model.d_model == 64
    assert cfg2.data.seq_len == 32


def test_bfloat16_training_runs():
    import jax.numpy as jnp

    cfg = _lm_cfg(data=8)
    cfg.model = dataclasses.replace(cfg.model, compute_dtype="bfloat16")
    t = Trainer(cfg)
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    # params stay in the declared param dtype
    import jax

    leaf = jax.tree_util.tree_leaves(t.state.params)[0]
    assert leaf.dtype == jnp.float32


def test_trainer_expert_tensor_end_to_end():
    """EP x TP through the Trainer: Megatron attention + tensor-sharded
    experts on a data x expert x tensor mesh, eval + dense-layout export."""
    cfg = _lm_cfg(data=2, expert=2, tensor=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert")
    t = Trainer(cfg)
    assert t.ep_tp and t.expert and not t.gspmd
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])
    # _eval_params undoes the qkv head-alignment permutation: same shapes
    # and treedef as a dense init
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    dense = t.model.init(prng.init_key(cfg.seed))
    got = jax.device_get(t._eval_params())
    assert (jax.tree_util.tree_structure(got)
            == jax.tree_util.tree_structure(dense))
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(dense)):
        assert a.shape == b.shape


def test_trainer_seq_expert_end_to_end():
    """SP x EP through the Trainer: ring attention over 'seq' composed with
    the all_to_all expert dispatch — long-context MoE."""
    cfg = _lm_cfg(data=2, seq=2, expert=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert",
                                    attention="ring")
    t = Trainer(cfg)
    assert t.sp_ep and t.expert and t.seq_parallel and not t.gspmd
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])
    assert "val_accuracy" in result

def test_pp_ep_tp_is_a_pure_rescheduling_of_ep_tp():
    """PP x EP x TP (GShard experts inside pipeline stages): numerically
    the EP x TP step with gradient accumulation — Megatron attention over
    local heads, experts sharded over 'expert' AND each expert's hidden
    dim over 'tensor', aux threaded through the tick carry.  Loss and
    updated params agree with parallel.expert.make_moe_tp_train_step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        expert as ep_lib,
        pipeline as pp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    V, T, n_mb = 64, 16, 2
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=T, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, attention="dense", moe_experts=4,
        moe_expert_axis="expert"))
    opt = optim.sgd(lr=0.1, momentum=0.9)
    rng = np.random.default_rng(1)
    tok = rng.integers(0, V, (8, T + 1))
    batch = {"x": tok[:, :-1].astype(np.int32),
             "y": tok[:, 1:].astype(np.int32),
             "mask": np.ones((8,), np.float32)}

    pmesh = make_mesh(MeshConfig(pipe=2, expert=2, tensor=2),
                      devices=jax.devices("cpu")[:8])
    state_pp, loss_pp = pp.run_one_step(model, opt, pmesh, batch,
                                        prng.init_key(0),
                                        n_microbatches=n_mb)

    emesh = make_mesh(MeshConfig(expert=2, tensor=2),
                      devices=jax.devices("cpu")[:4])
    state_ep = ep_lib.init_moe_tp_state(model, opt, prng.init_key(0), tp=2)
    state_ep = ep_lib.shard_moe_tp_state(state_ep, emesh, opt)
    moe_step = ep_lib.make_moe_tp_train_step(model, opt, emesh,
                                             accum_steps=n_mb, donate=False)
    placed = {k: jax.device_put(
        jnp.asarray(v),
        NamedSharding(emesh, P(("data", "fsdp", "expert"))))
        for k, v in batch.items()}
    state_ep, metrics = moe_step(state_ep, placed)

    np.testing.assert_allclose(float(loss_pp), float(metrics["loss"]),
                               rtol=1e-5, atol=1e-6)
    got_blocks = pp.unstack_blocks(jax.device_get(state_pp.params["blocks"]))
    ref_blocks = jax.device_get(state_ep.params["blocks"])
    assert len(got_blocks) == len(ref_blocks)
    for got, ref in zip(got_blocks, ref_blocks):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            got, ref)
    for name in ("embed", "pos", "ln_f", "head"):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            jax.device_get(state_pp.params[name]),
            jax.device_get(state_ep.params[name]))


def test_trainer_pp_ep_tp_end_to_end():
    """DP x PP x EP x TP through the Trainer: four parallelism axes in one
    job (pipe stages x all_to_all experts x Megatron tensor sharding)."""
    cfg = _lm_cfg(pipe=2, expert=2, tensor=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert")
    t = Trainer(cfg)
    assert t.pp_ep and t.pipeline and t.expert and t.tensor
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])

def test_trainer_pp_sp_end_to_end():
    """DP x PP x SP through the Trainer: ring attention over 'seq' inside
    pipeline stages — long-context pipelining (round 4)."""
    cfg = _lm_cfg(data=2, pipe=2, seq=2)
    cfg.model = dataclasses.replace(cfg.model, attention="ring")
    t = Trainer(cfg)
    assert t.pp_sp and t.pipeline and t.seq_parallel
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_pp_sp_striped_flash_end_to_end():
    """PP x SP with the striped (balanced-causal) ring flash kernel: the
    loader's round-robin token permutation composes with the pipeline
    schedule (positions come from sequence.global_positions)."""
    cfg = _lm_cfg(data=2, pipe=2, seq=2)
    cfg.model = dataclasses.replace(cfg.model, attention="striped_flash")
    t = Trainer(cfg)
    assert t.pp_sp and t.seq_permutation is not None
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_seq_expert_tensor_end_to_end():
    """SP x EP x TP through the Trainer: seq-sharded attention + all_to_all
    experts + Megatron tensor sharding in one layout (round 4)."""
    cfg = _lm_cfg(data=1, seq=2, expert=2, tensor=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    moe_expert_axis="expert",
                                    attention="ring")
    t = Trainer(cfg)
    assert t.ep_tp and t.seq_parallel and not t.sp_tp and not t.gspmd
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_sp_tp_moe_end_to_end():
    """seq x tensor with an MoE FFN routes to the expert module's step
    (expert axis 1: experts whole, hidden dim tensor-sharded)."""
    cfg = _lm_cfg(data=2, seq=2, tensor=2)
    cfg.model = dataclasses.replace(cfg.model, moe_experts=4,
                                    attention="ring")
    t = Trainer(cfg)
    assert t.ep_tp and not t.sp_tp and not t.expert
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_pp_sp_tensor_end_to_end():
    """PP x SP x TP through the Trainer (round 4): pipeline stages with
    Megatron-sharded heads and ring attention over 'seq'."""
    cfg = _lm_cfg(data=1, pipe=2, seq=2, tensor=2)
    cfg.model = dataclasses.replace(cfg.model, n_layers=2,
                                    attention="ring")
    t = Trainer(cfg)
    assert t.pipeline and t.pp_sp and t.seq_parallel and t.tensor
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_pp_sp_expert_end_to_end():
    """PP x SP x EP through the Trainer: long-context MoE pipelining."""
    cfg = _lm_cfg(data=1, pipe=2, seq=2, expert=2)
    cfg.model = dataclasses.replace(cfg.model, n_layers=2,
                                    moe_experts=4,
                                    moe_expert_axis="expert",
                                    attention="ring")
    t = Trainer(cfg)
    assert t.pipeline and t.pp_sp and t.pp_ep and t.expert
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert "val_loss" in result and np.isfinite(result["val_loss"])


def test_trainer_modern_stack_seq_expert_matches_dp():
    """The round-4 model family (RoPE + SwiGLU gated experts + GQA) on
    the SP x EP layout: the expert path's attention closure rotates q/k
    by per-shard GLOBAL positions and the gated experts dispatch through
    the all_to_all — trajectory parity against plain DP on the identical
    model pins every one of those pieces at once."""
    def mk(**mesh_kw):
        cfg = _lm_cfg(**mesh_kw)
        cfg.model = dataclasses.replace(
            cfg.model, moe_experts=4, pos_encoding="rope",
            ffn_activation="swiglu", n_kv_heads=2, d_ff=48)
        return cfg

    r_dp = Trainer(mk(data=8)).fit()
    cfg = mk(data=2, seq=2, expert=2)
    cfg.model = dataclasses.replace(cfg.model, moe_expert_axis="expert",
                                    attention="ring")
    t = Trainer(cfg)
    assert t.sp_ep
    r = t.fit()
    assert np.isfinite(r["final_loss"])
    # looser than the dense-parity bar: top-k routing is DISCRETE, so
    # layout-order float differences can flip a near-tie expert choice
    # and legitimately perturb the trajectory (observed ~3e-4 rel)
    assert r["final_loss"] == pytest.approx(r_dp["final_loss"], rel=3e-3)
