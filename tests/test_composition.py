"""Multi-strategy Trainer composition (round-2 wiring).

The reference's only strategy is pure synchronous DP
(dataParallelTraining_NN_MPI.py:185-208); everything here is added TPU-native
capability, and the bar is *trajectory parity*: every composed mesh must
train to the same weights as the plain-DP path on the same data, because all
of them compute the identical global-mean gradient.

Covers: DP x TP x PP (explicit Megatron TP inside pipeline stages),
zero1 + global-norm clip, zero1 under DP x SP, and gradient accumulation on
the GSPMD / pipeline / expert paths.
"""

import dataclasses

import jax
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer

# integration-heavy: full lane only (core lane: -m 'not slow')
pytestmark = pytest.mark.slow


def _lm_cfg(nepochs=2, **mesh_kw):
    return TrainConfig(
        nepochs=nepochs, batch_size=32, full_batch=False, shuffle=False,
        loss="cross_entropy", optimizer="adam", lr=1e-3,
        data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                        vocab_size=64),
        model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                          n_heads=4, d_ff=64, vocab_size=64, max_seq_len=16),
        mesh=MeshConfig(**mesh_kw),
    )


def _reg_cfg(**kw):
    mesh = kw.pop("mesh", MeshConfig(data=8))
    return TrainConfig(
        nepochs=2, batch_size=16, full_batch=False, shuffle=False, lr=1e-4,
        data=DataConfig(dataset="regression", n_samples=64, n_features=8),
        model=ModelConfig(arch="mlp", in_features=8, hidden=(16, 16),
                          out_features=1),
        mesh=mesh, **kw,
    )


def _dense_params(trainer):
    """Params in the dense (per-layer, unpermuted) layout, host-side."""
    return jax.device_get(trainer._eval_params())


# TP/accumulation meshes change matmul/reduction order, and Adam's
# 1/sqrt(v) normalization amplifies those float32 grad diffs to ~lr-sized
# (1e-3 * steps) param deltas — composed-mesh callers pass this; the
# default stays tight so same-reduction-order pins keep their teeth.
LOOSE_ATOL = 1e-4


def _assert_params_close(pa, pb, rtol=2e-4, atol=1e-6):
    la = jax.tree_util.tree_leaves(pa)
    lb = jax.tree_util.tree_leaves(pb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=rtol, atol=atol)


# --------------------------------------------------------------------------
# DP x TP x PP
# --------------------------------------------------------------------------

class TestPipelineTensor:
    def test_dp_tp_pp_matches_dp_pp_and_dp(self):
        # same job on three meshes; identical data order (shuffle=False)
        t_dp = Trainer(_lm_cfg(data=8))
        r_dp = t_dp.fit()
        t_pp = Trainer(_lm_cfg(data=4, pipe=2))
        r_pp = t_pp.fit()
        t_3d = Trainer(_lm_cfg(data=2, tensor=2, pipe=2))
        assert t_3d.pipeline and t_3d.tensor and not t_3d.gspmd
        r_3d = t_3d.fit()
        assert np.isfinite(r_3d["final_loss"])
        assert r_3d["final_loss"] == pytest.approx(r_pp["final_loss"],
                                                   rel=2e-4)
        assert r_3d["final_loss"] == pytest.approx(r_dp["final_loss"],
                                                   rel=2e-4)
        _assert_params_close(_dense_params(t_3d), _dense_params(t_pp),
                             atol=LOOSE_ATOL)
        _assert_params_close(_dense_params(t_3d), _dense_params(t_dp),
                             atol=LOOSE_ATOL)

    def test_tp_block_params_are_tensor_sharded(self):
        t = Trainer(_lm_cfg(nepochs=1, data=2, tensor=2, pipe=2))
        t.init_state()
        qkv_w = t.state.params["blocks"]["qkv"]["w"]
        # (n_stages, per, d, 3d): pipe on dim 0, tensor on dim 3
        local = qkv_w.addressable_shards[0].data.shape
        assert local[0] * 2 == qkv_w.shape[0]
        assert local[3] * 2 == qkv_w.shape[3]

    def test_dp_tp_pp_grad_clip_runs(self):
        cfg = _lm_cfg(nepochs=1, data=2, tensor=2, pipe=2)
        cfg.grad_clip = 0.5
        r = Trainer(cfg).fit()
        assert np.isfinite(r["final_loss"])


# --------------------------------------------------------------------------
# zero1 composition
# --------------------------------------------------------------------------

class TestZero1:
    def test_zero1_clip_matches_replicated_clip(self):
        # clip threshold low enough to engage on this workload
        tz = Trainer(_reg_cfg(update_sharding="zero1", grad_clip=0.5))
        rz = tz.fit()
        tr = Trainer(_reg_cfg(update_sharding="replicated", grad_clip=0.5))
        rr = tr.fit()
        assert rz["final_loss"] == pytest.approx(rr["final_loss"], rel=1e-5)
        _assert_params_close(tz.state.params, tr.state.params,
                             rtol=1e-5, atol=1e-7)

    def test_zero1_under_seq_parallel_matches_replicated(self):
        def cfg(sharding):
            c = _lm_cfg(data=4, seq=2)
            c.update_sharding = sharding
            c.model = dataclasses.replace(c.model, attention="ring")
            return c

        tz = Trainer(cfg("zero1"))
        assert tz.seq_parallel and tz.zero1
        rz = tz.fit()
        tr = Trainer(cfg("replicated"))
        rr = tr.fit()
        assert rz["final_loss"] == pytest.approx(rr["final_loss"], rel=1e-4)
        # zero1 flattens/scatters the update (different reduction order)
        _assert_params_close(tz.state.params, tr.state.params,
                             atol=LOOSE_ATOL)

    def test_zero1_seq_opt_state_sharded_over_data_only(self):
        c = _lm_cfg(nepochs=1, data=4, seq=2)
        c.update_sharding = "zero1"
        c.model = dataclasses.replace(c.model, attention="ring")
        t = Trainer(c)
        t.init_state()
        leaves = [l for l in jax.tree_util.tree_leaves(t.state.opt_state)
                  if l.ndim == 1]
        assert leaves, "expected flat zero1 buffers"
        local = leaves[0].addressable_shards[0].data.shape[0]
        assert local * 4 == leaves[0].shape[0]  # 1/data_size, seq-replicated


# --------------------------------------------------------------------------
# gradient accumulation on every path
# --------------------------------------------------------------------------

class TestAccumulation:
    def _parity(self, make_cfg, atol=LOOSE_ATOL, rel=2e-4):
        t1 = Trainer(make_cfg(1))
        r1 = t1.fit()
        t2 = Trainer(make_cfg(2))
        r2 = t2.fit()
        assert r2["final_loss"] == pytest.approx(r1["final_loss"], rel=rel)
        _assert_params_close(_dense_params(t2), _dense_params(t1), atol=atol)

    def test_gspmd_accum_matches_unaccumulated(self):
        def cfg(accum):
            c = _lm_cfg(data=2, tensor=2, fsdp=2)
            c.accum_steps = accum
            return c

        self._parity(cfg)

    def test_pipeline_accum_matches_unaccumulated(self):
        def cfg(accum):
            c = _lm_cfg(data=4, pipe=2)
            c.accum_steps = accum
            return c

        self._parity(cfg)

    def test_expert_accum_matches_unaccumulated(self):
        def cfg(accum):
            c = _lm_cfg(data=4, expert=2)
            # capacity_factor high enough that no token ever overflows —
            # capacity is enforced per-microbatch, so at the default 1.25
            # splitting the batch would drop *different* borderline tokens
            c.model = dataclasses.replace(c.model, moe_experts=4,
                                          moe_expert_axis="expert",
                                          moe_capacity_factor=8.0)
            c.accum_steps = accum
            return c

        # looser tolerance than the dense paths: the Switch load-balance
        # aux loss E * sum_e f_e*p_e (models/moe.py:102-105) is nonlinear
        # in the batch statistics f_e/p_e, so the mean of per-microbatch
        # aux losses differs from the full-batch aux loss — accumulation
        # under MoE is approximate in every framework; trajectories stay
        # close but not bit-equal.
        self._parity(cfg, atol=1e-2, rel=1e-3)


class TestTpCheckpointResume:
    def test_resume_across_tensor_axis_sizes(self, tmp_path):
        """A pipeline checkpoint written under tp=2 carries the (shape-
        preserving) head-aligned qkv permutation; meta.json records it and
        maybe_resume re-permutes params AND optimizer slots, so resuming
        with tp=1 (or vice versa) yields the identical dense model."""
        d = str(tmp_path / "ck")
        cfg = _lm_cfg(nepochs=1, data=2, tensor=2, pipe=2)
        cfg.checkpoint_dir = d
        t_tp = Trainer(cfg)
        t_tp.fit()  # writes the final checkpoint (qkv_tp=2 in meta)
        want = _dense_params(t_tp)

        cfg2 = _lm_cfg(nepochs=2, data=4, pipe=2)  # epoch 2 remains to run
        cfg2.checkpoint_dir = d
        cfg2.resume = True
        t_pp = Trainer(cfg2)
        t_pp.init_state()
        resumed_step = t_pp.maybe_resume()
        assert resumed_step > 0
        got = _dense_params(t_pp)
        _assert_params_close(got, want, rtol=0, atol=0)

        # and the resumed job trains (the re-permuted optimizer slots are
        # consistent with the re-permuted params)
        r = t_pp.fit()
        assert np.isfinite(r["final_loss"])


# --------------------------------------------------------------------------
# DP x SP x TP (Megatron matmuls + ring attention in one shard_map)
# --------------------------------------------------------------------------

class TestSeqTensor:
    def test_dp_sp_tp_matches_dp(self):
        t_dp = Trainer(_lm_cfg(data=8))
        r_dp = t_dp.fit()
        cfg = _lm_cfg(data=2, seq=2, tensor=2)
        cfg.model = dataclasses.replace(cfg.model, attention="ring")
        t_3d = Trainer(cfg)
        assert t_3d.sp_tp and not t_3d.gspmd and not t_3d.pipeline
        r_3d = t_3d.fit()
        assert np.isfinite(r_3d["final_loss"])
        assert r_3d["final_loss"] == pytest.approx(r_dp["final_loss"],
                                                   rel=2e-4)
        _assert_params_close(_dense_params(t_3d), _dense_params(t_dp),
                             atol=LOOSE_ATOL)

    def test_sp_tp_params_are_tensor_sharded(self):
        cfg = _lm_cfg(nepochs=1, data=2, seq=2, tensor=2)
        cfg.model = dataclasses.replace(cfg.model, attention="ring")
        t = Trainer(cfg)
        t.init_state()
        qkv_w = t.state.params["blocks"][0]["qkv"]["w"]  # (d, 3d)
        local = qkv_w.addressable_shards[0].data.shape
        assert local[1] * 2 == qkv_w.shape[1]  # columns over 'tensor'
        assert local[0] == qkv_w.shape[0]

    def test_sp_tp_eval_matches_train_layout(self):
        cfg = _lm_cfg(nepochs=1, data=2, seq=2, tensor=2)
        cfg.data = dataclasses.replace(cfg.data, val_fraction=0.25)
        cfg.eval_every = 1
        cfg.model = dataclasses.replace(cfg.model, attention="ring")
        r = Trainer(cfg).fit()
        assert np.isfinite(r["val_loss"])
        assert 0.0 <= r["val_accuracy"] <= 1.0

    def test_sp_tp_checkpoint_resume_to_dense_tp1(self, tmp_path):
        d = str(tmp_path / "ck")
        cfg = _lm_cfg(nepochs=1, data=2, seq=2, tensor=2)
        cfg.model = dataclasses.replace(cfg.model, attention="ring")
        cfg.checkpoint_dir = d
        t = Trainer(cfg)
        t.fit()
        want = _dense_params(t)

        cfg2 = _lm_cfg(nepochs=2, data=4, seq=2)
        cfg2.model = dataclasses.replace(cfg2.model, attention="ring")
        cfg2.checkpoint_dir = d
        cfg2.resume = True
        t2 = Trainer(cfg2)
        t2.init_state()
        assert t2.maybe_resume() > 0
        _assert_params_close(jax.device_get(t2.state.params), want,
                             rtol=0, atol=0)

    def test_sp_tp_grad_clip_matches_dp_clip(self):
        # low threshold so the clip engages; tensor-aware global norm must
        # reproduce the optimizer-level clip on the plain DP path
        def cfg(mesh_kw, att):
            c = _lm_cfg(**mesh_kw)
            c.grad_clip = 0.5
            c.model = dataclasses.replace(c.model, attention=att)
            return c

        t_dp = Trainer(cfg(dict(data=8), "dense"))
        r_dp = t_dp.fit()
        t_st = Trainer(cfg(dict(data=2, seq=2, tensor=2), "ring"))
        r_st = t_st.fit()
        assert r_st["final_loss"] == pytest.approx(r_dp["final_loss"],
                                                   rel=2e-4)
        _assert_params_close(_dense_params(t_st), _dense_params(t_dp),
                             atol=LOOSE_ATOL)


def test_dense_checkpoint_resumes_into_tp_layout(tmp_path):
    """The review's failure direction: a dense-layout save (qkv_tp=1 in
    meta) resumed INTO a seq x tensor trainer must be permuted on the way
    in — defaulting missing/1 metadata to the current tp would silently
    skip it and hand shard 0 all of q plus half of k."""
    d = str(tmp_path / "ck")
    cfg = _lm_cfg(nepochs=1, data=8)
    cfg.checkpoint_dir = d
    t_dense = Trainer(cfg)
    t_dense.fit()
    want = _dense_params(t_dense)

    cfg2 = _lm_cfg(nepochs=2, data=2, seq=2, tensor=2)
    cfg2.model = dataclasses.replace(cfg2.model, attention="ring")
    cfg2.checkpoint_dir = d
    cfg2.resume = True
    t_tp = Trainer(cfg2)
    t_tp.init_state()
    assert t_tp.maybe_resume() > 0
    # _dense_params un-permutes; round trip must be exact
    _assert_params_close(_dense_params(t_tp), want, rtol=0, atol=0)
    r = t_tp.fit()
    assert np.isfinite(r["final_loss"])


class TestVocabParallel:
    def test_sp_tp_vocab_parallel_matches_dense_head(self):
        """Same seq x tensor job with and without --vocab_parallel: the
        sharded-softmax loss and the trained weights must match (identical
        math, different collective placement)."""
        cfg = _lm_cfg(data=2, seq=2, tensor=2)
        cfg.model = dataclasses.replace(cfg.model, attention="ring")
        t_rep = Trainer(cfg)
        r_rep = t_rep.fit()

        cfg_vp = _lm_cfg(data=2, seq=2, tensor=2)
        cfg_vp.model = dataclasses.replace(cfg_vp.model, attention="ring")
        cfg_vp.vocab_parallel = True
        t_vp = Trainer(cfg_vp)
        assert t_vp.sp_tp
        r_vp = t_vp.fit()
        assert np.isfinite(r_vp["final_loss"])
        assert r_vp["final_loss"] == pytest.approx(r_rep["final_loss"],
                                                   rel=2e-4)
        _assert_params_close(_dense_params(t_vp), _dense_params(t_rep),
                             atol=LOOSE_ATOL)
        # the live state really is vocab-sharded
        emb = t_vp.state.params["embed"]["table"]
        assert emb.addressable_shards[0].data.shape[0] * 2 == emb.shape[0]
        head = t_vp.state.params["head"]["w"]
        assert head.addressable_shards[0].data.shape[1] * 2 == head.shape[1]

    def test_vocab_parallel_eval_and_accuracy(self):
        cfg = _lm_cfg(data=2, seq=2, tensor=2)
        cfg.model = dataclasses.replace(cfg.model, attention="ring")
        cfg.vocab_parallel = True
        cfg.data = dataclasses.replace(cfg.data, val_fraction=0.25)
        cfg.eval_every = 1
        r = Trainer(cfg).fit()
        assert np.isfinite(r["val_loss"])
        assert 0.0 <= r["val_accuracy"] <= 1.0

    def test_vocab_parallel_requires_sp_tp(self):
        cfg = _reg_cfg()
        cfg.vocab_parallel = True
        with pytest.raises(ValueError, match="vocab_parallel"):
            Trainer(cfg)
