"""Regression tests for review findings: accuracy denominator on sequence
models, SP reachable through Trainer, n_samples plumbing."""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.data.datasets import build_dataset
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer


def test_lm_accuracy_not_divided_by_seq_len(mesh8):
    """accuracy must use the example denominator, not CE's token count:
    random predictions on vocab=16 give ~1/16, not ~1/(16*T)."""
    cfg = TrainConfig(loss="cross_entropy", nepochs=1, mesh=MeshConfig(data=8))
    cfg.data = DataConfig(dataset="lm", n_samples=64, seq_len=32, vocab_size=16)
    cfg.model = ModelConfig(arch="transformer", vocab_size=16, max_seq_len=32,
                            n_layers=1, d_model=16, n_heads=2, d_ff=32)
    t = Trainer(cfg, mesh=mesh8)
    t.init_state()
    acc = t.evaluate()["accuracy"]
    assert 0.01 < acc < 0.25  # ~1/16; the token-count bug gave ~1/512


def test_sp_through_trainer(devices):
    """--sp > 1 must actually engage ring attention + the spmd step."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=2, seq=4), devices=devices)
    cfg = TrainConfig(loss="cross_entropy", nepochs=1, full_batch=False,
                      batch_size=8, mesh=MeshConfig(data=2, seq=4))
    cfg.data = DataConfig(dataset="lm", n_samples=16, seq_len=32, vocab_size=16)
    cfg.model = ModelConfig(arch="transformer", vocab_size=16, max_seq_len=32,
                            n_layers=1, d_model=16, n_heads=4, d_ff=32,
                            attention="ring")
    t = Trainer(cfg, mesh=mesh)
    assert t.seq_parallel
    result = t.fit()
    assert np.isfinite(result["final_loss"])


def test_pipe_requires_transformer(mesh8):
    # pipe>1 is wired into Trainer now (test_trainer_pp_ep), but only for
    # stage-splittable models — the default MLP must be rejected up front
    cfg = TrainConfig(mesh=MeshConfig(data=4, pipe=2))
    with pytest.raises(ValueError, match="transformer"):
        Trainer(cfg)


def test_tp_through_trainer(devices):
    """--tp 2 engages the GSPMD step with actually-sharded params."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )

    mesh = make_mesh(MeshConfig(data=2, tensor=2, fsdp=2), devices=devices)
    cfg = TrainConfig(loss="cross_entropy", nepochs=1, full_batch=False,
                      batch_size=8, mesh=MeshConfig(data=2, tensor=2, fsdp=2))
    cfg.data = DataConfig(dataset="lm", n_samples=16, seq_len=16, vocab_size=32)
    cfg.model = ModelConfig(arch="transformer", vocab_size=32, max_seq_len=16,
                            n_layers=1, d_model=32, n_heads=4, d_ff=64)
    t = Trainer(cfg, mesh=mesh)
    assert t.gspmd
    t.init_state()
    qkv = t.state.params["blocks"][0]["qkv"]["w"]
    assert qkv.addressable_shards[0].data.shape == (16, 48)  # fsdp x tensor
    result = t.fit()
    assert np.isfinite(result["final_loss"])


def test_n_samples_plumbs_to_lm():
    data = build_dataset(DataConfig(dataset="lm", n_samples=8, seq_len=16))
    assert data["x"].shape == (8, 16)


def test_n_samples_plumbs_to_mnist():
    data = build_dataset(DataConfig(dataset="mnist", n_samples=128))
    assert data["x"].shape == (128, 784)
