"""Training resilience (DESIGN.md §6): guarded update, anomaly rollback,
preemption-safe exit, and the crash-restart supervisor.

The reference's only failure mode is a silent hang (SURVEY.md §5.3); these
tests drive the full defend-the-state story: a NaN-gradient step is a
bitwise no-op (skip), K consecutive bad steps roll back to the last
checkpoint and re-draw the data order, SIGTERM produces a valid final
checkpoint and exit 0, a crashed child is relaunched by the supervisor and
resumes, and a deterministic divergence (exit 44) is NOT retried.  Fault
injection (utils.faults) makes every scenario exact-step deterministic.
"""

import dataclasses
import os
import pathlib
import subprocess
import sys

import jax
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops import optim
from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
    EXIT_ANOMALY, EXIT_HANG, EXIT_OK, EXIT_PEER, AnomalyAbort,
    ResilienceMonitor, strip_supervisor_flags, supervise,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    checkpoint as ckpt,
    faults as faults_lib,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _leaves_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ---------------------------------------------------------------- optimizer


def test_skip_guard_nonfinite_is_bitwise_noop():
    """NaN/Inf gradients: params and inner opt state bitwise unchanged,
    the skip counter advances, the inner step count does not."""
    import jax.numpy as jnp

    opt = optim.with_skip_guard(optim.sgd(0.1, momentum=0.9))
    params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones((3,))}
    state = opt.init(params)
    for poison in (jnp.nan, jnp.inf):
        grads = {"w": jnp.full((2, 3), poison), "b": jnp.ones((3,))}
        new_params, new_state = jax.jit(opt.update)(grads, state, params)
        _leaves_equal(new_params, params)
        _leaves_equal(new_state.inner, state.inner)
        assert int(new_state.skipped) == int(state.skipped) + 1
        state = new_state
    # a clean step still applies and bumps the INNER count only
    good = {"w": jnp.ones((2, 3)), "b": jnp.ones((3,))}
    new_params, new_state = jax.jit(opt.update)(good, state, params)
    assert not np.allclose(np.asarray(new_params["w"]),
                           np.asarray(params["w"]))
    assert int(new_state.inner.count) == 1
    assert int(new_state.skipped) == 2


def test_skip_guard_threshold():
    """skip_threshold rejects finite-but-huge gradients; under-threshold
    steps pass through with math identical to the unguarded optimizer."""
    import jax.numpy as jnp

    base = optim.sgd(0.1)
    opt = optim.with_skip_guard(base, skip_threshold=10.0)
    params = {"w": jnp.ones((4,))}
    state = opt.init(params)
    huge = {"w": jnp.full((4,), 100.0)}  # norm 200 > 10
    new_params, new_state = opt.update(huge, state, params)
    _leaves_equal(new_params, params)
    assert int(new_state.skipped) == 1
    small = {"w": jnp.full((4,), 1.0)}   # norm 2 <= 10
    guarded_p, _ = opt.update(small, new_state, params)
    plain_p, _ = base.update(small, base.init(params), params)
    _leaves_equal(guarded_p, plain_p)


def test_skip_guard_state_specs_and_checkpoint_roundtrip(tmp_path):
    """GuardedState is spec-mapped (GSPMD placement) and checkpointable."""
    from jax.sharding import PartitionSpec as P

    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )

    opt = optim.with_skip_guard(optim.adam(1e-3))
    specs = opt.state_specs({"w": P("data")})
    assert isinstance(specs.skipped, P)
    assert specs.inner.mu == {"w": P("data")}
    import jax.numpy as jnp

    params = {"w": jnp.ones((2, 2))}
    state = TrainState(jnp.zeros((), jnp.int32), params, opt.init(params))
    ckpt.save(str(tmp_path), state)
    restored = ckpt.restore(str(tmp_path), state)
    _leaves_equal(restored, state)


# ------------------------------------------------------------------ monitor


def test_monitor_consecutive_and_rollback_policy():
    m = ResilienceMonitor(rollback_after=3, max_rollbacks=1)
    nan = float("nan")
    assert m.observe(1.0) == "ok"
    assert m.observe(nan) == "bad"
    assert m.observe(nan) == "bad"
    assert m.observe(1.0) == "ok"      # a good step resets the streak
    assert m.observe(nan) == "bad"
    assert m.observe(nan) == "bad"
    assert m.observe(nan) == "rollback"
    assert m.rollbacks == 1
    assert m.observe(nan) == "bad"
    assert m.observe(nan) == "bad"
    assert m.observe(nan) == "abort"   # budget (max_rollbacks=1) exhausted
    assert m.bad_steps == 8


def test_monitor_loss_spike_ema():
    m = ResilienceMonitor(rollback_after=2, spike_factor=10.0, warmup=3)
    for _ in range(5):
        assert m.observe(1.0) == "ok"
    assert m.observe(4.0) == "ok"       # 4x the EMA: under the factor
    assert m.observe(50.0) == "bad"     # 50x: a spike
    assert m.observe(60.0) == "rollback"
    # EMA resets after rollback: big-but-steady losses re-warm it
    for _ in range(4):
        assert m.observe(30.0) == "ok"


# ------------------------------------------------------------------- faults


def test_fault_plan_parsing_and_firing(tmp_path):
    plan = faults_lib.FaultPlan.parse("nan@3-5?max=2,crash@9?once=%s"
                                      % (tmp_path / "m"))
    f_nan, f_crash = plan.faults
    assert (f_nan.kind, f_nan.start, f_nan.end, f_nan.max_fires) == \
        ("nan", 3, 5, 2)
    assert (f_crash.kind, f_crash.start, f_crash.end) == ("crash", 9, 9)
    assert f_nan.should_fire(3) and not f_nan.should_fire(2)
    f_nan.mark_fired(), f_nan.mark_fired()
    assert not f_nan.should_fire(4)       # max=2 exhausted
    assert f_crash.should_fire(9)
    f_crash.mark_fired()
    assert (tmp_path / "m").exists()
    assert not f_crash.should_fire(9)     # once-marker persists
    assert faults_lib.FaultPlan.parse("") is None
    for bad in ("boom@3", "nan", "nan@5-2", "nan@3?what=1"):
        with pytest.raises(ValueError):
            faults_lib.FaultPlan.parse(bad)


def test_fault_env_fallback(monkeypatch):
    monkeypatch.setenv(faults_lib.ENV_VAR, "nan@7")
    plan = faults_lib.FaultPlan.from_config("")
    assert plan.faults[0].start == 7
    # an explicit config spec wins over the env var
    assert faults_lib.FaultPlan.from_config("nan@2").faults[0].start == 2


def test_fault_preempt_and_slow_parsing():
    plan = faults_lib.FaultPlan.parse("preempt@5?grace=3.5,"
                                      "slow@2-4?ms=120")
    f_p, f_s = plan.faults
    assert (f_p.kind, f_p.start, f_p.end, f_p.grace) == \
        ("preempt", 5, 5, 3.5)
    assert (f_s.kind, f_s.start, f_s.end, f_s.ms) == ("slow", 2, 4, 120.0)
    # defaults when the option is omitted
    d_p, d_s = faults_lib.FaultPlan.parse("preempt@1,slow@1").faults
    assert d_p.grace == 2.0 and d_s.ms == 50.0
    # slow is a per-poll penalty inside the window, zero outside
    assert plan.slow_penalty_ms(1) == 0.0
    assert plan.slow_penalty_ms(3) == 120.0
    assert plan.slow_penalty_ms(3) == 120.0   # every poll, not one-shot
    assert plan.slow_penalty_ms(5) == 0.0
    # due_spec returns the spec (the worker reads grace off it) exactly
    # once, and only at the armed step
    assert plan.due_spec("preempt", 4) is None
    fired = plan.due_spec("preempt", 5)
    assert fired is not None and fired.grace == 3.5
    assert plan.due_spec("preempt", 5) is None   # one-shot
    # option/kind mismatches and negative windows are config errors
    for bad in ("nan@3?grace=1", "preempt@3?ms=5", "slow@3?grace=1",
                "slow@3?ms=-1", "preempt@3?grace=-2"):
        with pytest.raises(ValueError):
            faults_lib.FaultPlan.parse(bad)


def test_graceful_shutdown_preempt_notice(tmp_path, monkeypatch):
    """The advance-notice channel end to end in one process: notice file
    + SIGUSR1 -> noticed (grace from the file), idempotent on repeat,
    handlers restored on exit."""
    import signal

    from neural_networks_parallel_training_with_mpi_tpu.train import (
        resilience as res,
    )

    notice = tmp_path / "preempt-notice.json"
    monkeypatch.setenv(res.PREEMPT_NOTICE_ENV, str(notice))
    assert res.read_preempt_notice() is None      # absent: no notice yet
    assert res.write_preempt_notice(grace_s=4.5) == str(notice)
    rec = res.read_preempt_notice()
    assert rec["grace_s"] == 4.5 and "t_unix" in rec

    with res.GracefulShutdown() as stop:
        assert not stop.requested and not stop.noticed
        os.kill(os.getpid(), signal.SIGUSR1)
        assert stop.noticed and stop.requested
        assert stop.grace_s == 4.5                # read from the file
        os.kill(os.getpid(), signal.SIGUSR1)      # repeat: never escalates
        assert stop.noticed and stop.grace_s == 4.5
    assert signal.getsignal(signal.SIGUSR1) is signal.SIG_DFL

    # no file: PREEMPT_GRACE_ENV, then the 2 s default
    notice.unlink()
    monkeypatch.setenv(res.PREEMPT_GRACE_ENV, "7.25")
    with res.GracefulShutdown() as stop:
        os.kill(os.getpid(), signal.SIGUSR1)
        assert stop.grace_s == 7.25


# --------------------------------------------------------- guarded trainer


def _cfg(**kw):
    # lr=1e-3, momentum 0: the raw-scale regression targets put
    # momentum-0.9 lr>=0.003 in a chaotic/divergent regime (see
    # test_trainer.test_training_reduces_loss) — resilience tests need the
    # OPTIMIZER stable so the only instability is the injected one
    base = dict(nepochs=2, full_batch=False, batch_size=8, lr=1e-3,
                momentum=0.0, data=DataConfig(n_samples=32),
                mesh=MeshConfig(data=8))
    base.update(kw)
    return TrainConfig(**base)


def _poison(batch):
    batch = dict(batch)
    batch["mask"] = batch["mask"] * float("nan")
    return batch


@pytest.fixture(scope="session")
def mesh4x2(devices):
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )

    return make_mesh(MeshConfig(data=4, fsdp=2), devices=devices)


def test_guarded_step_dp_bitwise_noop(mesh8):
    """Acceptance: an injected NaN-gradient step leaves params/opt-state
    bitwise unchanged on the shard_map DP path.  TrainState.step still
    advances (it counts ATTEMPTED steps and drives the data order); the
    applied-update count lives in the inner optimizer state."""
    t = Trainer(_cfg(skip_nonfinite=True), mesh=mesh8)
    t.init_state()
    before_p = jax.device_get(t.state.params)
    before_o = jax.device_get(t.state.opt_state.inner)
    state1, loss = t.train_step(t.state, _poison(next(iter(t.loader.epoch(0)))))
    _leaves_equal(jax.device_get(state1.params), before_p)
    _leaves_equal(jax.device_get(state1.opt_state.inner), before_o)
    assert int(jax.device_get(state1.step)) == 1          # attempted
    assert int(jax.device_get(state1.opt_state.skipped)) == 1
    assert not np.isfinite(float(jax.device_get(loss)))
    # and the very next clean batch trains normally
    state2, loss2 = t.train_step(state1, next(iter(t.loader.epoch(1))))
    assert np.isfinite(float(jax.device_get(loss2)))
    assert int(jax.device_get(state2.opt_state.skipped)) == 1


def test_guarded_step_gspmd_bitwise_noop(mesh4x2):
    """Same invariant on the GSPMD (fsdp-sharded) path."""
    t = Trainer(_cfg(skip_nonfinite=True, mesh=MeshConfig(data=4, fsdp=2)),
                mesh=mesh4x2)
    assert t.gspmd
    t.init_state()
    before_p = jax.device_get(t.state.params)
    before_o = jax.device_get(t.state.opt_state.inner)
    state1, _ = t.train_step(t.state, _poison(next(iter(t.loader.epoch(0)))))
    _leaves_equal(jax.device_get(state1.params), before_p)
    _leaves_equal(jax.device_get(state1.opt_state.inner), before_o)
    assert int(jax.device_get(state1.opt_state.skipped)) == 1


def test_guard_composes_with_zero1(mesh8):
    """zero1's update consumes a scattered gradient SHARD, but the step
    psums the shard squares into the GLOBAL norm and hands it to the
    guard via Optimizer.update_with_norm — the skip predicate is
    identical on every replica, so the guard composes (it used to be
    refused here; tests/test_update_sharding.py pins the skip firing)."""
    t = Trainer(_cfg(skip_nonfinite=True, update_sharding="zero1"),
                mesh=mesh8)
    assert t.guarded and t.zero1
    r = t.fit()
    assert r["skipped_updates"] == 0 and np.isfinite(r["final_loss"])


@pytest.mark.parametrize("mesh_cfg", [MeshConfig(data=8),
                                      MeshConfig(data=4, fsdp=2)],
                         ids=["shard_map_dp", "gspmd"])
def test_skip_rollback_converge_story(tmp_path, mesh8, mesh4x2, mesh_cfg):
    """Acceptance: skip -> K-consecutive-skip rollback -> continued
    training to a finite final loss, on the shard_map DP path and the
    GSPMD path.  The NaN window (max=3 fires) poisons steps 10-12; the
    guard skips each, the monitor rolls back after K=2 bad losses and
    re-draws the data order, the exhausted injector lets training finish."""
    mesh = mesh8 if mesh_cfg.fsdp == 1 else mesh4x2
    cfg = _cfg(nepochs=6, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=2, mesh=mesh_cfg,
               checkpoint_dir=str(tmp_path), checkpoint_every=4,
               faults="nan@10-12?max=3")
    t = Trainer(cfg, mesh=mesh)
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert result["steps"] == 24                   # 6 epochs x 4 steps
    assert result["skipped_updates"] >= 1          # the guard fired
    assert result["rollbacks"] >= 1                # the monitor fired
    assert result["bad_steps"] >= 2
    # the final checkpoint is the completed run's
    assert ckpt.latest_step(str(tmp_path)) == 24


def test_anomaly_abort_after_rollback_budget(tmp_path, mesh8):
    """A PERSISTENT poison window (no max=) survives rollbacks; after
    max_rollbacks the monitor aborts — the supervisor's no-retry signal."""
    cfg = _cfg(nepochs=8, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=1, checkpoint_dir=str(tmp_path),
               checkpoint_every=2, faults="nan@4-999")
    with pytest.raises(AnomalyAbort, match="rollback budget"):
        Trainer(cfg, mesh=mesh8).fit()
    # the last good checkpoint survives (abort writes no final snapshot)
    assert ckpt.latest_step(str(tmp_path)) is not None


def test_rollback_without_checkpoint_restores_init(mesh8):
    """Before any snapshot exists, rollback restores the deterministic
    init (step 0) rather than failing."""
    cfg = _cfg(nepochs=3, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=2, faults="nan@1-2?max=2")
    t = Trainer(cfg, mesh=mesh8)
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    assert result["rollbacks"] == 1
    assert result["steps"] == 12  # restored to 0, re-ran 3 full epochs


def test_loader_order_salt(mesh8):
    """salt=0 keeps the historical (seed, epoch) stream bitwise intact;
    a bumped salt re-draws it (the rollback poison-window escape)."""
    from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
        ShardedLoader,
    )

    data = {"x": np.arange(64, dtype=np.float32).reshape(32, 2),
            "y": np.zeros((32, 1), np.float32)}
    mk = lambda: ShardedLoader(mesh8, data, 8, shuffle=True, seed=3)
    a, b = mk(), mk()
    np.testing.assert_array_equal(a._epoch_order(1), b._epoch_order(1))
    b.order_salt += 1
    assert not np.array_equal(a._epoch_order(1), b._epoch_order(1))
    # the salt must not leak into other epochs' determinism guarantees:
    # same salt -> same re-draw (rollback replay stays deterministic)
    c = mk()
    c.order_salt = 1
    np.testing.assert_array_equal(b._epoch_order(1), c._epoch_order(1))


def test_order_salt_persists_across_resume(tmp_path, mesh8):
    """The rollback re-draw salt rides in checkpoint metadata: a relaunch
    (crash + supervisor) must keep the re-drawn order instead of replaying
    the poison window and silently re-spending the rollback budget."""
    cfg = _cfg(nepochs=6, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=2, checkpoint_dir=str(tmp_path),
               checkpoint_every=4, faults="nan@10-12?max=3")
    t = Trainer(cfg, mesh=mesh8)
    result = t.fit()
    assert result["rollbacks"] == 1
    assert t.loader.order_salt == 1
    assert ckpt.read_meta(str(tmp_path))["order_salt"] == 1
    t2 = Trainer(dataclasses.replace(cfg, resume=True, faults=""),
                 mesh=mesh8)
    t2.init_state()
    t2.maybe_resume()
    assert t2.loader.order_salt == 1


def test_no_snapshot_while_bad_streak(tmp_path, mesh8):
    """Periodic saves are skipped while the monitor's bad-step streak is
    nonzero, so a diverging run cannot capture poisoned params or rotate
    the last good snapshot out (rollback's restore target survives)."""
    from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
        ResilienceMonitor,
    )

    m = ResilienceMonitor(rollback_after=100)
    m.observe(float("nan"))
    assert m.consecutive == 1  # the trainer's save gate keys off this
    # end-to-end: a persistent poison window from step 7 with
    # max_rollbacks=0 (first trigger aborts, so no final save either) —
    # boundaries inside the bad window must not add snapshots
    cfg = _cfg(nepochs=4, skip_nonfinite=True, rollback_after=2,
               max_rollbacks=0, checkpoint_dir=str(tmp_path),
               checkpoint_every=2, faults="nan@7-999")
    with pytest.raises(AnomalyAbort):
        Trainer(cfg, mesh=mesh8).fit()
    # observation lag is 2 dispatches: loss(7) is seen before the step-10
    # boundary fires, so the newest surviving snapshot is step 8's —
    # written while every observed loss was still clean
    assert ckpt.latest_step(str(tmp_path)) == 8


def test_eager_multihost_steps_per_dispatch_validation(mesh8, monkeypatch):
    """steps_per_dispatch > 1 + multi-host fails in Trainer.__init__, not
    lazily on the first epoch_groups iteration (ADVICE r5)."""
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(NotImplementedError, match="single-host"):
        Trainer(_cfg(steps_per_dispatch=2), mesh=mesh8)


# ------------------------------------------------- preemption-safe SIGTERM


def test_sigterm_graceful_exit_in_process(tmp_path, mesh8):
    """SIGTERM (self-injected at an exact step) -> flag at the next
    dispatch boundary -> final checkpoint at the current step -> fit
    returns normally with preempted=True, and the snapshot restores."""
    cfg = _cfg(nepochs=10, checkpoint_dir=str(tmp_path),
               faults="sigterm@7")
    t = Trainer(cfg, mesh=mesh8)
    result = t.fit()
    assert result.get("preempted") is True
    # the sigterm fires before the step-7 dispatch; that step still runs,
    # so exactly 8 steps completed — <= 1 step lost vs the signal
    assert result["steps"] == 8
    assert ckpt.latest_step(str(tmp_path)) == 8
    assert ckpt.read_meta(str(tmp_path))["step"] == 8
    # a resume picks up exactly there
    t2 = Trainer(dataclasses.replace(cfg, resume=True, faults=""),
                 mesh=mesh8)
    t2.init_state()
    assert t2.maybe_resume() == 8
    # handlers restored: pytest's own SIGINT handling is intact
    import signal

    assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL


def test_preempt_notice_in_process(tmp_path, mesh8):
    """Injected advance-notice preemption (faults kind ``preempt``):
    SIGUSR1 at step 7 -> the same dispatch-boundary final-checkpoint path
    as SIGTERM, but the result says preempt_notice (the CLI maps that to
    exit 47 so a supervisor retires instead of relaunching)."""
    cfg = _cfg(nepochs=10, checkpoint_dir=str(tmp_path),
               faults="preempt@7?grace=9")
    result = Trainer(cfg, mesh=mesh8).fit()
    assert result.get("preempt_notice") is True
    assert result.get("preempted") is True
    assert result["steps"] == 8                   # <= 1 step lost
    assert ckpt.latest_step(str(tmp_path)) == 8
    import signal

    assert signal.getsignal(signal.SIGUSR1) is signal.SIG_DFL


def test_sigterm_final_wait_surfaces_async_write_errors(tmp_path, mesh8,
                                                       monkeypatch):
    """A failing BACKGROUND checkpoint write must be re-raised by the
    final wait_pending() during graceful shutdown, not swallowed: the
    operator must know the 'final checkpoint' they are about to resume
    from is older than the run's last step."""
    monkeypatch.setattr(
        ckpt, "_write_npz",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")))
    cfg = _cfg(nepochs=10, checkpoint_dir=str(tmp_path), checkpoint_every=3,
               async_checkpoint=True, faults="sigterm@4")
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        Trainer(cfg, mesh=mesh8).fit()


# ---------------------------------------------------------------- supervisor


def test_exit_code_contract_pinned():
    """The contract is shared state across watchdog, faulty_child, cli and
    the supervisor — a change here is a deliberate migration."""
    assert (EXIT_OK, EXIT_HANG, EXIT_PEER, EXIT_ANOMALY) == (0, 42, 43, 44)


def test_strip_supervisor_flags():
    argv = ["--lr", "0.1", "--supervise", "3", "--supervise_backoff=0.5",
            "--nepochs", "2", "--supervise=4"]
    assert strip_supervisor_flags(argv) == ["--lr", "0.1", "--nepochs", "2"]


def test_supervise_policy_retry_and_stop():
    """Retry on crash up to max_restarts; never retry 0 or 44."""
    calls = []

    def run(code_seq):
        it = iter(code_seq)

        def fake_call(cmd, env=None):
            rc = next(it)
            calls.append(rc)
            return rc

        from neural_networks_parallel_training_with_mpi_tpu.train import (
            resilience as res,
        )

        orig = res.subprocess.call
        res.subprocess.call = fake_call
        try:
            return supervise(["x"], max_restarts=2, backoff=0.0,
                             _sleep=lambda s: None)
        finally:
            res.subprocess.call = orig

    calls.clear()
    assert run([1, 42, 0]) == 0           # crash, hang, success
    assert len(calls) == 3
    calls.clear()
    assert run([EXIT_ANOMALY]) == EXIT_ANOMALY   # 44: no retry
    assert len(calls) == 1
    calls.clear()
    assert run([7, 7, 7]) == 7            # budget exhausted -> last code
    assert len(calls) == 3


def _clean_env():
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        platform as plat,
    )

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop(faults_lib.ENV_VAR, None)
    plat.force_host_device_count(None, env=env)
    return env


def _cli(extra, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "2", "--dataset", "regression",
         "--n_samples", "32", "--batch_size", "8", "--no-full-batch",
         *extra],
        capture_output=True, text=True, timeout=timeout, env=_clean_env(),
        cwd=str(REPO))


def test_supervisor_relaunches_crash_and_resumes(tmp_path):
    """Acceptance: a child crashed at step N via fault injection is
    relaunched with backoff, resumes from the newest checkpoint, finishes
    the run, and exits 0."""
    out = _cli(["--nepochs", "4", "--checkpoint_dir", str(tmp_path / "c"),
                "--checkpoint_every", "3",
                "--faults", f"crash@9?once={tmp_path / 'crashed'}",
                "--supervise", "2", "--supervise_backoff", "0.1"])
    text = out.stdout + out.stderr
    assert out.returncode == 0, text[-3000:]
    assert "injected crash at step 9" in text
    assert "[supervise] attempt 2" in text
    assert "--resume" in text                     # relaunch resumes
    assert (tmp_path / "crashed").exists()        # crashed exactly once
    assert "[supervise] child completed" in text
    assert ckpt.latest_step(str(tmp_path / "c")) == 16  # 4 epochs x 4 steps


def test_supervisor_does_not_retry_anomaly_abort(tmp_path):
    """Acceptance: anomaly-abort (exit 44) after M rollbacks is NOT
    retried."""
    out = _cli(["--nepochs", "8", "--checkpoint_dir", str(tmp_path / "c"),
                "--checkpoint_every", "2", "--skip-nonfinite",
                "--rollback_after", "2", "--max_rollbacks", "1",
                "--faults", "nan@4-999",
                "--supervise", "3", "--supervise_backoff", "0.1"])
    text = out.stdout + out.stderr
    assert out.returncode == EXIT_ANOMALY, text[-3000:]
    assert "anomaly abort" in text
    assert "not retrying" in text
    assert "[supervise] attempt 1" in text
    assert "[supervise] attempt 2" not in text    # exactly one launch


def test_cli_sigterm_checkpoint_and_exit0(tmp_path):
    """Acceptance: SIGTERM mid-run -> valid final checkpoint (restorable,
    correct step in meta.json) and exit code 0."""
    d = tmp_path / "c"
    out = _cli(["--nepochs", "10", "--checkpoint_dir", str(d),
                "--faults", "sigterm@7"])
    text = out.stdout + out.stderr
    assert out.returncode == 0, text[-3000:]
    assert "caught signal 15" in text
    assert "preempted" in text
    assert ckpt.latest_step(str(d)) == 8
    assert ckpt.read_meta(str(d))["step"] == 8
    restored = ckpt.restore(str(d))
    assert int(np.asarray(restored.step)) == 8
    # and a --resume run completes the job from there
    out2 = _cli(["--nepochs", "10", "--checkpoint_dir", str(d), "--resume"])
    assert out2.returncode == 0, (out2.stdout + out2.stderr)[-3000:]
    assert ckpt.latest_step(str(d)) == 40


def test_cli_preempt_notice_exit47_not_retried(tmp_path):
    """Acceptance: an ADVANCE-notice preemption (SIGUSR1 mid-run) writes
    the same valid final checkpoint but exits 47 (decommission) — and a
    supervisor retires the slot instead of relaunching onto the doomed
    node (47 is in the no-retry set)."""
    d = tmp_path / "c"
    out = _cli(["--nepochs", "10", "--checkpoint_dir", str(d),
                "--faults", "preempt@7?grace=9",
                "--supervise", "3", "--supervise_backoff", "0.1"])
    text = out.stdout + out.stderr
    assert out.returncode == 47, text[-3000:]
    assert "preemption notice" in text
    assert "[supervise] attempt 2" not in text    # exactly one launch
    assert ckpt.latest_step(str(d)) == 8          # checkpoint still valid
    restored = ckpt.restore(str(d))
    assert int(np.asarray(restored.step)) == 8


# ---------------------------------------------------------------- overhead


@pytest.mark.slow
def test_guard_happy_path_overhead(mesh8):
    """The guard adds one global-norm reduction + a lax.cond per step and
    NO host sync.  At the CPU bench's transformer scale (4L/d256/T128/B64)
    the measured overhead is +0.9% (7825 -> 7896 ms/step) — under the 2%
    budget; this test uses a micro-model to stay test-lane-fast, where the
    fixed norm pass is proportionally larger, so the assert is loose and
    the printed number is the record."""
    import time

    def steptime(guard):
        cfg = _cfg(nepochs=1, skip_nonfinite=guard, batch_size=32,
                   data=DataConfig(dataset="lm", n_samples=64, seq_len=64,
                                   vocab_size=64),
                   model=ModelConfig(arch="transformer", n_layers=2,
                                     d_model=64, n_heads=4, d_ff=128,
                                     vocab_size=64, max_seq_len=64,
                                     attention="dense"),
                   loss="cross_entropy")
        t = Trainer(cfg, mesh=mesh8)
        t.init_state()
        batch = next(iter(t.loader.epoch(0)))
        state = t.state
        state, loss = t.train_step(state, batch)  # compile
        jax.block_until_ready(loss)
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = t.train_step(state, batch)
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / n

    base = min(steptime(False) for _ in range(3))
    guarded = min(steptime(True) for _ in range(3))
    ratio = guarded / base
    print(f"\nguarded-update overhead: {base * 1e3:.2f}ms -> "
          f"{guarded * 1e3:.2f}ms ({(ratio - 1) * 100:+.1f}%)")
    assert ratio < 1.25, f"guard overhead {ratio:.2f}x"
