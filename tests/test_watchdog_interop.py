"""Hang watchdog (failure detection, SURVEY.md §5.3) and
pipeline <-> dense checkpoint interop."""

import time

import jax
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig, build_argparser,
    config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.utils.watchdog import (
    HangWatchdog,
)


def test_watchdog_quiet_on_progress():
    exits = []
    with HangWatchdog(0.5, _exit=exits.append) as wd:
        for _ in range(8):
            time.sleep(0.1)
            wd.pat()
    assert exits == []


def test_watchdog_fires_on_stall(capsys):
    exits = []
    with HangWatchdog(0.3, _exit=exits.append) as wd:
        wd.pat()  # arm: the clock starts at the first completed step
        deadline = time.monotonic() + 3.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.05)  # no pats: simulated stalled device
    assert exits == [42]
    assert "HANG DETECTED" in capsys.readouterr().err


def test_watchdog_unarmed_never_fires():
    # first-step compile can exceed the timeout; until the first pat the
    # watchdog must stay quiet
    exits = []
    with HangWatchdog(0.2, _exit=exits.append):
        time.sleep(0.8)
    assert exits == []


def test_watchdog_suspension_covers_long_phases():
    exits = []
    with HangWatchdog(0.3, _exit=exits.append) as wd:
        wd.pat()
        with wd.suspended():  # e.g. an eval pass or checkpoint write
            time.sleep(0.8)
        time.sleep(0.1)
    assert exits == []


def test_watchdog_disabled_is_noop():
    with HangWatchdog(None) as wd:
        assert wd._thread is None
    with HangWatchdog(0.0) as wd:
        assert wd._thread is None


def test_cli_hang_and_backend_flags():
    args = build_argparser().parse_args(
        ["--hang_timeout", "60", "--data_backend", "auto",
         "--dataset", "lm", "--attention", "flash"])
    cfg = config_from_args(args)
    assert cfg.hang_timeout == 60.0
    assert cfg.data.backend == "auto"
    assert cfg.model.attention == "flash"


def test_cli_rejects_flash_with_sp():
    args = build_argparser().parse_args(
        ["--dataset", "lm", "--sp", "2", "--attention", "flash"])
    with pytest.raises(SystemExit):
        config_from_args(args)


def test_cli_rejects_ring_without_sp():
    args = build_argparser().parse_args(
        ["--dataset", "lm", "--attention", "ring"])
    with pytest.raises(SystemExit):
        config_from_args(args)


def test_trainer_rejects_hang_timeout_without_log_every():
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    cfg = TrainConfig(nepochs=1, hang_timeout=60.0, log_every=0,
                      data=DataConfig(dataset="regression", n_samples=64),
                      mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="hang_timeout"):
        Trainer(cfg)


def test_pipeline_checkpoint_interops_with_dense(tmp_path, mesh8):
    """A checkpoint written by a pipelined run restores into the dense
    model: unstack_blocks is the exact inverse of stack_blocks, so the
    pipelined layout is a pure re-view of the same logical params."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        pipeline as pp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    cfg = TransformerConfig(vocab_size=32, max_seq_len=16, n_layers=4,
                            d_model=32, n_heads=4, d_ff=64)
    model = Transformer(cfg)
    dense = model.init(prng.init_key(0))
    stacked = pp.stack_blocks(dense["blocks"], n_stages=2)
    roundtrip = pp.unstack_blocks(stacked)
    assert len(roundtrip) == 4
    for a, b in zip(dense["blocks"], roundtrip):
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    # and the dense/pipelined forward agree on the same logical params
    import jax.numpy as jnp

    ids = jnp.asarray(np.random.default_rng(0).integers(0, 32, (2, 16)),
                      jnp.int32)
    logits_dense = model.apply(dense, ids)
    restacked = dict(dense)
    restacked["blocks"] = pp.unstack_blocks(
        pp.stack_blocks(dense["blocks"], 2))
    logits_rt = model.apply(restacked, ids)
    np.testing.assert_allclose(np.asarray(logits_dense),
                               np.asarray(logits_rt), rtol=1e-6)
