"""Child process for the compiled-Pallas TPU smoke test.

Runs OUTSIDE the conftest CPU pin: the image's sitecustomize points JAX at
the axon TPU tunnel, so `jax.default_backend()` is 'tpu' when a chip is
reachable.  Compiles flash_attention (forward + the two Mosaic backward
kernels) and fused_layernorm through Mosaic and checks them against the
plain-JAX reference math in the same process.  Prints one JSON line;
the parent asserts on it (or skips when the probe fails/times out).
"""

import json
import sys

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print(json.dumps({"skip": f"backend={jax.default_backend()}"}))
        return 0

    from neural_networks_parallel_training_with_mpi_tpu.ops.pallas_kernels import (
        flash_attention, fused_layernorm,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.sequence import (
        attention_reference,
    )

    rng = np.random.default_rng(0)
    b, t, h, d = 2, 256, 4, 64
    mk = lambda: jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    q, k, v = mk(), mk(), mk()

    report = {"device_kind": jax.devices()[0].device_kind}

    # f32 checks run under matmul precision 'highest': at the TPU default
    # the MXU truncates f32 operands to bf16 in BOTH the kernel and the
    # reference, and the two round differently (~5e-3 apart) — pinning
    # precision makes the comparison test kernel MATH, not MXU rounding
    # (measured: max err drops 5e-3 -> 1e-6 on a v5e)
    with jax.default_matmul_precision("highest"):
        # forward, compiled through Mosaic (interpret=False)
        out = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, True, 128, 128, False)
        )(q, k, v)
        ref = attention_reference(q, k, v, causal=True)
        report["fwd_max_err"] = float(jnp.abs(out - ref).max())

        # backward: both Mosaic bwd kernels, vs autodiff of the dense
        # reference
        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, 128, 128, False) ** 2).sum()

        def loss_ref(q, k, v):
            return (attention_reference(q, k, v, causal=True) ** 2).sum()

        g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, bb in zip(("dq", "dk", "dv"), g_flash, g_ref):
            denom = float(jnp.abs(bb).max()) or 1.0
            report[f"bwd_{name}_rel_err"] = float(jnp.abs(a - bb).max()) / denom

        # exclusive-diagonal mode (striped ring blocks): compiled through
        # Mosaic, vs a strict-lower-triangle masked reference; the no-key
        # row 0 must come back exactly 0 with zero gradient
        from neural_networks_parallel_training_with_mpi_tpu.ops.pallas_kernels import (
            flash_attention_with_lse,
        )

        out_ex, lse_ex = jax.jit(
            lambda q, k, v: flash_attention_with_lse(
                q, k, v, True, 128, 128, False, "causal_exclusive")
        )(q, k, v)
        scale_a = 1.0 / np.sqrt(d)
        s_ref = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale_a
        mask = (jnp.arange(t)[None, :] < jnp.arange(t)[:, None])[None, None]
        probs = jax.nn.softmax(jnp.where(mask, s_ref, -1e30), axis=-1)
        ref_ex = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        report["excl_max_err"] = float(
            jnp.abs(out_ex[:, 1:] - ref_ex[:, 1:]).max())
        report["excl_row0_zero"] = bool(
            jnp.all(out_ex[:, 0] == 0.0))

        def loss_ex(q, k, v):
            o, _ = flash_attention_with_lse(q, k, v, True, 128, 128, False,
                                            "causal_exclusive")
            return (o ** 2).sum()

        gq_ex = jax.jit(jax.grad(loss_ex))(q, k, v)
        report["excl_grad_finite"] = bool(jnp.isfinite(gq_ex).all())

    # bf16 forward (the bench path): loose check against f32 reference
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out_bf16 = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, True, 128, 128, False)
    )(qb, kb, vb)
    report["fwd_bf16_max_err"] = float(
        jnp.abs(out_bf16.astype(jnp.float32) - ref).max())

    # fused layernorm, compiled
    x = jnp.asarray(rng.standard_normal((8, 128, 256)), jnp.float32)
    scale = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
    y = jax.jit(lambda x, s, b: fused_layernorm(x, s, b, interpret=False))(
        x, scale, bias)
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    y_ref = (x - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias
    report["ln_max_err"] = float(jnp.abs(y - y_ref).max())

    report["ok"] = (
        report["fwd_max_err"] < 2e-3
        and report["bwd_dq_rel_err"] < 2e-3
        and report["bwd_dk_rel_err"] < 2e-3
        and report["bwd_dv_rel_err"] < 2e-3
        and report["excl_max_err"] < 2e-3
        and report["excl_row0_zero"]
        and report["excl_grad_finite"]
        and report["fwd_bf16_max_err"] < 5e-2
        and report["ln_max_err"] < 2e-3
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
