"""Continuous-batching decode server (models.serve.DecodeServer).

The load-bearing property: a request decoded through the slot server —
batched with strangers, admitted mid-flight, finishing at its own time —
must emit exactly the tokens the single-stream generate() path emits for
the same prompt (greedy).  Plus the scheduling contract: slot reuse,
pool-full admission, staggered lifetimes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate,
)
from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
    DecodeServer,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

VOCAB = 64


def _model(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=64, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    base.update(kw)
    return Transformer(TransformerConfig(**base))


def _reference(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in np.asarray(out)[0]]


def test_single_request_matches_generate():
    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=4)
    rid = srv.submit([1, 2, 3], max_new_tokens=10)
    assert rid is not None and srv.live() == 1
    while not srv.done(rid):
        srv.step()
    assert srv.result(rid) == _reference(model, params, [1, 2, 3], 10)
    assert srv.live() == 0


def test_staggered_admission_exact_tokens():
    """Three requests joining at different times, different prompts and
    lengths, batched in flight — each must match its single-stream
    decode exactly (per-row attention reduces over the same values in
    the same order regardless of who shares the batch)."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=4)
    reqs = {}
    reqs[srv.submit([1, 2, 3], max_new_tokens=12)] = ([1, 2, 3], 12)
    srv.step(); srv.step()
    reqs[srv.submit([7, 8], max_new_tokens=6)] = ([7, 8], 6)
    srv.step()
    reqs[srv.submit([5, 9, 11, 13], max_new_tokens=9)] = ([5, 9, 11, 13], 9)
    for _ in range(40):
        if all(srv.done(r) for r in reqs):
            break
        srv.step()
    for rid, (prompt, n) in reqs.items():
        assert srv.result(rid) == _reference(model, params, prompt, n), rid


def test_slot_reuse_and_pool_full():
    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=2)
    a = srv.submit([1], max_new_tokens=4)
    b = srv.submit([2], max_new_tokens=20)
    assert srv.submit([3], max_new_tokens=4) is None      # pool full
    while not srv.done(a):
        srv.step()
    # a finished -> its slot is reclaimable while b is still in flight
    c = srv.submit([3], max_new_tokens=4)
    assert c is not None
    for _ in range(40):
        if srv.done(b) and srv.done(c):
            break
        srv.step()
    assert srv.result(a) == _reference(model, params, [1], 4)
    assert srv.result(b) == _reference(model, params, [2], 20)
    assert srv.result(c) == _reference(model, params, [3], 4)


def test_single_token_request():
    """max_new_tokens=1 completes at submit (prefill samples it)."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=2)
    rid = srv.submit([4, 5, 6], max_new_tokens=1)
    assert srv.done(rid)
    assert srv.result(rid) == _reference(model, params, [4, 5, 6], 1)


def test_gqa_server():
    """The per-row-position decode step's grouped-head branch."""
    model = _model(n_kv_heads=2)
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=2)
    rid = srv.submit([1, 2, 3], max_new_tokens=8)
    while not srv.done(rid):
        srv.step()
    assert srv.result(rid) == _reference(model, params, [1, 2, 3], 8)


def test_int8_weights_server():
    """Continuous batching on a quantized model (weights-only PTQ rides
    Linear.apply, so the server needs zero wiring)."""
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    model = _model()
    q = quantize_params(model.init(prng.init_key(0)))
    srv = DecodeServer(model, q, slots=2)
    rid = srv.submit([1, 2, 3], max_new_tokens=8)
    while not srv.done(rid):
        srv.step()
    assert srv.result(rid) == _reference(model, q, [1, 2, 3], 8)


def test_scan_layers_server():
    model = _model(scan_layers=True)
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=2)
    rid = srv.submit([9, 8, 7], max_new_tokens=6)
    while not srv.done(rid):
        srv.step()
    assert srv.result(rid) == _reference(model, params, [9, 8, 7], 6)


def test_int8_kv_cache_server():
    """kv_quant rides _block_chunk's shared int8 branch in the batched
    per-row-position step (the unification that replaced the duplicated
    token-batched block): greedy tokens must track the kv_quant
    single-stream decode exactly (identical quantization points: prefill
    chunk + one token per step)."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=2, kv_quant=True)
    assert srv.caches[0]["k"].dtype == jnp.int8
    rid = srv.submit([1, 2, 3], max_new_tokens=8)
    while not srv.done(rid):
        srv.step()
    want = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 8,
                    kv_quant=True)
    assert srv.result(rid) == [int(t) for t in np.asarray(want)[0]]


def test_done_raises_on_stale_or_unknown_rid():
    import pytest

    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=2)
    with pytest.raises(KeyError):
        srv.done(42)                       # never issued
    rid = srv.submit([1, 2], max_new_tokens=3)
    while not srv.done(rid):
        srv.step()
    srv.result(rid)
    with pytest.raises(KeyError):          # consumed: loud, not a spin
        srv.done(rid)


@pytest.mark.slow
def test_prefill_bucketing_exact_tokens():
    """Prompts of many lengths share log2(max_len) compiled prefill
    programs (padded to power-of-two buckets); pad positions' K/V are
    never attended, so tokens still match single-stream generate()."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = DecodeServer(model, params, slots=4)
    reqs = {}
    for prompt in ([1], [1, 2, 3, 4, 5], [3] * 9, [7] * 17):
        reqs[srv.submit(list(prompt), max_new_tokens=5)] = list(prompt)
    for _ in range(20):
        if all(rid in srv._results for rid in reqs):
            break
        srv.step()
    for rid, prompt in reqs.items():
        assert srv.result(rid) == _reference(model, params, prompt, 5), \
            prompt


@pytest.mark.slow
def test_moe_server():
    """MoE models flow through the slot server unchanged (_block_chunk's
    expert branch runs inside the batched per-row step); tokens equal the
    single-stream decode, with gated (SwiGLU) experts and int8 expert
    kernels stacked."""
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    model = _model(moe_experts=4, activation="swiglu", d_ff=48)
    params = quantize_params(model.init(prng.init_key(0)))
    srv = DecodeServer(model, params, slots=2)
    rid = srv.submit([1, 2, 3], max_new_tokens=8)
    while not srv.done(rid):
        srv.step()
    assert srv.result(rid) == _reference(model, params, [1, 2, 3], 8)


def test_server_chunked_prefill_exact():
    """prefill_chunk bounds the server's prefill attention memory
    (O(chunk * T) instead of O(bucket * T)); admission tokens must be
    identical to the unchunked server for prompts across bucket sizes,
    including boundaries that split unevenly."""
    model = _model()
    params = model.init(prng.init_key(0))
    plain = DecodeServer(model, params, slots=4)
    chunked = DecodeServer(model, params, slots=4, prefill_chunk=3)
    for prompt in ([1, 2], [1, 2, 3, 4, 5, 6, 7], [9] * 12):
        a = plain.submit(list(prompt), max_new_tokens=5)
        b = chunked.submit(list(prompt), max_new_tokens=5)
        while not plain.done(a):
            plain.step()
        while not chunked.done(b):
            chunked.step()
        assert plain.result(a) == chunked.result(b), prompt
