"""Shard-size math tests — the reference's Scatter/Scatterv replacement
(dataParallelTraining_NN_MPI.py:96-143), including the overflow regimes the
reference's int8 counts could not survive (bug B2, SURVEY.md §2.5)."""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.parallel import sharding as shd


def test_even_split_matches_reference_scatter():
    # reference even path: 16 rows / 8 procs = 2 each (:101-108)
    sizes = shd.shard_sizes(16, 8)
    assert sizes.tolist() == [2] * 8


@pytest.mark.parametrize("n,k", [(16, 3), (17, 4), (7, 8), (1, 8), (100, 7)])
def test_uneven_split_matches_reference_scatterv_policy(n, k):
    # reference uneven path: first `residue` shards get one extra row (:117)
    sizes = shd.shard_sizes(n, k)
    base, residue = divmod(n, k)
    assert sizes.sum() == n
    assert sizes.tolist() == [base + 1] * residue + [base] * (k - residue)
    offs = shd.shard_offsets(n, k)
    assert offs.tolist() == np.concatenate([[0], np.cumsum(sizes)[:-1]]).tolist()


def test_int8_overflow_regime_is_safe():
    # 43+ rows/shard overflowed the reference's int8 counts (bug B2)
    sizes = shd.shard_sizes(1_000_000, 3)
    assert sizes.dtype == np.int64
    assert sizes.sum() == 1_000_000
    assert sizes.max() >= 333_334


def test_pad_to_multiple():
    x = np.arange(14, dtype=np.float32).reshape(7, 2)
    padded, mask = shd.pad_to_multiple(x, 4)
    assert padded.shape == (8, 2)
    assert mask.tolist() == [1] * 7 + [0]
    np.testing.assert_array_equal(padded[:7], x)
    np.testing.assert_array_equal(padded[7], 0)

    same, mask = shd.pad_to_multiple(x, 7)
    assert same.shape == (7, 2) and mask.sum() == 7


def test_process_local_slice_covers_everything():
    spans = [shd.process_local_slice(17, 4, i) for i in range(4)]
    assert spans == [(0, 5), (5, 9), (9, 13), (13, 17)]


def test_shard_batch_places_on_data_axis(mesh8):
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    placed = shd.shard_batch(mesh8, {"x": x})["x"]
    assert placed.shape == (16, 2)
    # each of the 8 devices holds 2 rows
    assert len(placed.addressable_shards) == 8
    assert placed.addressable_shards[0].data.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(placed), x)


def test_loader_prefetch_yields_identical_batches(mesh8):
    """The threaded host-side prefetcher is a pure pipelining change: batch
    contents and order must be identical to the synchronous path."""
    from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
        ShardedLoader,
    )

    rng = np.random.default_rng(0)
    data = {"x": rng.standard_normal((40, 4)).astype(np.float32),
            "y": rng.standard_normal((40, 1)).astype(np.float32)}
    mk = lambda pf: ShardedLoader(mesh8, data, 16, shuffle=True, seed=3,
                                  prefetch=pf)
    for epoch in range(2):
        sync_batches = list(mk(0).epoch(epoch))
        pre_batches = list(mk(3).epoch(epoch))
        assert len(sync_batches) == len(pre_batches)
        for a, b in zip(sync_batches, pre_batches):
            for k in a:
                np.testing.assert_array_equal(np.asarray(a[k]),
                                              np.asarray(b[k]))


def test_loader_prefetch_propagates_worker_errors(mesh8):
    from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
        _thread_prefetch,
    )

    def boom():
        yield {"x": np.zeros((2, 2))}
        raise RuntimeError("worker exploded")

    it = _thread_prefetch(boom(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="worker exploded"):
        next(it)


def test_loader_prefetch_worker_exits_on_abandon(mesh8):
    """Abandoning the iterator (the Trainer's example-batch grab) must
    release the worker thread instead of parking it forever."""
    import threading
    import time

    from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
        ShardedLoader,
    )

    rng = np.random.default_rng(0)
    data = {"x": rng.standard_normal((64, 4)).astype(np.float32),
            "y": rng.standard_normal((64, 1)).astype(np.float32)}
    loader = ShardedLoader(mesh8, data, 8, shuffle=False, prefetch=2)
    before = {t.name for t in threading.enumerate()}
    it = loader.epoch(0)
    next(it)   # worker started, queue filling
    it.close()  # abandon -> GeneratorExit -> stop event
    deadline = time.time() + 3.0
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "loader-prefetch" and t.name not in before]
        if not any(t.is_alive() for t in alive):
            break
        time.sleep(0.05)
    assert not [t for t in threading.enumerate()
                if t.name == "loader-prefetch" and t.is_alive()], \
        "prefetch worker still parked after iterator close"
