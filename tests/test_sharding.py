"""Shard-size math tests — the reference's Scatter/Scatterv replacement
(dataParallelTraining_NN_MPI.py:96-143), including the overflow regimes the
reference's int8 counts could not survive (bug B2, SURVEY.md §2.5)."""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.parallel import sharding as shd


def test_even_split_matches_reference_scatter():
    # reference even path: 16 rows / 8 procs = 2 each (:101-108)
    sizes = shd.shard_sizes(16, 8)
    assert sizes.tolist() == [2] * 8


@pytest.mark.parametrize("n,k", [(16, 3), (17, 4), (7, 8), (1, 8), (100, 7)])
def test_uneven_split_matches_reference_scatterv_policy(n, k):
    # reference uneven path: first `residue` shards get one extra row (:117)
    sizes = shd.shard_sizes(n, k)
    base, residue = divmod(n, k)
    assert sizes.sum() == n
    assert sizes.tolist() == [base + 1] * residue + [base] * (k - residue)
    offs = shd.shard_offsets(n, k)
    assert offs.tolist() == np.concatenate([[0], np.cumsum(sizes)[:-1]]).tolist()


def test_int8_overflow_regime_is_safe():
    # 43+ rows/shard overflowed the reference's int8 counts (bug B2)
    sizes = shd.shard_sizes(1_000_000, 3)
    assert sizes.dtype == np.int64
    assert sizes.sum() == 1_000_000
    assert sizes.max() >= 333_334


def test_pad_to_multiple():
    x = np.arange(14, dtype=np.float32).reshape(7, 2)
    padded, mask = shd.pad_to_multiple(x, 4)
    assert padded.shape == (8, 2)
    assert mask.tolist() == [1] * 7 + [0]
    np.testing.assert_array_equal(padded[:7], x)
    np.testing.assert_array_equal(padded[7], 0)

    same, mask = shd.pad_to_multiple(x, 7)
    assert same.shape == (7, 2) and mask.sum() == 7


def test_process_local_slice_covers_everything():
    spans = [shd.process_local_slice(17, 4, i) for i in range(4)]
    assert spans == [(0, 5), (5, 9), (9, 13), (13, 17)]


def test_shard_batch_places_on_data_axis(mesh8):
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    placed = shd.shard_batch(mesh8, {"x": x})["x"]
    assert placed.shape == (16, 2)
    # each of the 8 devices holds 2 rows
    assert len(placed.addressable_shards) == 8
    assert placed.addressable_shards[0].data.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(placed), x)
