"""SwiGLU gated FFN (TransformerConfig.activation='swiglu' — Shazeer
2020): silu(x W_gate) * (x W_in) -> W_out, the modern-LM FFN.  The dense
tail is a single definition (Transformer._ffn) shared by training and
the KV-cache decode chunk, so the load-bearing checks are the param
shape, training, decode-vs-training parity, quantization of the third
projection, and the loud guards on the unwired TP/MoE paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

VOCAB, T = 64, 16


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=32, n_layers=2, d_model=32,
                n_heads=4, d_ff=48, activation="swiglu")
    base.update(kw)
    return TransformerConfig(**base)


def test_params_and_math():
    model = Transformer(_cfg())
    params = model.init(prng.init_key(0))
    blk = params["blocks"][0]
    assert blk["ff_gate"]["w"].shape == (32, 48)
    # hand-computed SwiGLU on one block's FFN == model._ffn
    mods = model._block_modules()
    h = jnp.asarray(np.random.default_rng(0).standard_normal((2, 4, 32)),
                    jnp.float32)
    want = (jax.nn.silu(h @ blk["ff_gate"]["w"] + blk["ff_gate"]["b"])
            * (h @ blk["ff_in"]["w"] + blk["ff_in"]["b"])) \
        @ blk["ff_out"]["w"] + blk["ff_out"]["b"]
    got = model._ffn(mods, blk, h)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_trains_and_fwd_flops_counts_gate():
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )

    model = Transformer(_cfg())
    gelu = Transformer(_cfg(activation="gelu"))
    assert (model.fwd_flops((2, T)) - gelu.fwd_flops((2, T))
            == 2 * 2.0 * 2 * T * 32 * 48)  # one extra (d, ff) matmul/layer
    mesh = mesh_lib.make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    opt = optim.sgd(lr=1e-2, momentum=0.0)
    state = dp.replicate_state(TrainState.create(model, opt,
                                                 prng.init_key(0)), mesh)
    step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                              "global_mean")
    rng = np.random.default_rng(0)
    batch = shd.shard_batch(mesh, {
        "x": rng.integers(0, VOCAB, (4, T)).astype(np.int32),
        "y": rng.integers(0, VOCAB, (4, T)).astype(np.int32),
        "mask": np.ones((4,), np.float32)})
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))
    g = jax.device_get(state.params["blocks"][0]["ff_gate"]["w"])
    base = jax.device_get(
        Transformer(_cfg()).init(prng.init_key(0))["blocks"][0][
            "ff_gate"]["w"])
    assert np.abs(g - base).max() > 0  # the gate actually trains


def test_decode_matches_training_forward_and_quantizes():
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        _forward_chunk, init_kv_cache,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    model = Transformer(_cfg())
    params = model.init(prng.init_key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, 8)),
                      jnp.int32)
    train_logits = model.apply(params, ids)
    cache_logits, _ = _forward_chunk(model, params,
                                     init_kv_cache(model, 2, 8), ids, 0)
    np.testing.assert_allclose(np.asarray(cache_logits),
                               np.asarray(train_logits),
                               rtol=2e-4, atol=2e-4)
    q = quantize_params(params)
    assert q["blocks"][0]["ff_gate"]["w"].dtype == jnp.int8
    out = generate(model, q, jnp.asarray([[1, 2, 3]], jnp.int32), 6)
    assert out.shape == (1, 9)


def test_llama_style_stack():
    """RoPE + GQA + SwiGLU — the full modern-LM configuration — trains a
    step and decodes through the continuous-batching server with exact
    single-stream parity."""
    from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
        DecodeServer,
    )

    model = Transformer(_cfg(pos_encoding="rope", n_kv_heads=2))
    params = model.init(prng.init_key(0))
    assert "pos" not in params
    srv = DecodeServer(model, params, slots=2)
    rid = srv.submit([1, 2, 3], max_new_tokens=6)
    while not srv.done(rid):
        srv.step()
    want = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 6)
    assert srv.result(rid) == [int(t) for t in np.asarray(want)[0]]


def test_tp_validates():
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    megatron.validate_tp(_cfg(), tp=2)  # SwiGLU wired under TP (round 4)


def test_swiglu_experts():
    """Gated MoE experts (round 4): per-expert w_gate/b_gate share
    w_in's column layout; logits are finite, the gate actually gates
    (zeroing it changes the output), and int8 PTQ quantizes the gate
    kernel with its own scales."""
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    model = Transformer(_cfg(moe_experts=4, moe_top_k=1))
    params = model.init(prng.init_key(0))
    ep = params["blocks"][0]["moe"]["experts"]
    assert ep["w_gate"].shape == (4, 32, 48)
    assert ep["b_gate"].shape == (4, 48)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, T)),
                      jnp.int32)
    out = model.apply(params, ids)
    assert np.isfinite(np.asarray(out)).all()
    zeroed = jax.tree_util.tree_map(lambda x: x, params)
    zeroed["blocks"][0]["moe"]["experts"]["w_gate"] = jnp.zeros_like(
        ep["w_gate"])
    zeroed["blocks"][0]["moe"]["experts"]["b_gate"] = jnp.zeros_like(
        ep["b_gate"])
    assert np.abs(np.asarray(model.apply(zeroed, ids) - out)).max() > 1e-3

    q = quantize_params(params)
    qep = q["blocks"][0]["moe"]["experts"]
    assert qep["w_gate"].dtype == jnp.int8
    assert qep["w_gate_scale"].shape == (4, 48)
    quant_out = model.apply(q, ids)
    assert np.asarray(jnp.abs(quant_out - out)).max() < 0.2


@pytest.mark.slow
def test_swiglu_moe_ep_trainer_matches_dp():
    """SwiGLU experts through the REAL expert-parallel path (all_to_all
    slot dispatch, per-rank expert shards including w_gate): trajectory
    parity against plain DP on the identical MoE model."""
    import dataclasses

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    def cfg(**mesh_kw):
        return TrainConfig(
            nepochs=2, batch_size=32, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                            vocab_size=VOCAB),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=48, ffn_activation="swiglu",
                              moe_experts=4, vocab_size=VOCAB,
                              max_seq_len=16),
            mesh=MeshConfig(**mesh_kw))

    r_dp = Trainer(cfg(data=8)).fit()
    c_ep = cfg(data=4, expert=2)
    c_ep.model = dataclasses.replace(c_ep.model,
                                     moe_expert_axis="expert")
    t_ep = Trainer(c_ep)
    r_ep = t_ep.fit()
    assert np.isfinite(r_ep["final_loss"])
    assert r_ep["final_loss"] == pytest.approx(r_dp["final_loss"],
                                               rel=2e-4)


@pytest.mark.slow
def test_swiglu_sp_tp_trainer_matches_dp():
    """SwiGLU through the REAL Megatron seq x tensor path: the gate is
    column-parallel with ff_in's exact column partition, so the local
    gated product is the local shard of the global one — pinned by full
    training-trajectory parity against plain DP on the same model."""
    import dataclasses

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    def cfg(**mesh_kw):
        return TrainConfig(
            nepochs=2, batch_size=32, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                            vocab_size=VOCAB),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=48, ffn_activation="swiglu",
                              vocab_size=VOCAB, max_seq_len=16),
            mesh=MeshConfig(**mesh_kw))

    r_dp = Trainer(cfg(data=8)).fit()
    c3 = cfg(data=2, seq=2, tensor=2)
    c3.model = dataclasses.replace(c3.model, attention="ring")
    t3 = Trainer(c3)
    assert t3.sp_tp
    r_3d = t3.fit()
    assert np.isfinite(r_3d["final_loss"])
    assert r_3d["final_loss"] == pytest.approx(r_dp["final_loss"],
                                               rel=2e-4)


def test_cli_ffn_activation_flag():
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        build_argparser, config_from_args,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.registry import (
        build_model,
    )

    args = build_argparser().parse_args(
        ["--dataset", "lm", "--ffn_activation", "swiglu"])
    model = build_model(config_from_args(args).model)
    assert model.cfg.activation == "swiglu"
    assert "ff_gate" in model.init(prng.init_key(0))["blocks"][0]
    # default stays gelu
    args0 = build_argparser().parse_args(["--dataset", "lm"])
    assert build_model(config_from_args(args0).model).cfg.activation == "gelu"
