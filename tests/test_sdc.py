"""Silent-data-corruption defense (utils.consistency SDC tiers,
train/trainer.py fingerprint monitor, DESIGN.md §9).

The load-bearing properties:

* the on-device fingerprint detects ANY single flipped bit in a
  replicated leaf (bit-exact uint32 fold, NaNs included) with O(1) host
  traffic, and is pure observation — params bitwise-identical with SDC
  checking on vs off;
* localization elects the MAJORITY shard group (a corrupt shard 0 is not
  mistaken for truth) and names leaf + shard + device;
* replay triage separates deterministic software bugs (abort, exit 45,
  never relaunched) from transient hardware faults (healed in place,
  bounded by a per-device strike budget);
* the chaos lane proves the full loop end to end through the CLI and the
  supervisor.
"""

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig, build_argparser,
    config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.train import (
    resilience,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
    Trainer,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    consistency, faults,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _cfg(**kw):
    base = dict(nepochs=2, full_batch=False, batch_size=8, lr=1e-3,
                momentum=0.9, data=DataConfig(n_samples=64),
                mesh=MeshConfig(data=8))
    base.update(kw)
    return TrainConfig(**base)


def _replicated(mesh8, x):
    return jax.device_put(x, NamedSharding(mesh8, P()))


def _flip(mesh8, leaf, shard, bit):
    return faults.flip_bit_in_shard(leaf, shard, bit)


# ------------------------------------------------------------- fingerprint


def test_fingerprint_healthy_is_bit_identical(mesh8):
    tree = {"w": _replicated(mesh8, jnp.ones((16, 16))),
            "step": _replicated(mesh8, jnp.zeros((), jnp.int32))}
    fpr = consistency.Fingerprinter(tree, mesh8)
    assert fpr.n_leaves == 2 and fpr.n_local_shards == 8
    d, f = consistency.Fingerprinter.fetch(fpr.compute(tree))
    assert not consistency.digests_differ(d)
    assert consistency.digest_report(d[None, :]) == {}
    assert np.all(f == f[0])


def test_fingerprint_detects_any_single_bitflip(mesh8):
    """Bit-exactness: one flipped bit — any bit, including exponent bits
    a float-sum fold could cancel — changes the digest of exactly the
    victim shard."""
    base = _replicated(mesh8, jnp.full((64, 64), 2.0))
    tree = {"w": base}
    fpr = consistency.Fingerprinter(tree, mesh8)
    for bit in (0, 12, 23, 30):
        bad = {"w": _flip(mesh8, base, shard=5, bit=bit)}
        d, _ = consistency.Fingerprinter.fetch(fpr.compute(bad))
        assert consistency.digests_differ(d), f"bit {bit} missed"
        others = np.delete(d, 5)
        assert np.all(others == others[0]) and d[5] != others[0]


def test_fingerprint_detects_nan_poisoned_shard(mesh8):
    base = _replicated(mesh8, jnp.ones((8, 8)))
    shards = base.addressable_shards
    datas = [np.asarray(s.data) for s in shards]
    datas[2] = datas[2].copy()
    datas[2][3, 3] = np.nan
    bad = jax.make_array_from_single_device_arrays(
        base.shape, base.sharding,
        [jax.device_put(d, s.device) for d, s in zip(datas, shards)])
    fpr = consistency.Fingerprinter({"w": base}, mesh8)
    d, _ = consistency.Fingerprinter.fetch(fpr.compute({"w": bad}))
    assert consistency.digests_differ(d)


def test_fingerprint_skips_sharded_leaves(mesh8):
    tree = {"w": _replicated(mesh8, jnp.ones((4, 4))),
            "x": jax.device_put(jnp.arange(16.0).reshape(16, 1),
                                NamedSharding(mesh8, P(("data", "fsdp"))))}
    fpr = consistency.Fingerprinter(tree, mesh8)
    assert fpr.paths == ["['w']"]


def test_digest_report_local_and_cross_verdicts():
    healthy = np.full((2, 4), 7, np.uint32)
    assert consistency.digest_report(healthy) == {}
    local = healthy.copy()
    local[1, 2] = 9  # process 1's devices disagree internally
    assert consistency.digest_report(local) == {
        "local": [1], "cross": [], "majority": 7}
    cross = np.array([[7, 7], [7, 7], [9, 9]], np.uint32)
    rep = consistency.digest_report(cross)  # host 2 consistent but wrong
    assert rep["local"] == [] and rep["cross"] == [2] and rep["majority"] == 7


# ------------------------------------------------- localization and healing


def test_divergence_report_names_leaf_shard_device(mesh8):
    base = _replicated(mesh8, jnp.full((8, 8), 3.0))
    bad = {"w": _flip(mesh8, base, shard=6, bit=9), "ok": base}
    rep = consistency.divergence_report(bad)
    assert list(rep) == ["['w']"]
    r = rep["['w']"]
    assert r["shards"] == [6] and r["reference_shard"] == 0
    assert r["n_bad_elements"] == 1 and 0 < r["max_abs_diff"] < 1e-3
    assert "6" in r["devices"][0]


def test_majority_vote_convicts_corrupt_shard_zero(mesh8):
    """Shard 0 is no oracle: when IT is the flipped one, the majority
    elects a healthy reference and shard 0 is the convict."""
    base = _replicated(mesh8, jnp.full((8, 8), 3.0))
    rep = consistency.divergence_report({"w": _flip(mesh8, base, 0, 9)})
    r = rep["['w']"]
    assert r["shards"] == [0] and r["reference_shard"] != 0


def test_heal_replication_restores_bitwise(mesh8):
    base = _replicated(mesh8, jnp.full((8, 8), 3.0))
    bad = {"w": _flip(mesh8, base, shard=4, bit=20), "b": base}
    healed, rep = consistency.heal_replication(bad)
    assert list(rep) == ["['w']"]
    assert consistency.check_replicas(healed) == {}
    # healthy leaves keep identity; healed leaf matches the majority bytes
    assert healed["b"] is bad["b"]
    np.testing.assert_array_equal(
        np.asarray(healed["w"].addressable_shards[4].data),
        np.asarray(base.addressable_shards[0].data))


# ----------------------------------------------------------- fault grammar


def test_sdc_fault_kinds_parse_and_options():
    plan = faults.FaultPlan.parse(
        "bitflip@5?param=blocks&shard=2&bit=7,desync@9?eps=0.01,"
        "desync@3?det")
    kinds = [f.kind for f in plan.faults]
    assert kinds == ["bitflip", "desync", "desync"]
    assert plan.faults[0].param == "blocks" and plan.faults[0].bit == 7
    assert plan.faults[1].eps == 0.01 and not plan.faults[1].det
    det = plan.det_desync()
    assert det is not None and det.start == 3
    with pytest.raises(ValueError, match="det"):
        faults.FaultPlan.parse("bitflip@5?det")


def test_apply_state_flips_exactly_one_bit(mesh8):
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import MLP
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    state = dp.replicate_state(
        TrainState.create(MLP(4, (8,), 1), optim.sgd(1e-2, momentum=0.9),
                          prng.init_key(0)), mesh8)
    plan = faults.FaultPlan.parse("bitflip@3?shard=2&bit=9")
    assert consistency.divergence_report(plan.apply_state(2, state)) == {}
    rep = consistency.divergence_report(plan.apply_state(3, state))
    (r,) = rep.values()
    assert r["shards"] == [2] and r["n_bad_elements"] == 1
    # desync hits the OPTIMIZER state
    plan2 = faults.FaultPlan.parse("desync@3?eps=0.5&shard=4")
    rep2 = consistency.divergence_report(plan2.apply_state(3, state))
    assert list(rep2) and all(".opt_state" in k for k in rep2)


# ------------------------------------------------------- the trainer loop


def test_bitflip_detect_localize_triage_heal_e2e(tmp_path, mesh8):
    """Acceptance core: a bitflip on one replica shard is detected within
    --sdc_check_every steps, localized to the injected leaf + shard,
    triaged as transient by replay, healed, and training continues to a
    finite loss with bit-identical replicas — while the telemetry stream
    carries the full SDC record."""
    d = str(tmp_path / "telem")
    cfg = _cfg(nepochs=3, sdc_check_every=1, telemetry_dir=d,
               faults="bitflip@5?shard=3&bit=9")
    t = Trainer(cfg, mesh=mesh8)
    res = t.fit()
    assert np.isfinite(res["final_loss"])
    assert res["sdc_incidents"] == 1 and res["sdc_healed"] == 1
    assert consistency.check_replicas(t.state) == {}
    recs = [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]
    (sdc,) = [r for r in recs if r.get("kind") == "sdc"]
    assert sdc["verdict"] == "transient" and sdc["action"] == "healed"
    (leaf,) = sdc["leaves"].values()
    assert leaf["shards"] == [3] and leaf["n_bad_elements"] == 1
    assert sdc["devices"] and "3" in sdc["devices"][0]
    # detection within the check cadence: flip at 5, detected by lag-2
    # on the very next boundary
    assert 5 <= sdc["step"] <= 5 + 2 * cfg.sdc_check_every
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert any(r.get("event") == "sdc" for r in pm["records"]
               if r.get("kind") == "event")


def test_desync_on_optimizer_state_heals_too(tmp_path, mesh8):
    cfg = _cfg(nepochs=3, sdc_check_every=1,
               faults="desync@6?eps=0.01&shard=5")
    t = Trainer(cfg, mesh=mesh8)
    res = t.fit()
    assert np.isfinite(res["final_loss"])
    assert res["sdc_incidents"] == 1 and res["sdc_healed"] == 1
    assert consistency.check_replicas(t.state) == {}


def test_params_bitwise_identical_sdc_on_off(tmp_path, mesh8):
    """Acceptance: the fingerprint is pure observation — healthy-path
    params are bitwise-identical with SDC checking on vs off (same
    discipline as the telemetry pin), including under k>1 dispatch."""
    def fit_params(sdc, k=1):
        cfg = _cfg(lr=1e-2, sdc_check_every=1 if sdc else 0,
                   steps_per_dispatch=k,
                   telemetry_dir=str(tmp_path / f"t{sdc}{k}")
                   if sdc else None)
        t = Trainer(cfg, mesh=mesh8)
        t.fit()
        return jax.device_get(t.state.params)

    for k in (1, 2):
        a, b = fit_params(False, k), fit_params(True, k)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_det_desync_aborts_deterministic(tmp_path, mesh8):
    """A divergence the step function REPRODUCES on replay is a software
    bug: abort with SDCAbort (exit 45 at the CLI) and a postmortem naming
    the leaf — healing would be lying."""
    d = str(tmp_path / "telem")
    cfg = _cfg(sdc_check_every=1, telemetry_dir=d,
               faults="desync@4?det&eps=0.001")
    t = Trainer(cfg, mesh=mesh8)
    with pytest.raises(resilience.SDCAbort, match="REPRODUCED on replay"):
        t.fit()
    recs = [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]
    (sdc,) = [r for r in recs if r.get("kind") == "sdc"]
    assert sdc["verdict"] == "deterministic"
    assert sdc["action"] == "abort_deterministic"
    assert sdc["leaves"]  # the diagnostic names the diverged leaf
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert "SDCAbort" in pm["reason"]


def test_strike_budget_aborts_repeatedly_flaky_device(mesh8):
    cfg = _cfg(nepochs=3, sdc_check_every=1, sdc_strikes=2,
               faults="bitflip@4?shard=3&bit=9,bitflip@10?shard=3&bit=9")
    t = Trainer(cfg, mesh=mesh8)
    with pytest.raises(resilience.SDCAbort, match="strike budget"):
        t.fit()
    assert t._sdc_policy.incidents == 2
    (dev, n), = t._sdc_policy.counts.items()
    assert "3" in dev and n == 2


def test_no_snapshot_of_unobserved_corrupt_state(tmp_path, mesh8):
    """The SDC analogue of PR 1's bad-streak snapshot skip: a snapshot
    boundary drains the fingerprint queue FIRST, so state the check has
    not yet cleared can never reach disk (and rotate the last good
    generation toward deletion).  With a strike budget of 1 the drain
    aborts at the corrupted boundary — the newest snapshot on disk must
    predate the corruption."""
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        checkpoint as ckpt,
    )

    ck = str(tmp_path / "ckpt")
    cfg = _cfg(nepochs=2, sdc_check_every=1, sdc_strikes=1,
               checkpoint_dir=ck, checkpoint_every=1,
               faults="bitflip@7?shard=2&bit=9")
    t = Trainer(cfg, mesh=mesh8)
    with pytest.raises(resilience.SDCAbort, match="strike budget"):
        t.fit()
    # the bitflip corrupts the state about to run step 7; the corrupted
    # post-step-7 state (counter 8) is fingerprint-flagged at its own
    # boundary and must NOT be saved — the newest snapshot stays the
    # pre-corruption counter-7 state written one iteration earlier
    # (before this guard, snapshot 8 was written first and carried the
    # flipped bytes to disk)
    assert ckpt.latest_step(ck) == 7


def test_legacy_check_replicas_is_detect_only(mesh8):
    """--check_replicas_every keeps its old contract (a divergence kills
    the run) but now detects via the lag-2 fingerprint and still
    localizes + triages before raising."""
    cfg = _cfg(check_replicas_every=1, faults="bitflip@4?shard=2&bit=9")
    t = Trainer(cfg, mesh=mesh8)
    assert not t.sdc_heal
    with pytest.raises(AssertionError, match="replica divergence"):
        t.fit()


def test_det_desync_refused_on_sharded_state_layouts(mesh8):
    with pytest.raises(NotImplementedError, match="desync"):
        Trainer(_cfg(mesh=MeshConfig(data=4, fsdp=2),
                     faults="desync@2?det"),
                mesh=None)


# --------------------------------------------------- policy and exit codes


def test_sdc_exit_code_contract_pinned():
    assert resilience.EXIT_SDC == 45
    assert resilience.EXIT_SDC in resilience._NO_RETRY
    p = resilience.SDCPolicy(strikes=2)
    assert p.record(["devA"]) == []
    assert p.record(["devB"]) == []
    assert p.record(["devA"]) == ["devA"]
    assert p.incidents == 3
    with pytest.raises(ValueError):
        resilience.SDCPolicy(strikes=0)


def test_supervisor_does_not_retry_exit_45(tmp_path):
    calls = []
    rc = resilience.supervise(
        [sys.executable, "-c", "import sys; sys.exit(45)"],
        max_restarts=3, backoff=0.01, log=calls.append,
        _sleep=lambda s: None)
    assert rc == 45
    assert any("not retrying" in m for m in calls)


def test_cli_flags_plumbed():
    args = build_argparser().parse_args(
        ["--sdc_check_every", "7", "--no-sdc-heal", "--sdc_strikes", "5",
         "--faults", "bitflip@3?shard=1&bit=4"])
    cfg = config_from_args(args)
    assert cfg.sdc_check_every == 7 and cfg.sdc_heal is False
    assert cfg.sdc_strikes == 5
    # defaults
    cfg2 = config_from_args(build_argparser().parse_args([]))
    assert cfg2.sdc_check_every == 0 and cfg2.sdc_heal is True
    assert cfg2.sdc_strikes == 3


# ------------------------------------------------------------ sdc_report


def test_sdc_report_tool(tmp_path, capsys):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import sdc_report
    finally:
        sys.path.pop(0)
    d = tmp_path / "telem"
    d.mkdir()
    recs = [
        {"kind": "step", "step": 1, "loss": 1.0},
        {"kind": "sdc", "step": 6, "verdict": "transient",
         "action": "healed", "devices": ["TFRT_CPU_3"],
         "leaves": {"w": {"shards": [3]}}, "strikes": {"TFRT_CPU_3": 1}},
        {"kind": "sdc", "step": 9, "verdict": "transient",
         "action": "abort_strikes", "devices": ["TFRT_CPU_3"],
         "leaves": {"w": {"shards": [3]}}, "strikes": {"TFRT_CPU_3": 2}},
    ]
    with open(d / "metrics.jsonl", "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    rc = sdc_report.main([str(d)])
    out = capsys.readouterr().out
    assert rc == 1  # abort_strikes => "do not just relaunch"
    assert "SDC incidents: 2" in out and "TFRT_CPU_3" in out
    rc_json = sdc_report.main([str(d), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc_json == 1
    assert doc["device_strikes"]["TFRT_CPU_3"] == 2
    assert doc["leaf_histogram"] == {"w": 2}
    assert doc["last_action"] == "abort_strikes"
    # healthy dir: exit 0
    d2 = tmp_path / "clean"
    d2.mkdir()
    (d2 / "metrics.jsonl").write_text(
        json.dumps({"kind": "step", "step": 1}) + "\n")
    assert sdc_report.main([str(d2)]) == 0
    assert "no SDC incidents" in capsys.readouterr().out


def test_sdc_report_is_stdlib_only(tmp_path):
    d = tmp_path / "telem"
    d.mkdir()
    (d / "metrics.jsonl").write_text(json.dumps(
        {"kind": "sdc", "step": 2, "verdict": "deterministic",
         "action": "abort_deterministic", "devices": ["dev0"],
         "leaves": {"w": {}}}) + "\n")
    # -S skips site-packages hooks: the tool must not import jax or the
    # package __init__ (same contract as ckpt_fsck/metrics_summary)
    proc = subprocess.run(
        [sys.executable, "-S", str(REPO / "tools" / "sdc_report.py"),
         str(d)], capture_output=True, text=True)
    assert proc.returncode == 1, proc.stderr  # deterministic => exit 1
    assert "DETERMINISTIC" in proc.stdout


# ------------------------------------------------------------- chaos lane


def _run_cli(args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu", "--platform",
         "cpu", "--num_devices", "8", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO))


@pytest.mark.chaos
@pytest.mark.slow
def test_cli_det_desync_exits_45_with_postmortem(tmp_path):
    """Acceptance: a deterministic desync injected in the step function
    aborts with the new exit code and a postmortem naming the leaf."""
    d = str(tmp_path / "telem")
    proc = _run_cli(["--nepochs", "2", "--batch_size", "8",
                     "--n_samples", "64", "--no-full-batch",
                     "--sdc_check_every", "1", "--telemetry_dir", d,
                     "--faults", "desync@4?det&eps=0.001"])
    assert proc.returncode == 45, (proc.stdout, proc.stderr)
    assert "SDC abort" in proc.stderr + proc.stdout
    pm = json.load(open(os.path.join(d, "postmortem.json")))
    assert "SDCAbort" in pm["reason"]
    (sdc,) = [r for r in pm["records"] if r.get("kind") == "event"
              and r.get("event") == "sdc"]
    assert sdc["verdict"] == "deterministic" and sdc["leaves"]


@pytest.mark.chaos
@pytest.mark.slow
def test_supervised_bitflip_heals_and_completes(tmp_path):
    """The full production story through the supervisor: a transient
    bitflip mid-run is healed in-process (no relaunch needed), the job
    completes exit 0, and the telemetry dir carries the incident record
    for tools/sdc_report.py."""
    d = str(tmp_path / "telem")
    ck = str(tmp_path / "ckpt")
    proc = _run_cli(["--nepochs", "3", "--batch_size", "8",
                     "--n_samples", "64", "--no-full-batch",
                     "--sdc_check_every", "1", "--telemetry_dir", d,
                     "--checkpoint_dir", ck, "--checkpoint_every", "4",
                     "--supervise", "1",
                     "--faults", "bitflip@5?shard=3&bit=9"],
                    timeout=420)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "attempt 2" not in proc.stderr  # healed, never relaunched
    recs = [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]
    sdc = [r for r in recs if r.get("kind") == "sdc"]
    assert len(sdc) == 1 and sdc[0]["action"] == "healed"
    # and the offline triage tool reads it
    rep = subprocess.run(
        [sys.executable, str(REPO / "tools" / "sdc_report.py"), d],
        capture_output=True, text=True)
    assert rep.returncode == 0
    assert "healed x1" in rep.stdout


# ------------------------------------------------------------- overhead


@pytest.mark.slow
def test_fingerprint_happy_path_overhead(mesh8):
    """Steady-state marginal cost of the fingerprint check: one extra
    tiny jitted dispatch per checked step plus a few-bytes lag-2 fetch
    (compile happens once per run and is excluded, as everywhere else in
    the suite).  Measured at the CPU bench's transformer scale
    (4L/d256/T128/B64) the delta is ~1% of step time (DESIGN.md §9);
    this micro-model run asserts loosely — the fixed fold/dispatch cost
    is proportionally much larger against a 2L/d64 step — and prints the
    measured number as the record."""
    import time

    cfg = _cfg(nepochs=1, batch_size=32, momentum=0.0,
               data=DataConfig(dataset="lm", n_samples=64, seq_len=64,
                               vocab_size=64),
               model=ModelConfig(arch="transformer", n_layers=2,
                                 d_model=64, n_heads=4, d_ff=128,
                                 vocab_size=64, max_seq_len=64,
                                 attention="dense"),
               loss="cross_entropy")
    t = Trainer(cfg, mesh=mesh8)
    t.init_state()
    batch = next(iter(t.loader.epoch(0)))
    fpr = consistency.Fingerprinter(t.state, t.mesh)
    state, out = t.train_step(t.state, batch)           # compile step
    jax.block_until_ready(out)
    consistency.Fingerprinter.fetch(fpr.compute(state))  # compile fp

    def steptime(sdc, n=20):
        nonlocal state
        q = []
        t0 = time.perf_counter()
        for _ in range(n):
            state, out = t.train_step(state, batch)
            if sdc:
                q.append(fpr.compute(state))
                if len(q) >= 2:  # the trainer's lag-2 fetch discipline
                    consistency.Fingerprinter.fetch(q.pop(0))
        jax.block_until_ready(out)
        while q:
            consistency.Fingerprinter.fetch(q.pop(0))
        return (time.perf_counter() - t0) / n

    # INTERLEAVED min-of-k pairs: grouping all base runs before all sdc
    # runs lets one host-load spike masquerade as overhead
    base = fp = None
    for _ in range(3):
        b, f = steptime(False), steptime(True)
        base = b if base is None else min(base, b)
        fp = f if fp is None else min(fp, f)
    ratio = fp / base
    print(f"\nsdc fingerprint overhead: {base * 1e3:.2f}ms -> "
          f"{fp * 1e3:.2f}ms per step ({(ratio - 1) * 100:+.1f}%)")
    assert ratio < 1.5, f"fingerprint overhead {ratio:.2f}x"
