"""The canonical bench JSON line under accelerator fallback (VERDICT r3
item 6): when the capture-time probe fails, the headline must be the
cached real-chip row — explicitly stamped — with this run's CPU number
demoted to a machine-readable mechanism check, so no driver-readable
artifact carries an unmarked sub-1.0 vs_baseline.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fallback_headline_is_cached_tpu_row():
    env = dict(os.environ)
    # force the probe to resolve fast and to cpu: the conftest already
    # stripped the tunnel env, so a 10s single attempt answers "cpu_only"
    # immediately and the fallback path engages
    env["BENCH_PROBE_TIMEOUT"] = "10"
    env["BENCH_PROBE_ATTEMPTS"] = "1"
    env["BENCH_PROBE_BACKOFF"] = "1"
    out = subprocess.run(
        [sys.executable, "bench.py", "--config", "toy", "--no-baseline"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-1500:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])

    # BENCH_TPU_LATEST.json (committed) holds a toy row, so the headline
    # must be the cached real-chip measurement, stamped as such
    assert rec["measurement"] == "cached_tpu"
    assert rec["platform_fallback"] is True
    assert rec["platform"] not in (None, "cpu")
    assert rec["captured_iso"] and rec["age_hours"] is not None
    assert rec["probe"]["attempts"] >= 1

    # this run's CPU number is inside, demoted and labeled
    cpu = rec["cpu_fallback_run"]
    assert cpu["role"] == "mechanism_check_on_fallback_host"
    assert cpu["platform"] == "cpu"
    assert cpu["value"] > 0

    # the invariant the schema exists for: a sub-1.0 vs_baseline is never
    # presented at top level without the fallback marker
    if (rec.get("vs_baseline") or 1.0) < 1.0:
        assert rec.get("platform_fallback") or rec.get("role")


def test_merge_artifact_rows(tmp_path):
    """The cross-window row-merge protocol both chip tools share: new
    success wins, an error row never clobbers a prior success, labels not
    re-run are kept, a brand-new error row is recorded."""
    sys.path.insert(0, REPO)
    import bench

    path = tmp_path / "rows.json"
    path.write_text(json.dumps({"results": [
        {"label": "a", "mfu": 0.3},
        {"label": "b", "mfu": 0.2},
        {"label": "c", "error": "old boom"},
    ]}))
    merged = bench.merge_artifact_rows(str(path), [
        {"label": "a", "error": "boom"},       # must NOT clobber prior a
        {"label": "b", "mfu": 0.25},           # new success wins
        {"label": "c", "error": "new boom"},   # error-over-error: new
        {"label": "d", "error": "fresh"},      # new label, error recorded
    ])
    by = {r["label"]: r for r in merged}
    assert by["a"] == {"label": "a", "mfu": 0.3}
    assert by["b"] == {"label": "b", "mfu": 0.25}
    assert by["c"] == {"label": "c", "error": "new boom"}
    assert by["d"] == {"label": "d", "error": "fresh"}
    # missing artifact: everything passes through
    merged2 = bench.merge_artifact_rows(str(tmp_path / "nope.json"),
                                        [{"label": "x", "mfu": 1.0}])
    assert merged2 == [{"label": "x", "mfu": 1.0}]


def test_committed_big_lm_sweep_row_matching():
    """The shared matcher behind the preflight's chip_validated gate AND
    the CPU-fallback headline: a BIGLM_SWEEP row speaks for the committed
    big_lm config only when EVERY knob matches (shapes, batch, remat,
    attention, ce_chunk, scan_layers, kernel tiles)."""
    sys.path.insert(0, REPO)
    import jax.numpy as jnp

    import bench

    cfg = bench._make_config("big_lm")
    mc = cfg["make_model"](jnp.bfloat16).cfg

    # the real artifact must contain a row for the committed config —
    # this is the invariant that keeps `bench.py --config big_lm` honest
    # on a wedged tunnel (the headline quotes a chip measurement of
    # exactly the committed knobs, stamped with its sweep label)
    row = bench.committed_big_lm_sweep_row(mc, cfg["batch"])
    assert row is not None, (
        "no BIGLM_SWEEP.json chip row matches the committed big_lm "
        "config — re-run tools/big_lm_sweep.py on the chip or revert "
        "the config flip")
    assert row.get("platform") == "tpu" and row.get("mfu")
    assert row.get("scan_layers") == mc.scan_layers
    assert row.get("ce_chunk", 0) == mc.ce_chunk

    # every knob is load-bearing: flip one -> no match.  (scan_layers is
    # NOT in this list on purpose: flipping it back to True matches the
    # genuine scanned-config chip rows from the earlier sweep windows —
    # exactly the legacy-default semantics the matcher implements.)
    import dataclasses
    for flip in (dict(ce_chunk=mc.ce_chunk + 128),
                 dict(remat=not mc.remat),
                 dict(attention="dense"),
                 dict(flash_block_k=512)):
        assert bench.committed_big_lm_sweep_row(
            dataclasses.replace(mc, **flip), cfg["batch"]) is None, flip
    assert bench.committed_big_lm_sweep_row(mc, cfg["batch"] + 1) is None
