"""RL workload (rl/): GAE property pins, env semantics, Anakin PPO
learning on gridworld, telemetry/resume bitwise pins, supervisor e2e.

Cheap pins run in the budgeted core lane; the subprocess supervisor run
is marked slow (full lane).  `-m rl` runs this lane alone.
"""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from neural_networks_parallel_training_with_mpi_tpu.config import (
    MeshConfig, ModelConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.models.registry import (
    build_model,
)
from neural_networks_parallel_training_with_mpi_tpu.ops import optim
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    mesh as mesh_lib,
)
from neural_networks_parallel_training_with_mpi_tpu.rl import (
    CartPole, GridWorld, anakin, gae_advantages, make_env,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    checkpoint as ckpt,
)

pytestmark = pytest.mark.rl

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# GAE: jitted scan vs a plain-numpy reference
# ---------------------------------------------------------------------------

def _numpy_gae(rewards, values, dones, last_value, gamma, lam):
    """The textbook backward recursion, written the slow obvious way."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    acc = np.zeros_like(last_value)
    for t in reversed(range(T)):
        v_next = last_value if t == T - 1 else values[t + 1]
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * v_next * nd - values[t]
        acc = delta + gamma * lam * nd * acc
        adv[t] = acc
    return adv, adv + values


def test_gae_matches_numpy_reference():
    """Property pin: random (rewards, values, dones, gamma, lam) draws —
    including episodes terminating mid-rollout, the boundary every
    hand-rolled GAE gets wrong — must match the numpy reference."""
    rng = np.random.default_rng(0)
    jitted = jax.jit(gae_advantages, static_argnames=("gamma", "lam"))
    for trial in range(20):
        T = int(rng.integers(1, 13))
        n = int(rng.integers(1, 5))
        rewards = rng.normal(size=(T, n)).astype(np.float32)
        values = rng.normal(size=(T, n)).astype(np.float32)
        # p=0.35: virtually every trial has mid-rollout terminations
        dones = (rng.random((T, n)) < 0.35).astype(np.float32)
        last_value = rng.normal(size=(n,)).astype(np.float32)
        gamma = float(rng.uniform(0.9, 1.0))
        lam = float(rng.uniform(0.8, 1.0))
        ref_adv, ref_ret = _numpy_gae(rewards, values, dones, last_value,
                                      gamma, lam)
        adv, ret = jitted(jnp.asarray(rewards), jnp.asarray(values),
                          jnp.asarray(dones), jnp.asarray(last_value),
                          gamma=gamma, lam=lam)
        np.testing.assert_allclose(np.asarray(adv), ref_adv, rtol=1e-5,
                                   atol=1e-5, err_msg=f"trial {trial}")
        np.testing.assert_allclose(np.asarray(ret), ref_ret, rtol=1e-5,
                                   atol=1e-5, err_msg=f"trial {trial}")


def test_gae_done_blocks_bootstrap_and_recursion():
    """A done at step k must cut BOTH the one-step bootstrap and the
    lambda recursion: advantages at t <= k are invariant to everything
    after k."""
    T, gamma, lam = 6, 0.99, 0.95
    rng = np.random.default_rng(1)
    rewards = rng.normal(size=(T, 1)).astype(np.float32)
    values = rng.normal(size=(T, 1)).astype(np.float32)
    dones = np.zeros((T, 1), np.float32)
    dones[3] = 1.0
    base_adv, _ = gae_advantages(jnp.asarray(rewards), jnp.asarray(values),
                                 jnp.asarray(dones), jnp.zeros((1,)),
                                 gamma, lam)
    # perturb everything past the boundary
    rewards2, values2 = rewards.copy(), values.copy()
    rewards2[4:] += 100.0
    values2[4:] -= 50.0
    pert_adv, _ = gae_advantages(jnp.asarray(rewards2),
                                 jnp.asarray(values2),
                                 jnp.asarray(dones),
                                 jnp.full((1,), 1e3, jnp.float32),
                                 gamma, lam)
    np.testing.assert_allclose(np.asarray(pert_adv[:4]),
                               np.asarray(base_adv[:4]), rtol=1e-6)
    assert not np.allclose(np.asarray(pert_adv[4:]),
                           np.asarray(base_adv[4:]))


# ---------------------------------------------------------------------------
# environments
# ---------------------------------------------------------------------------

def test_gridworld_semantics():
    env = GridWorld(size=5, max_steps=30)
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    assert obs.shape == (env.obs_dim,)
    assert float(jnp.sum(obs)) == pytest.approx(2.0)  # two one-hots
    # step onto the goal: from (4, 3), action 1 (right) -> (4, 4)
    state = {"pos": jnp.asarray([4, 3], jnp.int32),
             "t": jnp.asarray(5, jnp.int32)}
    nstate, nobs, reward, done = env.step(state, jnp.asarray(1), key)
    assert float(done) == 1.0
    assert float(reward) == pytest.approx(env.goal_reward)
    # auto-reset: carried state already belongs to a fresh episode
    assert int(nstate["t"]) == 0
    assert not bool(jnp.all(nstate["pos"] == 4))  # never spawns on goal
    # non-terminal step: penalty, t advances, no reset
    state = {"pos": jnp.asarray([0, 0], jnp.int32),
             "t": jnp.asarray(0, jnp.int32)}
    nstate, _, reward, done = env.step(state, jnp.asarray(2), key)
    assert float(done) == 0.0
    assert float(reward) == pytest.approx(-env.step_penalty)
    assert int(nstate["t"]) == 1
    assert nstate["pos"].tolist() == [1, 0]
    # edge clipping: moving up from row 0 is a no-op on the position
    nstate, _, _, _ = env.step(state, jnp.asarray(0), key)
    assert nstate["pos"].tolist() == [0, 0]
    # time-limit truncation counts as done
    state = {"pos": jnp.asarray([0, 0], jnp.int32),
             "t": jnp.asarray(env.max_steps - 1, jnp.int32)}
    nstate, _, _, done = env.step(state, jnp.asarray(3), key)
    assert float(done) == 1.0 and int(nstate["t"]) == 0


def test_cartpole_semantics():
    env = CartPole()
    key = jax.random.PRNGKey(2)
    state, obs = env.reset(key)
    assert obs.shape == (4,)
    assert bool(jnp.all(jnp.abs(obs) <= 0.05))
    # a near-upright pole does not fall in one step
    nstate, nobs, reward, done = env.step(state, jnp.asarray(1), key)
    assert float(reward) == 1.0 and float(done) == 0.0
    assert int(nstate["t"]) == 1
    # a pole past the angle threshold terminates (and auto-resets)
    state = {"x": jnp.asarray([0.0, 0.0, 0.5, 0.0], jnp.float32),
             "t": jnp.asarray(3, jnp.int32)}
    nstate, nobs, reward, done = env.step(state, jnp.asarray(0), key)
    assert float(done) == 1.0
    assert int(nstate["t"]) == 0
    assert bool(jnp.all(jnp.abs(nstate["x"]) <= 0.05))  # fresh episode


def test_make_env_registry():
    assert isinstance(make_env("gridworld"), GridWorld)
    assert isinstance(make_env("cartpole"), CartPole)
    with pytest.raises(ValueError, match="unknown env"):
        make_env("atari")


# ---------------------------------------------------------------------------
# the Anakin step
# ---------------------------------------------------------------------------

def _policy(env, hidden=(32, 32)):
    return build_model(ModelConfig(arch="mlp", in_features=env.obs_dim,
                                   hidden=hidden,
                                   out_features=env.n_actions + 1))


def _mesh():
    return mesh_lib.make_mesh(MeshConfig(data=8))


def _run(n_updates, lr, seed=0, with_metrics=True, guard=False,
         n_envs=16, T=16, env_name="gridworld", state=None, mesh=None):
    env = make_env(env_name)
    model = _policy(env)
    opt = optim.adam(lr=lr)
    if guard:
        opt = optim.with_skip_guard(opt)
    mesh = mesh or _mesh()
    if state is None:
        state = anakin.place_rl_state(
            anakin.init_rl_state(env, model, opt, n_envs, seed), mesh)
    step = anakin.make_anakin_step(env, model, opt, mesh, rollout_steps=T,
                                   with_metrics=with_metrics)
    outs = []
    for _ in range(n_updates):
        state, out = step(state)
        outs.append(jax.device_get(out))
    return state, outs


def _return_ema(outs):
    ema = None
    for o in outs:
        r = float(o["return_mean"])
        if np.isfinite(r):
            ema = r if ema is None else 0.9 * ema + 0.1 * r
    return ema


def test_anakin_gridworld_ppo_improves():
    """The acceptance pin: seeded gridworld PPO must beat the measured
    random-policy baseline within the step budget (deterministic — same
    seed, same mesh, same program every run)."""
    _, random_outs = _run(n_updates=10, lr=0.0, seed=0)
    baseline = _return_ema(random_outs)
    _, trained_outs = _run(n_updates=40, lr=3e-3, seed=0)
    trained = _return_ema(trained_outs)
    assert baseline is not None and trained is not None
    # measured on this config: baseline ~0.2-0.5 (timeouts at -0.3 mixed
    # with lucky random-walk goals), trained >0.9 (policy walks to the
    # goal); the margin is wide enough to be seed-robust
    assert trained > 0.85, f"trained EMA {trained} vs baseline {baseline}"
    assert trained > baseline + 0.2, (trained, baseline)
    # learning diagnostics: entropy must fall from its uniform-policy
    # start as the policy commits
    assert float(trained_outs[-1]["entropy"]) < float(
        trained_outs[0]["entropy"])


def test_anakin_telemetry_on_vs_off_bitwise():
    """Params after k updates must be BITWISE identical with the
    telemetry metrics vector on vs off — the same pin the DP LM step
    carries (train.telemetry: the metrics are computed from values the
    update already owns, never changing the update math).  Runs with the
    skip guard wired so the update_with_norm seam is exercised too."""
    s_on, _ = _run(n_updates=3, lr=3e-3, guard=True, with_metrics=True)
    s_off, _ = _run(n_updates=3, lr=3e-3, guard=True, with_metrics=False)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s_on.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s_off.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # env trajectories identical too (sampling never consults telemetry)
    np.testing.assert_array_equal(np.asarray(jax.device_get(s_on.obs)),
                                  np.asarray(jax.device_get(s_off.obs)))


def test_anakin_checkpoint_resume_bitwise(tmp_path):
    """Trajectory-exact resume: save mid-run through the manifest
    checkpoint layer, restore into a fresh placed state, continue — the
    final params/env state must be bitwise the uninterrupted run's
    (RLState round-trips env state, observations, running returns and
    the per-env PRNG keys)."""
    mesh = _mesh()
    straight, _ = _run(n_updates=6, lr=3e-3, mesh=mesh)

    half, _ = _run(n_updates=3, lr=3e-3, mesh=mesh)
    ckpt.save(str(tmp_path), half, keep=2,
              extra_meta={"workload": "rl"})
    env = make_env("gridworld")
    model = _policy(env)
    opt = optim.adam(lr=3e-3)
    template = anakin.place_rl_state(
        anakin.init_rl_state(env, model, opt, 16, 0), mesh)
    restored = ckpt.restore(str(tmp_path), template)
    assert restored is not None
    assert int(np.asarray(restored.step)) == 3
    resumed, _ = _run(n_updates=3, lr=3e-3, mesh=mesh,
                      state=anakin.place_rl_state(restored, mesh))

    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(straight)),
                    jax.tree_util.tree_leaves(jax.device_get(resumed))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_anakin_elastic_restore_refuses_env_count_change(tmp_path):
    """Elastic restore must never treat env-state leaves as repaddable
    optimizer padding: a checkpoint saved with one --rl_envs restored
    into a template with another must REFUSE (loud shape mismatch), not
    silently zero-extend env state/obs/keys.  (RLState's opt_state is
    NOT its trailing field — this pins checkpoint._restore_npz's
    field-ordered opt-leaf range.)"""
    mesh = _mesh()
    env = make_env("gridworld")
    model = _policy(env)
    opt = optim.adam(lr=3e-3)
    state = anakin.place_rl_state(
        anakin.init_rl_state(env, model, opt, 16, 0), mesh)
    ckpt.save(str(tmp_path), state, keep=1)
    template = anakin.place_rl_state(
        anakin.init_rl_state(env, model, opt, 24, 0), mesh)
    with pytest.raises(ValueError, match="wrong model config"):
        ckpt.restore(str(tmp_path), template, elastic=True)


def test_anakin_guarded_update_skips_nonfinite():
    """The skip guard rides the RL step unchanged: poisoning the params
    to produce a non-finite gradient must leave params bitwise untouched
    and tick the cumulative skip counter."""
    env = make_env("gridworld")
    model = _policy(env)
    opt = optim.with_skip_guard(optim.adam(lr=3e-3))
    mesh = _mesh()
    state = anakin.place_rl_state(
        anakin.init_rl_state(env, model, opt, 16, 0), mesh)
    step = anakin.make_anakin_step(env, model, opt, mesh, rollout_steps=4,
                                   with_metrics=True, ppo_epochs=1)
    # poison one param leaf -> NaN logits -> NaN loss/grads.  (A NaN
    # action distribution still samples; the guard must reject the
    # update, not crash.)
    flat, treedef = jax.tree_util.tree_flatten(state.params)
    poisoned = [flat[0] * float("nan")] + flat[1:]
    bad_params = jax.tree_util.tree_unflatten(treedef, poisoned)
    bad_state = state._replace(params=bad_params)
    bad_host = jax.device_get(bad_params)  # the step donates its input
    new_state, out = step(bad_state)
    assert int(jax.device_get(new_state.opt_state.skipped)) == 1
    # a skipped step is a bitwise no-op on EVERY param leaf (NaNs
    # compare equal bytewise via the uint32 view)
    for got, want in zip(
            jax.tree_util.tree_leaves(jax.device_get(new_state.params)),
            jax.tree_util.tree_leaves(bad_host)):
        np.testing.assert_array_equal(np.asarray(got).view(np.uint32),
                                      np.asarray(want).view(np.uint32))


def test_rl_runner_rejects_batch_poison_fault():
    """A chaos run asking for the host-batch 'nan' fault must refuse
    loudly (RL frames are generated on device — the fault would inject
    nothing and the run would pass vacuously); the state kinds
    (bitflip/desync) remain the RL-compatible SDC faults."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        RLConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.rl.runner import (
        RLRunner,
    )

    cfg = TrainConfig(workload="rl", faults="nan@3",
                      rl=RLConfig(n_envs=16, rollout_steps=4))
    with pytest.raises(NotImplementedError, match="nan"):
        RLRunner(cfg)


def test_anakin_step_flops_accounting():
    """The MFU numerator must charge T actor forwards + the bootstrap +
    ppo_epochs fwd/bwd — not pretend the step is one supervised pass."""
    env = make_env("gridworld")
    model = _policy(env)
    fwd = model.fwd_flops((1, env.obs_dim))
    per_frame = anakin.anakin_step_flops(model, env.obs_dim,
                                         rollout_steps=32, ppo_epochs=4)
    assert per_frame == pytest.approx(fwd * (1 + 1 / 32 + 12))
    assert anakin.anakin_step_flops(model, env.obs_dim, 32, 1) < per_frame


def test_donation_audit_anakin_step_all_leaves_aliased():
    """The donation audit (utils.profiling.donation_report) extended to
    the RL step: the fused rollout+GAE+PPO program donates its RLState
    (params, opt state, env state, obs, returns, per-env keys) and the
    compiler must alias EVERY leaf in/out — an RLState leaf migrating to
    unaliased_donors means a silent per-update copy of the env buffers."""
    from neural_networks_parallel_training_with_mpi_tpu.utils.profiling import (
        donation_report,
    )

    env = make_env("gridworld")
    mesh = _mesh()
    model = _policy(env)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    state = anakin.place_rl_state(
        anakin.init_rl_state(env, model, opt, 16, 0), mesh)
    step = anakin.make_anakin_step(env, model, opt, mesh, rollout_steps=4)
    rep = donation_report(step.lower(state).compile())
    assert rep["n_aliased"] == len(jax.tree_util.tree_leaves(state)), rep
    assert rep["unaliased_donors"] == 0, rep


# ---------------------------------------------------------------------------
# CLI / supervisor e2e (subprocess — full lane)
# ---------------------------------------------------------------------------

def _clean_env():
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        platform as plat,
    )

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    plat.force_host_device_count(None, env=env)
    return env


@pytest.mark.slow
def test_cli_rl_supervisor_crash_resumes(tmp_path):
    """Acceptance pin: an injected crash mid-RL-run under --supervise
    relaunches, restores from the newest VERIFIED checkpoint, and
    completes exit 0 — with ZERO RL-specific resilience code (the point
    is reuse: utils.faults + train.resilience.supervise + the manifest
    checkpoint layer operate on the RL process unchanged)."""
    ck = tmp_path / "ck"
    marker = tmp_path / "crash_marker"
    out = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--workload", "rl", "--platform", "cpu", "--num_devices", "8",
         "--rl_envs", "16", "--rollout_steps", "8", "--rl_updates", "10",
         "--optimizer", "adam", "--lr", "3e-3", "--seed", "5",
         "--checkpoint_dir", str(ck), "--checkpoint_every", "3",
         "--supervise", "2", "--supervise_backoff", "0.2",
         "--faults", f"crash@5?once={marker}"],
        capture_output=True, text=True, timeout=600, env=_clean_env(),
        cwd=str(REPO))
    text = out.stdout + out.stderr
    assert out.returncode == 0, text[-3000:]
    assert "injected crash at step 5" in text
    assert marker.exists()  # the fault fired exactly once
    assert "done: final loss" in text
    # the run completed all 10 updates across the crash
    assert ckpt.latest_step(str(ck)) == 10


@pytest.mark.slow
def test_cli_rl_cartpole_completes():
    """The second env end to end through the CLI (no checkpointing —
    pure workload smoke)."""
    out = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--workload", "rl", "--rl_env", "cartpole", "--platform", "cpu",
         "--num_devices", "8", "--rl_envs", "16", "--rollout_steps", "8",
         "--rl_updates", "4", "--optimizer", "adam"],
        capture_output=True, text=True, timeout=300, env=_clean_env(),
        cwd=str(REPO))
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    assert "done: final loss" in out.stdout + out.stderr
