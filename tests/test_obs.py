"""Fleet observability plane (DESIGN.md §7): rollup sketch snapshots,
cross-process aggregation, SLO burn-rate alerting, per-request flow
traces, per-role heartbeats.

Pins, by acceptance criterion:

* **rollups**: trainer and serving scheduler emit ``kind="rollup"``
  records carrying SERIALIZED sketch state + the (process, run,
  incarnation) identity; a final rollup lands at flush/close.
* **alerts**: a nan-poisoned loss raises ``loss_nonfinite``; missed
  deadlines past the error budget raise ``slo_burn_rate``; ``alerts``
  off silences both; the supervisor summarizes a child's alerts next
  to its exit (observe-and-annotate).
* **fleet merge**: ``tools/obs_agg.py`` merges N dirs into fleet.json
  whose percentiles match exact numpy within the sketches' STATED
  rank-error bound, Prometheus text exposition + the /metrics endpoint
  serve the same numbers, and a stale non-final heartbeat raises
  ``heartbeat_stale``.
* **heartbeat collision**: a trainer and a serving replica sharing one
  telemetry dir own separate ``heartbeat-<role>-p<P>.json`` files;
  legacy readers resolve through the back-compat fallback.
* **flow traces**: one request's admit -> prefill -> decode -> retire
  is a connected s/t/f flow chain in the trace, rendered as Chrome
  flow events by trace_report; a bounded tracer's dropped-span footer
  surfaces as TRUNCATED in the merged summary.

Cheap pins run in the budgeted core lane; the supervised-fault
acceptance e2e is slow/chaos.  ``-m obs`` runs the lane alone.
"""

import glob
import json
import math
import os
import pathlib
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.train import (
    resilience,
    telemetry as telemetry_lib,
    trace as trace_lib,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
    Trainer,
)
from neural_networks_parallel_training_with_mpi_tpu.utils.sketches import (
    QuantileSketch,
)

pytestmark = pytest.mark.obs

REPO = pathlib.Path(__file__).resolve().parent.parent
OBS_AGG = REPO / "tools" / "obs_agg.py"


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"_obs_{name}", str(REPO / "tools" / f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cfg(**kw):
    base = dict(nepochs=2, full_batch=False, batch_size=8, lr=1e-3,
                momentum=0.0, data=DataConfig(n_samples=32),
                mesh=MeshConfig(data=8), metrics_every=1)
    base.update(kw)
    return TrainConfig(**base)


def _records(d):
    with open(os.path.join(d, "metrics.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def _tiny_serve(tmp_path, tag="s", n_requests=25, slo_ms=None, **cfg_kw):
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (  # noqa: E501
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve.scheduler import (  # noqa: E501
        Scheduler, ServeConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    model = Transformer(TransformerConfig(
        vocab_size=32, max_seq_len=64, n_layers=1, d_model=16,
        n_heads=2, d_ff=32))
    params = model.init(prng.init_key(0))
    tdir = str(tmp_path / tag)
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=24, block_size=8, telemetry_dir=tdir,
        metrics_every=2, default_slo_ms=slo_ms, **cfg_kw))
    rids = [sched.submit([1 + i % 5, 2, 3], 4) for i in range(n_requests)]
    sched.run_until_drained()
    return sched, tdir, rids


# ------------------------------------------------------------------ rollups

def test_trainer_rollups_carry_sketches_and_identity(tmp_path, mesh8,
                                                     monkeypatch):
    monkeypatch.setenv(trace_lib.RUN_ID_ENV, "r-obs")
    monkeypatch.setenv(trace_lib.INCARNATION_ENV, "2")
    d = str(tmp_path / "t")
    t = Trainer(_cfg(nepochs=4, telemetry_dir=d, rollup_every=4),
                mesh=mesh8)
    t.fit()
    recs = _records(d)
    rollups = [r for r in recs if r["kind"] == "rollup"]
    steps = [r for r in recs if r["kind"] == "step"]
    assert rollups, "no rollup records at rollup_every=4"
    last = rollups[-1]
    # identity triple (the PR 10 correlation channel) + role stamp
    assert last["run"] == "r-obs" and last["inc"] == 2
    assert last["role"] == "train" and "t_unix" in last
    # serialized sketch STATE, not point stats — and it round-trips
    # into quantiles consistent with the raw step stream
    losses = [r["loss"] for r in steps]
    sk = QuantileSketch.from_dict(last["sketches"]["loss"])
    assert sk.n == len(losses)
    assert sk.quantile(0.0) == min(losses)
    assert sk.quantile(1.0) == max(losses)
    exact = float(np.quantile(np.array(losses), 0.5,
                              method="inverted_cdf"))
    rank = sorted(losses).index(sk.quantile(0.5))
    target = math.ceil(0.5 * len(losses)) - 1
    assert abs(rank - target) <= max(
        1, math.ceil(sk.rank_error_bound * sk.n)), (exact, sk.quantile(0.5))
    assert last["counters"]["metrics_records"] == len(steps)
    # the final rollup is the flush-time snapshot: it covers ALL steps
    assert last["step"] == steps[-1]["step"]


def test_trainer_rollups_off_by_default(tmp_path, mesh8):
    d = str(tmp_path / "t")
    Trainer(_cfg(telemetry_dir=d), mesh=mesh8).fit()
    assert not [r for r in _records(d) if r["kind"] == "rollup"]


# ------------------------------------------------------------------- alerts

def test_nonfinite_loss_alert_and_opt_out(tmp_path, mesh8):
    def run(alerts):
        d = str(tmp_path / f"t{alerts}")
        t = Trainer(_cfg(nepochs=2, skip_nonfinite=True,
                         faults="nan@3?max=1", telemetry_dir=d,
                         alerts=alerts), mesh=mesh8)
        t.fit()
        return ([r for r in _records(d) if r["kind"] == "alert"],
                t.telemetry)

    alerts, telem = run(True)
    assert any(a["alert"] == "loss_nonfinite" for a in alerts)
    a = next(a for a in alerts if a["alert"] == "loss_nonfinite")
    assert a["role"] == "train" and "t_unix" in a and a["step"] >= 3
    # the non-finite value is STRINGIFIED so the record (and any
    # fleet.json it is copied into) stays strict JSON
    assert a["value"] == "nan"
    assert json.loads(json.dumps(a, allow_nan=False))["value"] == "nan"
    # the flight recorder saw it too (a postmortem shows what fired)
    assert any(r.get("event") == "alert"
               for r in telem.recorder.records)
    assert telem.alerts_fired == len(alerts)
    off, _ = run(False)
    assert not off


def test_slo_burn_rate_alert_fires_and_is_quiet_without_slo(tmp_path):
    # 0.001ms SLO: every request misses -> burn rate >> threshold
    sched, tdir, _ = _tiny_serve(tmp_path, "hot", n_requests=25,
                                 slo_ms=0.001, rollup_every=8)
    sched.close()
    recs = _records(tdir)
    alerts = [r for r in recs if r["kind"] == "alert"]
    assert alerts and all(a["alert"] == "slo_burn_rate" for a in alerts)
    assert alerts[0]["burn_rate"] >= 2.0 and alerts[0]["role"] == "serve"
    rollup = [r for r in recs if r["kind"] == "rollup"][-1]
    assert rollup["counters"]["deadline_missed"] == 25
    assert rollup["counters"]["slo_events"] == 25
    # SLO-less requests never burn the budget
    quiet, qdir, _ = _tiny_serve(tmp_path, "quiet", n_requests=25)
    quiet.close()
    assert not [r for r in _records(qdir) if r["kind"] == "alert"]
    # ...and the sketch state still rolled up on close despite the
    # cadence never being crossed mid-run
    sched3, tdir3, _ = _tiny_serve(tmp_path, "final", n_requests=3,
                                   rollup_every=10 ** 6)
    sched3.close()
    finals = [r for r in _records(tdir3) if r["kind"] == "rollup"]
    assert len(finals) == 1 and "ttft_ms" in finals[0]["sketches"]


def test_supervise_annotates_child_alerts(tmp_path):
    metrics = tmp_path / "metrics.jsonl"
    alert = {"kind": "alert", "alert": "slo_burn_rate",
             "t_unix": round(time.time(), 3)}
    child = (f"import json; open({str(metrics)!r}, 'a').write("
             f"json.dumps({alert!r}) + '\\n'); raise SystemExit(7)")
    logs = []
    rc = resilience.supervise(
        [sys.executable, "-c", child], max_restarts=1, backoff=0.0,
        log=logs.append, alerts_path=str(metrics),
        _sleep=lambda s: None)
    assert rc == 7
    annotated = [m for m in logs if "telemetry alert(s)" in m]
    # one launch + one relaunch -> each child's alert annotated once
    assert len(annotated) == 2
    assert "slo_burn_rate x1" in annotated[0] and "observe-only" in \
        annotated[0]


# ------------------------------------------------- per-role heartbeats

def test_shared_dir_heartbeats_do_not_collide(tmp_path, mesh8):
    d = str(tmp_path / "shared")
    Trainer(_cfg(telemetry_dir=d), mesh=mesh8).fit()
    sched, _, _ = _tiny_serve(tmp_path, "unused", n_requests=2)
    # point the serving telemetry at the SAME dir (two writers, one dir)
    sched.close()
    sched2, tdir2, _ = _tiny_serve(pathlib.Path(d).parent, "shared",
                                   n_requests=2)
    sched2.close()
    assert tdir2 == d
    names = sorted(os.path.basename(p)
                   for p in glob.glob(os.path.join(d, "heartbeat*.json")))
    assert names == ["heartbeat-serve-p0.json", "heartbeat-train-p0.json"]
    # each file carries ITS writer's final step — no last-writer-wins
    train_hb = json.load(open(os.path.join(d, names[1])))
    serve_hb = json.load(open(os.path.join(d, names[0])))
    assert train_hb["step"] == 8           # 2 epochs x 4 steps
    assert serve_hb["step"] == sched2.tick_no
    # back-compat reads: the legacy shared path resolves to the
    # freshest role file, for both the age and the document
    legacy = os.path.join(d, "heartbeat.json")
    assert not os.path.exists(legacy)
    assert resilience.heartbeat_age_s(legacy) is not None
    assert telemetry_lib.read_heartbeat(legacy) == serve_hb
    # a directory path works too (obs_agg/metrics_summary convention)
    assert resilience.heartbeat_age_s(d) is not None
    # staleness stays PER ROLE: age the serve file artificially and the
    # train file still reads fresh through its exact path
    old = time.time() - 1000
    os.utime(os.path.join(d, names[0]), (old, old))
    assert resilience.heartbeat_age_s(
        os.path.join(d, names[0])) > 900
    assert resilience.heartbeat_age_s(
        os.path.join(d, names[1])) < 900
    # ...and the legacy fallback reports the freshest (train) one
    assert resilience.heartbeat_age_s(legacy) < 900
    # a MISSING role-qualified path never falls back to a sibling: the
    # hang monitor must not read a co-resident process's beats as its
    # own child's health (that would re-create the collision blindness)
    assert resilience.heartbeat_age_s(
        os.path.join(d, "heartbeat-train-p7.json")) is None
    assert resilience.heartbeat_filename("train", 0) == \
        "heartbeat-train-p0.json"


# ------------------------------------------------------- flow traces

def test_request_flow_chain_and_chrome_export(tmp_path):
    trace_dir = str(tmp_path / "trace")
    sched, tdir, rids = _tiny_serve(tmp_path, "flow", n_requests=3,
                                    trace_dir=trace_dir)
    sched.close()
    flows = []
    for p in glob.glob(os.path.join(trace_dir, "trace-*.jsonl")):
        for line in open(p):
            rec = json.loads(line)
            if rec.get("kind") == "flow":
                flows.append(rec)
    rid = rids[0]
    chain = [f for f in flows if f.get("rid") == rid]
    phases = [f["fph"] for f in chain]
    stages = [f.get("stage") for f in chain]
    # admit starts the flow, prefill chunks + decode ticks step it,
    # retire finishes it — one connected arrow path per request
    assert phases[0] == "s" and phases[-1] == "f"
    assert stages[0] == "admit" and stages[-1] == "retire"
    assert "prefill" in stages and "decode" in stages
    assert all(p == "t" for p in phases[1:-1])
    assert len({f["id"] for f in chain}) == 1
    # trace_report renders them as Chrome flow events bound to slices
    tr = _load_tool("trace_report")
    data = tr.load_dir(trace_dir)
    chrome = tr.to_chrome(data)
    evs = [e for e in chrome["traceEvents"]
           if e.get("cat") == "flow" and e.get("id") ==
           chain[0]["id"]]
    assert [e["ph"] for e in evs] == phases
    assert evs[-1]["bp"] == "e"
    summary = tr.summarize(data)
    assert summary["groups"][0]["n_flows"] == len(flows)


def test_trace_report_surfaces_dropped_footer(tmp_path):
    tracer = trace_lib.Tracer(str(tmp_path), process_id=1, run_id="r",
                              incarnation=0, max_events=5)
    trace_lib.install(tracer)
    try:
        for i in range(9):
            with trace_lib.span("tick", i=i):
                pass
    finally:
        trace_lib.stop_run(tracer)
    tr = _load_tool("trace_report")
    summary = tr.summarize(tr.load_dir(str(tmp_path)))
    g = summary["groups"][0]
    assert g["n_spans"] == 5 and g["dropped_spans"] == 4
    assert summary["dropped_spans_total"] == 4
    text = tr.render_text(summary)
    assert "TRUNCATED: 4 span(s)" in text


# ------------------------------------------------------ fleet aggregation

def _write_rollup_dir(tmp_path, tag, role, samples, p=0, inc=0,
                      counters=None, gauges=None, run="r-fleet"):
    """A telemetry dir containing one hand-built rollup (the aggregator
    contract is the record schema, not the writer)."""
    d = tmp_path / tag
    d.mkdir(exist_ok=True)
    sk = QuantileSketch()
    for v in samples:
        sk.add(float(v))
    rec = {"kind": "rollup", "role": role, "step": len(samples),
           "t": 1.0, "t_unix": round(time.time(), 3), "p": p,
           "inc": inc, "run": run,
           "sketches": {"ttft_ms": sk.to_dict()},
           "counters": dict(counters or {}),
           "gauges": {k: {"last": v, "t": time.time(), "min": v,
                          "max": v} for k, v in (gauges or {}).items()}}
    with open(d / "metrics.jsonl", "a") as f:
        f.write(json.dumps(rec) + "\n")
    return str(d)


def test_obs_agg_merges_within_stated_bound(tmp_path):
    agg = _load_tool("obs_agg")
    rng = np.random.default_rng(5)
    shards = [rng.lognormal(3.0, 1.0, int(rng.integers(100, 800)))
              for _ in range(3)]
    dirs = [
        _write_rollup_dir(tmp_path, f"d{i}", "serve", s, p=i,
                          counters={"tokens_out": 100 * (i + 1)},
                          gauges={"tokens_per_sec": 50.0 * (i + 1)})
        for i, s in enumerate(shards)]
    doc = agg.aggregate(dirs)
    serve = doc["roles"]["serve"]
    assert serve["writers"] == 3
    merged = serve["sketches"]["ttft_ms"]
    data = np.sort(np.concatenate(shards))
    n = len(data)
    assert merged["n"] == n
    bound = merged["rank_error_bound"]
    assert bound <= 0.0101  # one K-way merge level: 2 * eps
    for q_name, q in (("p50", 0.5), ("p99", 0.99)):
        ans = merged[q_name]
        lo = np.searchsorted(data, ans, side="left") + 1
        hi = np.searchsorted(data, ans, side="right")
        target = max(1, math.ceil(q * n))
        err = (0 if lo <= target <= hi
               else min(abs(lo - target), abs(hi - target))) / n
        assert err <= bound + 1.0 / n, (q_name, err, bound)
    # counters SUM across identities; additive gauges sum too
    # (100+200+300 tokens; 50+100+150 tok/s)
    assert serve["counters"]["tokens_out"] == 600
    assert serve["gauges"]["tokens_per_sec"] == 300.0
    assert doc["fleet"]["tokens_per_sec"] == 300.0


def test_obs_agg_gauges_only_from_latest_incarnation(tmp_path):
    agg = _load_tool("obs_agg")
    d = _write_rollup_dir(tmp_path, "d", "serve", [1.0], inc=0,
                          counters={"tokens_out": 100},
                          gauges={"tokens_per_sec": 999.0})
    _write_rollup_dir(tmp_path, "d", "serve", [2.0, 3.0], inc=1,
                      counters={"tokens_out": 40},
                      gauges={"tokens_per_sec": 10.0})
    doc = agg.aggregate([d])
    serve = doc["roles"]["serve"]
    # counters: both incarnations' work happened -> 140; gauges: only
    # the live incarnation's rate is current load -> 10, not 1009
    assert serve["counters"]["tokens_out"] == 140
    assert serve["gauges"]["tokens_per_sec"] == 10.0
    # sketches merge across incarnations (all that latency was served)
    assert serve["sketches"]["ttft_ms"]["n"] == 3


def test_obs_agg_heartbeat_stale_alert_and_window(tmp_path):
    agg = _load_tool("obs_agg")
    d = _write_rollup_dir(tmp_path, "d", "serve", [1.0, 2.0])
    hb = os.path.join(d, "heartbeat-serve-p0.json")
    json.dump({"step": 5}, open(hb, "w"))
    old = time.time() - 500
    os.utime(hb, (old, old))
    # an EXPIRED alert record must fall out of the fleet window
    with open(os.path.join(d, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({"kind": "alert", "alert": "loss_zscore",
                            "t_unix": time.time() - 9999}) + "\n")
    doc = agg.aggregate([d], stale_after_s=120.0, alert_window_s=3600.0)
    assert doc["alerts"]["by_name"] == {"heartbeat_stale": 1}
    stale = doc["alerts"]["recent"][-1]
    assert stale["age_s"] > 400 and stale["role"] == "serve"
    # a FINAL heartbeat is a finished run, not a stale one
    json.dump({"step": 5, "final": True}, open(hb, "w"))
    os.utime(hb, (old, old))
    doc2 = agg.aggregate([d], stale_after_s=120.0)
    assert doc2["alerts"]["n"] == 0


def test_obs_agg_fleet_json_prometheus_and_http(tmp_path):
    agg = _load_tool("obs_agg")
    d = _write_rollup_dir(tmp_path, "d", "serve", [10.0, 20.0, 30.0],
                          counters={"tokens_out": 7},
                          gauges={"queue_depth": 2.0})
    out = tmp_path / "fleet.json"
    prom_path = tmp_path / "fleet.prom"
    rc = agg.main([d, "--out", str(out), "--prom", str(prom_path)])
    assert rc == 0
    fleet = json.load(open(out))
    assert fleet["roles"]["serve"]["sketches"]["ttft_ms"]["p50"] == 20.0
    prom = open(prom_path).read()
    assert "# TYPE nnpt_ttft_ms summary" in prom
    assert 'nnpt_ttft_ms{role="serve",quantile="0.99"} 30.0' in prom
    assert 'nnpt_tokens_out_total{role="serve"} 7' in prom
    # gauges live in a '_current' family disjoint from any summary of
    # the same series (one family must not mix sample types)
    assert 'nnpt_queue_depth_current{role="serve"} 2.0' in prom
    # the optional http.server endpoint serves the same two documents
    server = agg.make_http_server(0, lambda: agg.aggregate([d]))
    import threading

    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read()
        assert b"nnpt_ttft_ms" in body
        fleet_doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet.json", timeout=10).read())
        assert fleet_doc["roles"]["serve"]["counters"]["tokens_out"] == 7
    finally:
        server.shutdown()
        server.server_close()
    # dashboard rendering is plain text over the same doc
    text = agg.render_dashboard(agg.aggregate([d]))
    assert "NNPT FLEET" in text and "ttft" in text


def test_obs_agg_python_S_smoke(tmp_path):
    """python -S (no site-packages): the aggregator must run on a
    jax-less ops host — the ckpt_fsck convention, wired into the core
    lane."""
    d = _write_rollup_dir(tmp_path, "d", "serve", [1.0, 2.0, 3.0])
    out = subprocess.run(
        [sys.executable, "-S", str(OBS_AGG), d, "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["roles"]["serve"]["sketches"]["ttft_ms"]["n"] == 3
    miss = subprocess.run(
        [sys.executable, "-S", str(OBS_AGG), str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=120)
    assert miss.returncode == 2


# ----------------------------------------- metrics_summary composition

def test_metrics_summary_composes_alert_and_rollup_views(tmp_path,
                                                         capsys):
    sched, tdir, _ = _tiny_serve(tmp_path, "ms", n_requests=25,
                                 slo_ms=0.001, rollup_every=8)
    sched.close()
    ms = _load_tool("metrics_summary")
    capsys.readouterr()  # drain the serve run's own log lines
    assert ms.main([tdir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["alerts"]["by_name"].get("slo_burn_rate", 0) >= 1
    assert doc["rollups"]["serve"]["sketches"]["ttft_ms"]["n"] == 25
    assert doc["rollups"]["serve"]["counters"]["deadline_missed"] == 25
    assert doc["heartbeat"]["final"] is True  # per-role file resolved
    # text render names the alerts and the rollup percentiles
    assert ms.main([tdir]) == 0
    text = capsys.readouterr().out
    assert "ALERTS:" in text and "slo_burn_rate" in text
    assert "rollups [serve]" in text and "ttft_ms" in text


# ------------------------------------------------- acceptance e2e (chaos)

@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_acceptance_train_fault_plus_serving(tmp_path):
    """The ISSUE 14 acceptance path: a supervised training run with an
    injected nan fault and a concurrent serving loadgen run, each with
    its own telemetry dir, aggregate via tools/obs_agg.py into one
    fleet.json whose merged serving percentiles match single-process
    ground truth within the sketch's stated bound; the anomaly's alert
    is visible in the fleet view and the Prometheus exposition; a
    request flow chain exists in the serving trace."""
    train_dir = tmp_path / "telem_train"
    serve_dir = tmp_path / "telem_serve"
    trace_dir = str(tmp_path / "serve_trace")
    env = {k: v for k, v in os.environ.items()
           if k not in ("NNPT_RUN_ID", "NNPT_INCARNATION")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    out = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "2", "--dataset",
         "regression", "--n_samples", "32", "--batch_size", "8",
         "--no-full-batch", "--nepochs", "4", "--skip-nonfinite",
         "--faults", "nan@5?max=1", "--telemetry_dir", str(train_dir),
         "--rollup_every", "4", "--checkpoint_dir",
         str(tmp_path / "ck"), "--supervise", "1",
         "--supervise_backoff", "0.1"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=str(REPO))
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]

    # concurrent serving workload with its own dir + flow trace
    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        loadgen,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (  # noqa: E501
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve.scheduler import (  # noqa: E501
        Scheduler, ServeConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    model = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=64, n_layers=1, d_model=16,
        n_heads=2, d_ff=32))
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=48, block_size=8,
        telemetry_dir=str(serve_dir), metrics_every=4, rollup_every=16,
        trace_dir=trace_dir, default_slo_ms=0.001))
    row = loadgen.run_closed_loop(sched, clients=4,
                                  requests_per_client=8, vocab_size=64)
    truth = sorted(s.ttft_ms for s in
                   [sched.stats(r) for r in range(sched.completed)]
                   if s.ttft_ms is not None)
    sched.close()

    agg = _load_tool("obs_agg")
    fleet_path = tmp_path / "fleet.json"
    prom_path = tmp_path / "fleet.prom"
    rc = agg.main([str(train_dir), str(serve_dir), "--out",
                   str(fleet_path), "--prom", str(prom_path)])
    assert rc == 0
    fleet = json.load(open(fleet_path))
    # both roles merged into one fleet view
    assert set(fleet["roles"]) == {"serve", "train"}
    # merged serving percentiles vs single-process ground truth, within
    # the sketch's stated rank-error bound
    merged = fleet["roles"]["serve"]["sketches"]["ttft_ms"]
    n = len(truth)
    assert merged["n"] == n == row["requests"]
    bound = merged["rank_error_bound"]
    for q_name, q in (("p50", 0.5), ("p99", 0.99)):
        ans = merged[q_name]
        arr = np.asarray(truth)
        lo = np.searchsorted(arr, ans, side="left") + 1
        hi = np.searchsorted(arr, ans, side="right")
        target = max(1, math.ceil(q * n))
        err = (0 if lo <= target <= hi
               else min(abs(lo - target), abs(hi - target))) / n
        assert err <= bound + 1.0 / n, (q_name, ans, err, bound)
    # train MFU rode the rollups into the fleet view
    assert "mfu" in fleet["roles"]["train"]["sketches"]
    # the training anomaly and the SLO burn are fleet-visible alerts
    assert fleet["alerts"]["by_name"].get("loss_nonfinite")
    assert fleet["alerts"]["by_name"].get("slo_burn_rate")
    prom = open(prom_path).read()
    assert "nnpt_alerts_by_name{alert=\"loss_nonfinite\"}" in prom
    assert "nnpt_ttft_ms{role=\"serve\",quantile=\"0.99\"}" in prom
    # one request's full flow chain exists in the serving trace
    flows = []
    for p in glob.glob(os.path.join(trace_dir, "trace-*.jsonl")):
        for line in open(p):
            rec = json.loads(line)
            if rec.get("kind") == "flow" and rec.get("rid") == 0:
                flows.append(rec)
    phases = [f["fph"] for f in flows]
    assert phases[0] == "s" and phases[-1] == "f" and "t" in phases
    # the supervisor's relaunch log annotated the child's alerts
    assert "telemetry alert(s)" in (out.stdout + out.stderr)
