"""Sequence-parallel attention must reproduce dense attention exactly —
ring (ppermute ring + online softmax) and ulysses (all-to-all) vs the
full-sequence reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.parallel import sequence as sq
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    devs = jax.devices("cpu")[:4]
    return make_mesh(MeshConfig(data=1, seq=4), devices=devs)


def _qkv(b=2, t=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((b, t, h, d)).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    ring = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ring_attention(a, b_, c, axis="seq", causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    uly = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ulysses_attention(a, b_, c, axis="seq", causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = uly(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_flow(seq_mesh):
    """Ring attention must be differentiable through the ppermute chain."""
    q, k, v = _qkv(t=16)

    def loss_dense(q, k, v):
        return (sq.attention_reference(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        out = jax.shard_map(
            lambda a, b_, c: sq.ring_attention(a, b_, c, axis="seq"),
            mesh=seq_mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(q, k, v)
        return (out ** 2).sum()

    g_dense = jax.grad(loss_dense)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ring = jax.jit(jax.grad(loss_ring))(jnp.asarray(q), jnp.asarray(k),
                                          jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=1e-3, atol=1e-4)


def test_transformer_seq_parallel_matches_dense(seq_mesh):
    """Full model: ring-attention Transformer under seq sharding == dense
    Transformer on one device."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    dense_cfg = TransformerConfig(vocab_size=64, max_seq_len=t, n_layers=2,
                                  d_model=32, n_heads=4, d_ff=64,
                                  attention="dense")
    ring_cfg = TransformerConfig(vocab_size=64, max_seq_len=t, n_layers=2,
                                 d_model=32, n_heads=4, d_ff=64,
                                 attention="ring")
    dense_model, ring_model = Transformer(dense_cfg), Transformer(ring_cfg)
    params = dense_model.init(prng.init_key(0))
    ids = np.random.default_rng(0).integers(0, 64, (2, t)).astype(np.int32)

    expected = dense_model.apply(params, jnp.asarray(ids))
    got = jax.jit(jax.shard_map(
        lambda p, i: ring_model.apply(p, i),
        mesh=seq_mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_attention_matches_dense(seq_mesh, causal):
    """Ring + Pallas flash kernel per block (interpret mode on CPU): the
    lse-weighted blockwise merge must reproduce dense attention."""
    q, k, v = _qkv()
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    ringf = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ring_flash_attention(a, b_, c, axis="seq",
                                                 causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = ringf(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_flash_attention_grads_match_dense(seq_mesh):
    """The lse cotangent path (delta shift in the Mosaic backward) composed
    with the blockwise merge must reproduce dense-attention gradients.
    Grad is taken OUTSIDE shard_map (the convention every train step here
    follows: per-shard grads + explicit psum, never grad-of-psum)."""
    q, k, v = _qkv(t=16)

    def loss_dense(q, k, v):
        return (sq.attention_reference(q, k, v, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def loss_ringf(q, k, v):
        out = jax.shard_map(
            lambda a, b_, c: sq.ring_flash_attention(a, b_, c, axis="seq",
                                                     causal=True),
            mesh=seq_mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(q, k, v)
        return (out ** 2).sum()

    grads = jax.jit(jax.grad(loss_ringf, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_transformer_ring_flash_matches_dense(seq_mesh):
    """Full model with attention='ring_flash' under seq sharding == dense
    Transformer on one device (kernel in interpret mode on CPU)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    base = dict(vocab_size=64, max_seq_len=t, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    dense_model = Transformer(TransformerConfig(attention="dense", **base))
    rf_model = Transformer(TransformerConfig(attention="ring_flash", **base))
    params = dense_model.init(prng.init_key(0))
    ids = np.random.default_rng(0).integers(0, 64, (2, t)).astype(np.int32)

    expected = dense_model.apply(params, jnp.asarray(ids))
    got = jax.jit(jax.shard_map(
        lambda p, i: rf_model.apply(p, i),
        mesh=seq_mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Striped layout (round-robin token stripes): balanced causal blocks
# ---------------------------------------------------------------------------

def _striped(x, perm):
    return np.asarray(x)[:, perm]


@pytest.mark.parametrize("causal", [True, False])
def test_striped_ring_matches_dense(seq_mesh, causal):
    """Striped ring attention on the permuted layout == dense attention on
    the original order (outputs unpermuted back)."""
    q, k, v = _qkv()
    t = q.shape[1]
    perm = sq.striped_permutation(t, 4)
    inv = sq.inverse_striped_permutation(t, 4)
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    ring = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ring_attention(a, b_, c, axis="seq",
                                           causal=causal, striped=True),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = ring(_striped(q, perm), _striped(k, perm), _striped(v, perm))
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_striped_flash_matches_dense(seq_mesh, causal):
    """Striped ring with the Pallas kernel per block (inclusive/exclusive
    diagonal modes, interpret on CPU) == dense attention."""
    q, k, v = _qkv()
    t = q.shape[1]
    perm = sq.striped_permutation(t, 4)
    inv = sq.inverse_striped_permutation(t, 4)
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    ringf = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.striped_ring_flash_attention(
            a, b_, c, axis="seq", causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = ringf(_striped(q, perm), _striped(k, perm), _striped(v, perm))
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_striped_flash_grads_match_dense(seq_mesh):
    """Backward through the exclusive-diagonal kernel blocks + lse merge
    == dense-attention gradients (unpermuted comparison)."""
    q, k, v = _qkv(t=16)
    t = q.shape[1]
    perm = sq.striped_permutation(t, 4)

    def loss_dense(q, k, v):
        return (sq.attention_reference(q, k, v, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def loss_striped(qs, ks, vs):
        out = jax.shard_map(
            lambda a, b_, c: sq.striped_ring_flash_attention(a, b_, c,
                                                             axis="seq"),
            mesh=seq_mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(qs, ks, vs)
        return (out ** 2).sum()  # sum is permutation-invariant

    grads = jax.jit(jax.grad(loss_striped, argnums=(0, 1, 2)))(
        jnp.asarray(_striped(q, perm)), jnp.asarray(_striped(k, perm)),
        jnp.asarray(_striped(v, perm)))
    for got, ref in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(got), _striped(ref, perm),
                                   rtol=2e-4, atol=2e-4)


def test_exclusive_mask_mode_matches_reference():
    """flash_attention_with_lse(mask_mode='causal_exclusive') == softmax
    over the strictly-lower triangle; the no-key row 0 returns output 0 /
    lse NEG_INF, and its gradients are exactly zero (not NaN)."""
    from neural_networks_parallel_training_with_mpi_tpu.ops.pallas_kernels import (
        NEG_INF, flash_attention_with_lse,
    )

    rng = np.random.default_rng(3)
    b, t, h, d = 2, 16, 2, 8
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)

    out, lse = flash_attention_with_lse(q, k, v, True, 8, 8, True,
                                        "causal_exclusive")
    # reference: strict lower-triangle mask
    scale = 1.0 / np.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = (jnp.arange(t)[None, :] < jnp.arange(t)[:, None])[None, None]
    s = jnp.where(mask, s, -1e30)
    probs = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out[:, 1:]),
                               np.asarray(ref[:, 1:]), rtol=2e-4, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(out[:, 0]),
                                  np.zeros((b, h, d), np.float32))
    assert np.all(np.asarray(lse.reshape(b, h, t)[:, :, 0]) == NEG_INF)

    def loss(q, k, v):
        o, _ = flash_attention_with_lse(q, k, v, True, 8, 8, True,
                                        "causal_exclusive")
        return (o ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert np.all(np.isfinite(np.asarray(g)))
    # row 0 attends nothing -> zero gradient on its query
    np.testing.assert_array_equal(np.asarray(gq[:, 0]),
                                  np.zeros((b, h, d), np.float32))


def test_transformer_striped_flash_matches_dense(seq_mesh):
    """Full model with attention='striped_flash' on striped-permuted ids ==
    the dense model on the original order (positional embeddings follow
    the stripes)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    perm = sq.striped_permutation(t, 4)
    inv = sq.inverse_striped_permutation(t, 4)
    base = dict(vocab_size=64, max_seq_len=t, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    dense_model = Transformer(TransformerConfig(attention="dense", **base))
    st_model = Transformer(TransformerConfig(attention="striped_flash",
                                             **base))
    params = dense_model.init(prng.init_key(0))
    ids = np.random.default_rng(0).integers(0, 64, (2, t)).astype(np.int32)

    expected = dense_model.apply(params, jnp.asarray(ids))
    got = jax.jit(jax.shard_map(
        lambda p, i: st_model.apply(p, i),
        mesh=seq_mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))(params, ids[:, perm])
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # trainer-level integration
def test_trainer_striped_matches_dense_trajectory():
    """End-to-end: --attention striped_flash on a DP x SP mesh trains the
    SAME trajectory as dense attention on plain DP (the loader's stripe
    permutation reorders tokens and targets together; per-token CE is
    permutation-invariant)."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig as MC, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    losses = {}
    for att, mesh in (("dense", MC(data=8)),
                      ("striped_flash", MC(data=4, seq=2)),
                      ("striped", MC(data=4, seq=2))):
        cfg = TrainConfig(
            nepochs=2, batch_size=16, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=32, seq_len=32,
                            vocab_size=64),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=64,
                              max_seq_len=32, attention=att),
            mesh=mesh,
        )
        losses[att] = Trainer(cfg).fit()["final_loss"]
    np.testing.assert_allclose(losses["striped_flash"], losses["dense"],
                               rtol=2e-4)
    np.testing.assert_allclose(losses["striped"], losses["dense"],
                               rtol=2e-4)


@pytest.mark.slow  # trainer-level integration
def test_trainer_striped_validation_matches_dense():
    """Validation must see the stripe permutation too (advisor-caught r3
    regression: the val loader once fed contiguous tokens to a model
    reading its shards as stripes) — val_loss equality with dense is the
    proof, train-loss equality alone cannot catch it."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig as MC, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    results = {}
    for att, mesh in (("dense", MC(data=8)),
                      ("striped_flash", MC(data=4, seq=2))):
        cfg = TrainConfig(
            nepochs=2, batch_size=16, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=40, seq_len=32,
                            vocab_size=64, val_fraction=0.2),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=64,
                              max_seq_len=32, attention=att),
            mesh=mesh,
        )
        results[att] = Trainer(cfg).fit()
    np.testing.assert_allclose(results["striped_flash"]["val_loss"],
                               results["dense"]["val_loss"], rtol=2e-4)


@pytest.mark.slow  # trainer-level integration
def test_trainer_striped_on_sp_tp_matches_dense():
    """Striped attention composed with Megatron TP (seq x tensor path):
    same trajectory as dense DP."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig as MC, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    losses = {}
    for att, mesh in (("dense", MC(data=8)),
                      ("striped_flash", MC(data=2, seq=2, tensor=2))):
        cfg = TrainConfig(
            nepochs=2, batch_size=16, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=32, seq_len=32,
                            vocab_size=64),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=64,
                              max_seq_len=32, attention=att),
            mesh=mesh,
        )
        losses[att] = Trainer(cfg).fit()["final_loss"]
    np.testing.assert_allclose(losses["striped_flash"], losses["dense"],
                               rtol=5e-4)
