"""Sequence-parallel attention must reproduce dense attention exactly —
ring (ppermute ring + online softmax) and ulysses (all-to-all) vs the
full-sequence reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.parallel import sequence as sq
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    devs = jax.devices("cpu")[:4]
    return make_mesh(MeshConfig(data=1, seq=4), devices=devs)


def _qkv(b=2, t=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((b, t, h, d)).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    ring = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ring_attention(a, b_, c, axis="seq", causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    uly = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ulysses_attention(a, b_, c, axis="seq", causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = uly(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_flow(seq_mesh):
    """Ring attention must be differentiable through the ppermute chain."""
    q, k, v = _qkv(t=16)

    def loss_dense(q, k, v):
        return (sq.attention_reference(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        out = jax.shard_map(
            lambda a, b_, c: sq.ring_attention(a, b_, c, axis="seq"),
            mesh=seq_mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(q, k, v)
        return (out ** 2).sum()

    g_dense = jax.grad(loss_dense)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ring = jax.jit(jax.grad(loss_ring))(jnp.asarray(q), jnp.asarray(k),
                                          jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=1e-3, atol=1e-4)


def test_transformer_seq_parallel_matches_dense(seq_mesh):
    """Full model: ring-attention Transformer under seq sharding == dense
    Transformer on one device."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    dense_cfg = TransformerConfig(vocab_size=64, max_seq_len=t, n_layers=2,
                                  d_model=32, n_heads=4, d_ff=64,
                                  attention="dense")
    ring_cfg = TransformerConfig(vocab_size=64, max_seq_len=t, n_layers=2,
                                 d_model=32, n_heads=4, d_ff=64,
                                 attention="ring")
    dense_model, ring_model = Transformer(dense_cfg), Transformer(ring_cfg)
    params = dense_model.init(prng.init_key(0))
    ids = np.random.default_rng(0).integers(0, 64, (2, t)).astype(np.int32)

    expected = dense_model.apply(params, jnp.asarray(ids))
    got = jax.jit(jax.shard_map(
        lambda p, i: ring_model.apply(p, i),
        mesh=seq_mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_attention_matches_dense(seq_mesh, causal):
    """Ring + Pallas flash kernel per block (interpret mode on CPU): the
    lse-weighted blockwise merge must reproduce dense attention."""
    q, k, v = _qkv()
    expected = sq.attention_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal)

    ringf = jax.jit(jax.shard_map(
        lambda a, b_, c: sq.ring_flash_attention(a, b_, c, axis="seq",
                                                 causal=causal),
        mesh=seq_mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    got = ringf(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_flash_attention_grads_match_dense(seq_mesh):
    """The lse cotangent path (delta shift in the Mosaic backward) composed
    with the blockwise merge must reproduce dense-attention gradients.
    Grad is taken OUTSIDE shard_map (the convention every train step here
    follows: per-shard grads + explicit psum, never grad-of-psum)."""
    q, k, v = _qkv(t=16)

    def loss_dense(q, k, v):
        return (sq.attention_reference(q, k, v, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def loss_ringf(q, k, v):
        out = jax.shard_map(
            lambda a, b_, c: sq.ring_flash_attention(a, b_, c, axis="seq",
                                                     causal=True),
            mesh=seq_mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(q, k, v)
        return (out ** 2).sum()

    grads = jax.jit(jax.grad(loss_ringf, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_transformer_ring_flash_matches_dense(seq_mesh):
    """Full model with attention='ring_flash' under seq sharding == dense
    Transformer on one device (kernel in interpret mode on CPU)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    t = 32
    base = dict(vocab_size=64, max_seq_len=t, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    dense_model = Transformer(TransformerConfig(attention="dense", **base))
    rf_model = Transformer(TransformerConfig(attention="ring_flash", **base))
    params = dense_model.init(prng.init_key(0))
    ids = np.random.default_rng(0).integers(0, 64, (2, t)).astype(np.int32)

    expected = dense_model.apply(params, jnp.asarray(ids))
    got = jax.jit(jax.shard_map(
        lambda p, i: rf_model.apply(p, i),
        mesh=seq_mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
