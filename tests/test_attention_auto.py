"""--attention auto (VERDICT r4 item 3): shape-based dense-vs-flash
dispatch at the measured crossover, so users stop paying the ~10% dense
deficit at short T that a hard-coded ``flash`` costs (BENCH_ATTENTION.json:
full-step flash 0.89x @ T=512, kernel-only 0.91x @ 1k / 0.98x @ 2k)."""

import jax
import jax.numpy as jnp
import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.config import (
    TrainConfig, build_argparser, config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel.sequence import (
    AUTO_FLASH_MIN_SEQ, resolve_attention_impl,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng


def test_dispatch_table_pinned():
    """The per-backend crossover is the measured one — a change to the
    table is a deliberate re-measurement, not an accident."""
    assert AUTO_FLASH_MIN_SEQ == {"tpu": 2048}
    # tpu: dense strictly below 2048, flash at/above
    for t in (128, 512, 1024, 2047):
        assert resolve_attention_impl("auto", t, "tpu") == "dense"
    for t in (2048, 4096, 8192):
        assert resolve_attention_impl("auto", t, "tpu") == "flash"
    # cpu (and any unmeasured backend): never auto-select the pallas
    # kernel — it runs in interpret mode there
    for t in (128, 2048, 65536):
        assert resolve_attention_impl("auto", t, "cpu") == "dense"
    # explicit impls pass through untouched
    for impl in ("dense", "flash", "ring", "ring_flash", "striped",
                 "striped_flash", "ulysses"):
        assert resolve_attention_impl(impl, 8192, "tpu") == impl


def test_auto_is_the_default():
    """TransformerConfig, ModelConfig, and the CLI all default to auto."""
    assert TransformerConfig(vocab_size=8).attention == "auto"
    assert TrainConfig().model.attention == "auto"
    args = build_argparser().parse_args(["--dataset", "text"])
    assert config_from_args(args).model.attention == "auto"


def test_dense_blockwise_exact_vs_dense():
    """attention_dense_blockwise (VERDICT r4 item 5): same math as dense
    with a (B,H,C,T) scores temp — outputs AND grads must match the
    reference to float32 tolerance at chunking, non-chunking (T % chunk
    != 0 falls back to one block), causal and bidirectional shapes."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel.sequence import (
        attention_dense_blockwise, attention_reference,
    )

    rng = np.random.default_rng(0)
    for (b, t, h, d), chunk, causal in [
        ((2, 512, 4, 16), 128, True),
        ((2, 512, 4, 16), 128, False),
        # 96 % 64 != 0 -> falls back to the LARGEST DIVISOR of t that
        # fits the requested chunk: 48 here (two blocks), not one
        # whole-seq block
        ((1, 96, 2, 8), 64, True),
        # prime T: the divisor fallback's worst case, q_chunk=1 -> t
        # scan ticks of (B, H, 1, T) — still never the full (B, H, T, T)
        ((1, 29, 2, 8), 16, True),
        ((2, 256, 2, 32), 256, True),  # chunk == T
    ]:
        q, k, v = (jnp.asarray(rng.standard_normal((b, t, h, d)),
                               jnp.float32) for _ in range(3))

        def loss(fn, q, k, v, _c=causal):
            return jnp.sum(fn(q, k, v, causal=_c).astype(jnp.float32) ** 2)

        ref = attention_reference(q, k, v, causal=causal)
        blk = attention_dense_blockwise(q, k, v, causal=causal,
                                        q_chunk=chunk)
        np.testing.assert_allclose(np.asarray(blk), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        g_ref = jax.grad(lambda *a: loss(attention_reference, *a))(q, k, v)
        g_blk = jax.grad(
            lambda *a: loss(attention_dense_blockwise, *a))(q, k, v)
        np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_ref),
                                   atol=1e-4, rtol=1e-4)


def test_auto_equals_dense_below_crossover():
    """On this backend (cpu) auto resolves to dense at every T, so the
    forward is bitwise identical — the resolution changes dispatch, never
    math."""
    cfg_auto = TransformerConfig(vocab_size=64, max_seq_len=32, n_layers=2,
                                 d_model=32, n_heads=4, d_ff=64,
                                 attention="auto")
    cfg_dense = TransformerConfig(vocab_size=64, max_seq_len=32, n_layers=2,
                                  d_model=32, n_heads=4, d_ff=64,
                                  attention="dense")
    model_a, model_d = Transformer(cfg_auto), Transformer(cfg_dense)
    params = model_a.init(prng.init_key(0))
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
    out_a = jax.jit(model_a.apply)(params, ids)
    out_d = jax.jit(model_d.apply)(params, ids)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_d))
