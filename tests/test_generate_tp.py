"""Tensor-parallel decoding (models.generate_tp): serving SP x TP / PP
checkpoints in their native layout must agree exactly with the dense
KV-cache decode (models.generate) — greedy parity on the 8-device mesh is
the bar (VERDICT r2 item 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate,
)
from neural_networks_parallel_training_with_mpi_tpu.models.generate_tp import (
    generate_tp, pipeline_params_for_decode,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    megatron,
    mesh as mesh_lib,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

V = 32


@pytest.fixture(scope="module")
def lm():
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64)
    model = Transformer(cfg)
    params = model.init(prng.init_key(0))
    return model, params


def _tp_params(model, params, tp):
    """Dense params -> the native SP x TP layout (head-aligned qkv)."""
    out = dict(params)
    out["blocks"] = megatron.permute_qkv(params["blocks"], model.cfg.d_model,
                                         model.cfg.n_heads, tp)
    return out


@pytest.fixture(scope="module")
def tp_mesh():
    devs = np.asarray(jax.devices()[:8])
    return mesh_lib.make_mesh(MeshConfig(data=2, tensor=4),
                              devices=devs)


def test_greedy_parity_vs_dense(lm, tp_mesh):
    """Megatron-sharded decode == dense decode, token for token, on the
    data=2 x tensor=4 mesh (replicated head)."""
    model, params = lm
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, V, (4, 4)), jnp.int32)
    dense = generate(model, params, prompt, max_new_tokens=8)
    tp = generate_tp(model, _tp_params(model, params, 4), prompt, tp_mesh,
                     max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))


def test_greedy_parity_vocab_parallel(lm, tp_mesh):
    """Vocab-parallel head: sharded logits + pmax/pmin argmax must still
    match the dense argmax exactly (same tie-breaking)."""
    model, params = lm
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, V, (4, 3)), jnp.int32)
    dense = generate(model, params, prompt, max_new_tokens=6)
    tp = generate_tp(model, _tp_params(model, params, 4), prompt, tp_mesh,
                     max_new_tokens=6, vocab_parallel=True)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))


def test_ragged_prompts_parity(lm, tp_mesh):
    """Per-row prompt lengths: the sequential path must keep long rows'
    prompt tokens and decode short rows from their own length."""
    model, params = lm
    rng = np.random.default_rng(2)
    full = jnp.asarray(rng.integers(1, V, (4, 6)), jnp.int32)
    lens = jnp.asarray([3, 6, 4, 5], jnp.int32)
    pad = jnp.where(jnp.arange(6)[None, :] < lens[:, None], full, 0)
    dense = generate(model, params, pad, max_new_tokens=4, prompt_lens=lens)
    tp = generate_tp(model, _tp_params(model, params, 4), pad, tp_mesh,
                     max_new_tokens=4, prompt_lens=lens)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))


def test_temperature_sampling_seeded_and_valid(lm, tp_mesh):
    """Gumbel-max sampling over the sharded vocab: deterministic per key,
    different across keys, tokens in range."""
    model, params = lm
    tpp = _tp_params(model, params, 4)
    prompt = jnp.zeros((4, 2), jnp.int32)
    a = generate_tp(model, tpp, prompt, tp_mesh, 6, temperature=1.0,
                    key=jax.random.PRNGKey(7), vocab_parallel=True)
    b = generate_tp(model, tpp, prompt, tp_mesh, 6, temperature=1.0,
                    key=jax.random.PRNGKey(7), vocab_parallel=True)
    c = generate_tp(model, tpp, prompt, tp_mesh, 6, temperature=1.0,
                    key=jax.random.PRNGKey(8), vocab_parallel=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert int(jnp.max(a)) < V and int(jnp.min(a)) >= 0


def test_gumbel_max_matches_categorical_distribution(lm, tp_mesh):
    """The sharded Gumbel-max sampler IS categorical sampling: over many
    draws from a fixed skewed logits row, empirical frequencies match the
    softmax within 4 sigma."""
    model, params = lm
    tpp = _tp_params(model, params, 4)
    prompt = jnp.asarray(np.full((4, 3), 5), jnp.int32)
    draws = []
    for s in range(64):
        out = generate_tp(model, tpp, prompt, tp_mesh, 1, temperature=1.0,
                          key=jax.random.PRNGKey(s), vocab_parallel=True)
        draws.extend(np.asarray(out[:, -1]).tolist())
    logits = model.apply(params, prompt)[:, -1]
    probs = np.asarray(jax.nn.softmax(logits[0]))
    counts = np.bincount(draws, minlength=V) / len(draws)
    # all rows identical => draws iid from probs; top token frequency check
    top = int(np.argmax(probs))
    se = np.sqrt(probs[top] * (1 - probs[top]) / len(draws))
    assert abs(counts[top] - probs[top]) < 4 * se + 1e-3


def test_vocab_parallel_rejects_top_p(lm, tp_mesh):
    model, params = lm
    with pytest.raises(NotImplementedError, match="top_p"):
        generate_tp(model, _tp_params(model, params, 4),
                    jnp.zeros((4, 2), jnp.int32), tp_mesh, 4,
                    temperature=1.0, top_p=0.9, key=jax.random.PRNGKey(0),
                    vocab_parallel=True)


def test_vocab_parallel_top_k_stays_in_dense_candidate_set(lm, tp_mesh):
    """Sharded top-k sampling (local top-k + tp*k all_gather threshold):
    every sampled token must lie in the DENSE top-k set of its context's
    logits row, across seeds; the stream is seed-deterministic."""
    model, params = lm
    tpp = _tp_params(model, params, 4)
    prompt = jnp.asarray(np.full((4, 3), 9), jnp.int32)
    k = 5
    logits = model.apply(params, prompt)[:, -1]
    allowed = set(np.asarray(
        jax.lax.top_k(logits[0], k)[1]).tolist())  # rows identical
    for s in range(8):
        out = generate_tp(model, tpp, prompt, tp_mesh, 1, temperature=1.0,
                          top_k=k, key=jax.random.PRNGKey(s),
                          vocab_parallel=True)
        for tok in np.asarray(out[:, -1]).tolist():
            assert tok in allowed, (tok, allowed)
    a = generate_tp(model, tpp, prompt, tp_mesh, 4, temperature=1.0,
                    top_k=k, key=jax.random.PRNGKey(3), vocab_parallel=True)
    b = generate_tp(model, tpp, prompt, tp_mesh, 4, temperature=1.0,
                    top_k=k, key=jax.random.PRNGKey(3), vocab_parallel=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scan_layers_checkpoint_decodes(tp_mesh):
    """A scan_layers (stacked-blocks) checkpoint: generate_tp unstacks the
    params AND the specs consistently, and matches the dense decode."""
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64, scan_layers=True)
    model = Transformer(cfg)
    params = model.init(prng.init_key(4))
    tpp = dict(params)
    tpp["blocks"] = megatron.permute_qkv(params["blocks"], cfg.d_model,
                                         cfg.n_heads, 4)
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, V, (4, 4)), jnp.int32)
    dense = generate(model, params, prompt, max_new_tokens=6)
    tp = generate_tp(model, tpp, prompt, tp_mesh, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))


def test_pipeline_checkpoint_decodes_natively(lm):
    """A pipe-sharded (stage, layer) checkpoint decodes through
    pipeline_params_for_decode + generate_tp with no host gather and no
    dense re-init: tokens match the dense decode of the same weights."""
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        pipeline,
    )

    model, params = lm
    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 2)
    pmesh = mesh_lib.make_mesh(MeshConfig(data=2, pipe=2, tensor=2),
                               devices=devs.reshape(-1))
    opt = optim.sgd(1e-2)
    state = pipeline.init_pipeline_state(model, opt, prng.init_key(0),
                                         n_stages=2, tp=2)
    state = pipeline.shard_pipeline_state(state, pmesh, opt)
    dec_params = pipeline_params_for_decode(state.params, model)

    # the same underlying weights, dense layout, for the oracle
    dense_params = dict(dec_params)
    dense_params["blocks"] = megatron.permute_qkv(
        dec_params["blocks"], model.cfg.d_model, model.cfg.n_heads, 2,
        inverse=True)
    dense_params = jax.device_get(dense_params)

    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, V, (4, 4)), jnp.int32)
    dense = generate(model, dense_params, prompt, max_new_tokens=6)
    tmesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=2),
                               devices=np.asarray(jax.devices()[:4]))
    tp = generate_tp(model, dec_params, prompt, tmesh, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))

def test_temperature_distinct_across_data_shards(lm, tp_mesh):
    """Identical prompts placed in DIFFERENT data shards must decode
    independent continuations (advisor r3: the shard_map-replicated key was
    only folded with the tensor rank, so row i of every data shard drew
    identical noise).  Covers both sampling bodies: the vocab-parallel
    Gumbel-max path and the replicated-head categorical path."""
    model, params = lm
    tpp = _tp_params(model, params, 4)
    # batch 4 over data=2 -> rows (0,1) on shard 0, rows (2,3) on shard 1
    prompt = jnp.asarray(np.full((4, 3), 7), jnp.int32)
    for vp in (True, False):
        out = generate_tp(model, tpp, prompt, tp_mesh, 8, temperature=1.0,
                          key=jax.random.PRNGKey(11), vocab_parallel=vp)
        cont = np.asarray(out[:, 3:])
        assert not np.array_equal(cont[0], cont[2]), (
            f"vocab_parallel={vp}: shard-0 row decoded identically to the "
            f"same-index shard-1 row — replicated sampling noise")
        # determinism must survive the fold
        again = generate_tp(model, tpp, prompt, tp_mesh, 8, temperature=1.0,
                            key=jax.random.PRNGKey(11), vocab_parallel=vp)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(again))


def test_pipeline_checkpoint_decode_tp_mismatch_repermutes(lm):
    """Decoding a pp x tp=2 checkpoint on a tensor=4 mesh: the qkv column
    permutation is tp-degree-dependent, so pipeline_params_for_decode must
    re-permute (inverse tp=2, forward tp=4) when told both degrees —
    tokens then match the dense decode exactly (advisor r3 low)."""
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        pipeline,
    )

    model, params = lm
    pmesh = mesh_lib.make_mesh(MeshConfig(data=2, pipe=2, tensor=2),
                               devices=np.asarray(jax.devices()[:8]))
    opt = optim.sgd(1e-2)
    state = pipeline.init_pipeline_state(model, opt, prng.init_key(0),
                                         n_stages=2, tp=2)
    state = pipeline.shard_pipeline_state(state, pmesh, opt)
    dec_params = pipeline_params_for_decode(state.params, model,
                                            qkv_tp=2, decode_tp=4)

    # the INDEPENDENT oracle: the dense weights the pipeline init started
    # from (same key; init_pipeline_params = stack(permute_qkv(model.init,
    # tp=2))).  Inverting the produced layout would be circular — it could
    # not detect a missing re-permutation.
    dense_params = model.init(prng.init_key(0))

    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(0, V, (4, 4)), jnp.int32)
    dense = generate(model, dense_params, prompt, max_new_tokens=6)
    tmesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=4),
                               devices=np.asarray(jax.devices()[:8]))
    tp = generate_tp(model, dec_params, prompt, tmesh, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))


@pytest.mark.slow  # lane budget (round 5): heaviest in module; core coverage kept by the sibling tests
def test_moe_greedy_parity_vs_dense(tp_mesh):
    """MoE checkpoints decode tensor-parallel (round 4): experts whole per
    rank, hidden dims tensor-sharded (the SP x TP MoE training layout).
    Greedy TP decode == the dense KV-cache decode on the same weights.
    Ample capacity so routing is drop-free in both chunkings."""
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64,
                            moe_experts=4, moe_capacity=256)
    model = Transformer(cfg)
    params = model.init(prng.init_key(3))
    prompt = np.asarray([[5, 9, 2, 7], [1, 1, 4, 30], [3, 8, 8, 2],
                         [29, 0, 6, 11]], np.int32)

    dense_out = generate(model, params, jnp.asarray(prompt), 10)
    tp_out = generate_tp(model, _tp_params(model, params, 4),
                         jnp.asarray(prompt), tp_mesh, 10)
    np.testing.assert_array_equal(np.asarray(dense_out),
                                  np.asarray(tp_out))


def test_moe_vocab_parallel_greedy_parity(tp_mesh):
    """MoE TP decode composes with vocab-parallel logits + sampling."""
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64,
                            moe_experts=4, moe_capacity=256)
    model = Transformer(cfg)
    params = model.init(prng.init_key(4))
    prompt = np.asarray([[5, 9, 2, 7], [1, 1, 4, 30]], np.int32)

    dense_out = generate(model, params, jnp.asarray(prompt), 8)
    tp_out = generate_tp(model, _tp_params(model, params, 4),
                         jnp.asarray(prompt), tp_mesh, 8,
                         vocab_parallel=True)
    np.testing.assert_array_equal(np.asarray(dense_out),
                                  np.asarray(tp_out))


def test_gqa_decode_parity_vs_dense(tp_mesh):
    """GQA in the native TP layout (round 4): per-rank [q|k|v] split at
    the GQA widths, kv_heads/tp-head-sharded cache, grouped local
    attention — token-for-token equal to the dense GQA decode."""
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, n_kv_heads=4 // 2,
                            d_ff=64)
    model = Transformer(cfg)
    params = model.init(prng.init_key(0))
    tp_params = dict(params)
    tp_params["blocks"] = megatron.permute_qkv(
        params["blocks"], cfg.d_model, cfg.n_heads, 2,
        kv_heads=cfg.kv_heads)
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=2),
                              devices=np.asarray(jax.devices()[:4]))
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, V, (4, 4)), jnp.int32)
    dense = generate(model, params, prompt, max_new_tokens=8)
    tp = generate_tp(model, tp_params, prompt, mesh, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))


def test_rope_decode_parity_vs_dense(tp_mesh):
    """RoPE in the native TP layout: local heads rotate at the chunk's
    absolute positions (rotation is per-head-independent), cached keys
    stored rotated — token-for-token equal to the dense RoPE decode;
    stacks with GQA and vocab-parallel sampling."""
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, n_kv_heads=2,
                            d_ff=64, pos_encoding="rope")
    model = Transformer(cfg)
    params = model.init(prng.init_key(0))
    assert "pos" not in params
    tp_params = dict(params)
    tp_params["blocks"] = megatron.permute_qkv(
        params["blocks"], cfg.d_model, cfg.n_heads, 2,
        kv_heads=cfg.kv_heads)
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=2),
                              devices=np.asarray(jax.devices()[:4]))
    rng = np.random.default_rng(4)
    prompt = jnp.asarray(rng.integers(0, V, (4, 3)), jnp.int32)
    dense = generate(model, params, prompt, max_new_tokens=8)
    tp = generate_tp(model, tp_params, prompt, mesh, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))
    tp_vp = generate_tp(model, tp_params, prompt, mesh, max_new_tokens=8,
                        vocab_parallel=True)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp_vp))


def test_swiglu_decode_parity_vs_dense(tp_mesh):
    """SwiGLU in the native TP layout (ADVICE r4): ff_gate is
    column-parallel with the SAME partition as ff_in, so the gated
    product of local shards is the local shard of the global product —
    the decode chunk must gate before the row-parallel ff_out psum.
    Stacked with RoPE + GQA (the modern-stack serving config)."""
    cfg = TransformerConfig(vocab_size=V, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, n_kv_heads=2,
                            d_ff=64, activation="swiglu",
                            pos_encoding="rope")
    model = Transformer(cfg)
    params = model.init(prng.init_key(5))
    assert "ff_gate" in params["blocks"][0]
    tp_params = dict(params)
    tp_params["blocks"] = megatron.permute_qkv(
        params["blocks"], cfg.d_model, cfg.n_heads, 2,
        kv_heads=cfg.kv_heads)
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=2),
                              devices=np.asarray(jax.devices()[:4]))
    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(0, V, (4, 4)), jnp.int32)
    dense = generate(model, params, prompt, max_new_tokens=8)
    tp = generate_tp(model, tp_params, prompt, mesh, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(tp))
