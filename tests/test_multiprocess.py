"""Real 2-process ``jax.distributed`` world on localhost (VERDICT r1 item 6).

The rest of the suite exercises multi-*device* SPMD on one process; this
test exercises multi-*process* world formation — the part of the stack the
reference gets from ``mpiexec`` (README.md:12) and ``MPI.COMM_WORLD``
(dataParallelTraining_NN_MPI.py:61-63).  Two OS processes, 2 virtual CPU
devices each, gloo collectives over localhost: world_setup, barrier,
broadcast_host_array, per-host data loading, a jitted DP train step over
the 4-device global mesh, and an orbax shard-parallel checkpoint round
trip — see distributed_child.py for the phase list.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

CHILD = Path(__file__).with_name("distributed_child.py")
TIMEOUT_S = float(os.environ.get("MULTIPROC_TEST_TIMEOUT", "300"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_world(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # child sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(CHILD.parent.parent) + os.pathsep + \
        env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), "2", str(port),
             str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(CHILD.parent.parent))
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=TIMEOUT_S)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"2-process world did not complete in {TIMEOUT_S:.0f}s "
                    "(world formation hang?)")

    reports = []
    for rc, out, err in outs:
        assert rc == 0, f"child rc={rc}\nstdout: {out[-1500:]}\nstderr: {err[-2500:]}"
        rec = None
        for line in reversed(out.strip().splitlines()):
            try:
                rec = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        assert rec is not None, f"no JSON from child: {out[-500:]}"
        reports.append(rec)

    assert {r["process_index"] for r in reports} == {0, 1}
    for r in reports:
        assert r["ok"] and r["broadcast_ok"] and r["replicas_ok"] \
            and r["checkpoint_ok"], r
    # both hosts computed the identical loss trajectory (one logical job)
    assert reports[0]["losses"] == reports[1]["losses"]
