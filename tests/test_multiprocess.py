"""Real 2-process ``jax.distributed`` world on localhost (VERDICT r1 item 6).

The rest of the suite exercises multi-*device* SPMD on one process; this
test exercises multi-*process* world formation — the part of the stack the
reference gets from ``mpiexec`` (README.md:12) and ``MPI.COMM_WORLD``
(dataParallelTraining_NN_MPI.py:61-63).  Two OS processes, 2 virtual CPU
devices each, gloo collectives over localhost: world_setup, barrier,
broadcast_host_array, per-host data loading, a jitted DP train step over
the 4-device global mesh, and an orbax shard-parallel checkpoint round
trip — see distributed_child.py for the phase list.  faulty_child.py adds
the fault-injection side: a rank dies mid-training and the survivor must
fail fast.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

# integration-heavy: full lane only (core lane: -m 'not slow')
pytestmark = pytest.mark.slow

CHILD = Path(__file__).with_name("distributed_child.py")
FAULTY = Path(__file__).with_name("faulty_child.py")
TIMEOUT_S = float(os.environ.get("MULTIPROC_TEST_TIMEOUT", "300"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_pair(script: Path, args_for):
    """Launch the two world processes of ``script`` and wait for both.
    ``args_for(pid)`` -> the child's argv tail.  Returns
    [(rc, stdout, stderr)] in pid order; fails the test on timeout
    (killing both children) — the one env/timeout convention both
    multiprocess tests share."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children set their own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(script.parent.parent) + os.pathsep + \
        env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script)] + [str(a) for a in args_for(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(script.parent.parent))
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=TIMEOUT_S)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"{script.name}: 2-process run did not complete in "
                    f"{TIMEOUT_S:.0f}s (collective/world-formation hang?)")
    return outs


def test_two_process_world(tmp_path):
    port = _free_port()
    outs = _spawn_pair(CHILD, lambda pid: [pid, 2, port, tmp_path])

    reports = []
    for rc, out, err in outs:
        assert rc == 0, f"child rc={rc}\nstdout: {out[-1500:]}\nstderr: {err[-2500:]}"
        rec = None
        for line in reversed(out.strip().splitlines()):
            try:
                rec = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        assert rec is not None, f"no JSON from child: {out[-500:]}"
        reports.append(rec)

    assert {r["process_index"] for r in reports} == {0, 1}
    for r in reports:
        assert r["ok"] and r["broadcast_ok"] and r["replicas_ok"] \
            and r["checkpoint_ok"] and r["sp_ok"], r
    # both hosts computed the identical loss trajectory (one logical job)
    assert reports[0]["losses"] == reports[1]["losses"]
    # ...including the cross-host ring-attention step (seq axis spans the
    # process boundary, so its ppermute hops ride the gloo backend) and
    # the cross-host Megatron TP step (partitioner-inserted all-reduces)
    assert reports[0]["sp_loss"] == reports[1]["sp_loss"]
    for r in reports:
        assert r["tp_ok"], r
    assert reports[0]["tp_loss"] == reports[1]["tp_loss"]
    # ...and the cross-host MoE step (the all_to_all slot exchange spans
    # the process boundary on the 'expert' axis)
    for r in reports:
        assert r["ep_ok"], r
    assert reports[0]["ep_loss"] == reports[1]["ep_loss"]


def test_peer_death_fails_fast():
    """Kill one rank mid-training; the survivor must exit within the
    deadline — by a surfaced collective error (43) or the step-hang
    watchdog (42) — instead of hanging forever in a collective (the
    reference's failure mode: its gather at :185 has no timeout)."""
    port = _free_port()
    rcs = _spawn_pair(FAULTY, lambda pid: [pid, port])
    survivor_rc = rcs[0][0]
    assert rcs[1][0] == 1, f"victim should exit 1, got {rcs[1]}"
    assert survivor_rc in (42, 43), (
        f"survivor rc={survivor_rc} (42=watchdog, 43=surfaced error)\n"
        f"stdout: {rcs[0][1][-800:]}\nstderr: {rcs[0][2][-800:]}")
