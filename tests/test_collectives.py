"""Collectives wrappers over the 8-device CPU mesh — the primitives that
replace the reference's MPI call inventory (SURVEY.md §2.3)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.parallel import collectives as coll


def _run(mesh, fn, x, in_spec=P("data"), out_spec=P()):
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                                 out_specs=out_spec, check_vma=False))(x)


def test_pmean_replaces_gather_average_send(mesh8):
    # the reference's whole grad-sync round (:185-208) in one collective
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = _run(mesh8, lambda v: coll.pmean(v, "data"), x, out_spec=P())
    np.testing.assert_allclose(np.asarray(out), [[3.5]])


def test_psum_over_mesh(mesh8):
    x = np.ones((8, 2), np.float32)
    out = _run(mesh8, lambda v: coll.psum(v, "data"), x)
    np.testing.assert_allclose(np.asarray(out), np.full((1, 2), 8.0))


def test_broadcast_from_matches_mpi_bcast(mesh8):
    # semantic equivalent of comm.bcast(..., root=0) (:87/:97)
    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    out = _run(mesh8, lambda v: coll.broadcast_from(v, "data", src=3), x,
               out_spec=P("data"))
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.0))


def test_ppermute_ring_rotates(mesh8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = _run(mesh8, lambda v: coll.ppermute_ring(v, "data", shift=1), x,
               out_spec=P("data"))
    # member i's value goes to member i+1
    np.testing.assert_allclose(np.asarray(out).ravel(),
                               [7, 0, 1, 2, 3, 4, 5, 6])


def test_all_gather(mesh8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = _run(mesh8, lambda v: coll.all_gather(v, "data"), x,
               out_spec=P("data"))
    got = np.asarray(out)
    assert got.shape == (64, 1)
    np.testing.assert_allclose(got[:8].ravel(), np.arange(8))


def test_reduce_scatter(mesh8):
    x = np.tile(np.arange(8, dtype=np.float32), (8, 1)).reshape(8, 8)

    out = _run(mesh8, lambda v: coll.reduce_scatter(v, "data", scatter_axis=1),
               x, in_spec=P("data"), out_spec=P("data"))
    # all-sum over members = 8*[0..7]; member i keeps column block i -> 8*i
    np.testing.assert_allclose(np.asarray(out).ravel(), 8.0 * np.arange(8))


def test_axis_index_is_get_rank(mesh8):
    out = _run(mesh8, lambda v: coll.axis_index("data").reshape(1, 1).astype(jnp.float32),
               np.zeros((8, 1), np.float32), out_spec=P("data"))
    np.testing.assert_allclose(np.asarray(out).ravel(), np.arange(8))
