"""Paged KV cache (serve/paged_kv.py): allocator accounting + the parity
pin.

The load-bearing property: greedy paged decode — blocks allocated on
demand, prompts straddling block boundaries, strangers sharing the
batched step — must emit exactly the tokens the dense ``DecodeServer``
and the single-stream ``generate()`` emit for the same request.  The
gathered attention reduces over the same values in the same order as the
dense cache, so this is a testable contract, not a tolerance band.

Core-lane budget note: one test pins paged == generate() DIRECTLY; the
rest pin paged == dense ``DecodeServer``, which tests/test_serve.py pins
against generate() per request — the transitive chain keeps the lane off
the expensive un-jitted generate() reference (several seconds per call)
without weakening the contract."""

import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate,
)
from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
    DecodeServer,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    BlockAllocator, PagedDecodeServer,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

VOCAB = 64


def _model(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=64, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    base.update(kw)
    return Transformer(TransformerConfig(**base))


def _reference(model, params, prompt, n, **kw):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32), n, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _dense_reference(model, params, prompt, n):
    """Single-stream decode through the dense slot server (its jitted
    programs are lru-cached per model config, so repeat references cost
    steps, not compiles; test_serve.py pins this path == generate())."""
    srv = DecodeServer(model, params, slots=1)
    rid = srv.submit(list(prompt), max_new_tokens=n)
    while not srv.done(rid):
        srv.step()
    return srv.result(rid)


def _drain(srv, rid, prefill_width=16):
    while not srv.prefill_step(rid, prefill_width):
        pass
    while not srv.done(rid):
        srv.step()
    return srv.result(rid)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_allocator_accounting():
    a = BlockAllocator(8)                     # 7 usable, block 0 = sink
    assert a.capacity == 7 and a.free_blocks == 7
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got     # the sink is never granted
    assert a.free_blocks == 4 and a.used_blocks == 3
    assert a.alloc(5) is None                 # all-or-nothing
    assert a.free_blocks == 4                 # refused alloc took nothing
    a.free(got)
    a.assert_drained()


def test_allocator_double_free_raises():
    a = BlockAllocator(4)
    got = a.alloc(2)
    a.free(got)
    with pytest.raises(ValueError):
        a.free([got[0]])
    with pytest.raises(ValueError):
        a.free([0])                           # the sink was never granted


def test_allocator_leak_detection():
    a = BlockAllocator(4)
    a.alloc(1)
    with pytest.raises(AssertionError):
        a.assert_drained()


def test_sink_pool_minimum():
    with pytest.raises(ValueError):
        BlockAllocator(1)                     # sink-only pool is unusable


# ---------------------------------------------------------------------------
# parity pin: paged == dense DecodeServer == generate (greedy)
# ---------------------------------------------------------------------------

def test_paged_matches_generate_directly():
    """The one direct generate() pin (the rest chain through the dense
    server): single request, blocks grown on demand across boundaries."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8)
    rid = srv.try_admit([1, 2, 3], 10)
    got = _drain(srv, rid)
    assert got == _reference(model, params, [1, 2, 3], 10)
    assert got == _dense_reference(model, params, [1, 2, 3], 10)
    srv.allocator.assert_drained()


def test_staggered_straddling_admissions_exact():
    """Requests joining mid-flight with ragged lengths — including an
    11-token prompt prefilled in width-4 chunks, straddling the 8-token
    block boundary mid-chunk — each token-identical to its single-stream
    decode, and every block back in the pool after the drain."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8)
    straddle = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11]
    reqs = {}
    a = srv.try_admit(straddle, 12)
    while not srv.prefill_step(a, 4):         # chunks split mid-block
        pass
    reqs[a] = (straddle, 12)
    srv.step(); srv.step()
    b = srv.try_admit([7, 8], 6)
    while not srv.prefill_step(b, 16):
        pass
    reqs[b] = ([7, 8], 6)
    srv.step()
    c = srv.try_admit([5, 9, 11, 13], 9)
    while not srv.prefill_step(c, 16):
        pass
    reqs[c] = ([5, 9, 11, 13], 9)
    for _ in range(40):
        srv.step()
        if all(srv.done(r) for r in reqs):
            break
    for rid, (prompt, n) in reqs.items():
        assert srv.result(rid) == _dense_reference(model, params, prompt,
                                                   n), rid
    srv.allocator.assert_drained()


def test_evict_then_rerun_reproduces_tokens():
    """Eviction discards device state; a greedy re-run of the same
    request must reproduce the same tokens (the scheduler's requeue
    correctness hinges on this)."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=40,
                            block_size=8)
    rid = srv.try_admit([4, 5, 6], 10)
    while not srv.prefill_step(rid, 16):
        pass
    srv.step(); srv.step(); srv.step()        # mid-flight
    prompt, max_new = srv.evict(rid)
    srv.allocator.assert_drained()            # eviction freed everything
    rid2 = srv.try_admit(prompt, max_new)
    assert _drain(srv, rid2) == _dense_reference(model, params, [4, 5, 6],
                                                 10)


def test_unservable_request_raises():
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=3,
                            block_size=8, max_len=64)
    with pytest.raises(ValueError):           # needs 3 blocks, pool has 2
        srv.try_admit([1] * 8, 16)
    with pytest.raises(ValueError):
        srv.try_admit([1] * 60, 8)            # over max_len
    with pytest.raises(ValueError):
        srv.try_admit([], 4)


def test_capacity_beats_dense_at_equal_memory():
    """The tentpole claim at unit scale: the same cache positions, paged
    into blocks, admit MORE short concurrent streams than dense slots
    (measured by admitting until refusal — the bench's capacity A/B at
    bench scale writes BENCH_SERVE.json)."""
    model = _model()
    params = model.init(prng.init_key(0))
    dense = DecodeServer(model, params, slots=2, max_len=64)
    dense_cap = 0
    while dense.submit([1, 2, 3, 4], 4) is not None:
        dense_cap += 1
    # equal cache positions: 2 slots x 64 = 128 = 16 blocks of 8 (+ sink)
    paged = PagedDecodeServer(model, params, slots=16, num_blocks=17,
                              block_size=8, max_len=64)
    paged_cap = 0
    while paged.try_admit([1, 2, 3, 4], 4) is not None:
        paged_cap += 1
    assert dense_cap == 2
    assert paged_cap > 2 * dense_cap, (dense_cap, paged_cap)


def test_dense_server_sync_flag_identical():
    """The host-sync satellite fix: completion from host-tracked
    positions must behave exactly like the legacy per-step device fetch
    (same tokens, same completion steps)."""
    model = _model()
    params = model.init(prng.init_key(0))
    outs = []
    for sync in (False, True):
        srv = DecodeServer(model, params, slots=2, sync_per_step=sync)
        a = srv.submit([1, 2, 3], max_new_tokens=7)
        srv.step(); srv.step()
        b = srv.submit([9, 4], max_new_tokens=5)
        steps = 0
        while not (srv.done(a) and srv.done(b)):
            srv.step()
            steps += 1
            assert steps < 30
        outs.append((srv.result(a), srv.result(b), steps))
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# model-variant parity (full lane: each is a fresh compile of the paged
# programs for a different config)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gqa_paged_exact():
    model = _model(n_kv_heads=2)
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=20,
                            block_size=8)
    rid = srv.try_admit([1, 2, 3], 8)
    assert _drain(srv, rid) == _reference(model, params, [1, 2, 3], 8)


@pytest.mark.slow
def test_int8_kv_paged_exact():
    """kv_quant pools quantize per (position, head) — identical
    quantization points to the dense int8 cache, so tokens match the
    kv_quant single-stream decode exactly even with prefill chunks and
    block boundaries in different places."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=20,
                            block_size=8, kv_quant=True)
    assert srv.pools[0]["k"].dtype == jnp.int8
    rid = srv.try_admit([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], 8)
    got = _drain(srv, rid, prefill_width=4)
    assert got == _reference(model, params, [1, 2, 3, 4, 5, 6, 7, 8, 9, 10],
                             8, kv_quant=True)


@pytest.mark.slow
def test_scan_layers_paged_exact():
    model = _model(scan_layers=True)
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=20,
                            block_size=8)
    rid = srv.try_admit([9, 8, 7], 6)
    assert _drain(srv, rid) == _reference(model, params, [9, 8, 7], 6)


@pytest.mark.slow
def test_rope_paged_exact():
    """RoPE rotates at absolute positions; paging must not disturb them
    (chunked prefill at width 4 splits blocks and rotation windows)."""
    model = _model(pos_encoding="rope")
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=20,
                            block_size=8)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    rid = srv.try_admit(prompt, 8)
    assert _drain(srv, rid, prefill_width=4) == _reference(
        model, params, prompt, 8)
