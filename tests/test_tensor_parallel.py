"""TP/FSDP via GSPMD must be numerically equivalent to pure DP — sharding
annotations change placement, never math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models.mlp import wide_mlp
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops import optim
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    gspmd, tensor_parallel as tp,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import make_mesh
from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
from neural_networks_parallel_training_with_mpi_tpu.utils import prng


def _tiny_transformer():
    return Transformer(TransformerConfig(
        vocab_size=32, max_seq_len=16, n_layers=2, d_model=32, n_heads=4,
        d_ff=64))


def _lm_batch(b=8, t=16, vocab=32, seed=0):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t + 1))
    return {"x": tok[:, :-1].astype(np.int32),
            "y": tok[:, 1:].astype(np.int32),
            "mask": np.ones((b,), np.float32)}


def _run_steps(mesh, model, batch, nsteps=3, opt_name="sgd"):
    opt = (optim.sgd(0.01, 0.9) if opt_name == "sgd" else optim.adam(0.01))
    state = TrainState.create(model, opt, prng.init_key(0))
    state = gspmd.shard_state(model, state, opt, mesh)
    placed = gspmd.shard_batch(mesh, batch)
    step = gspmd.make_gspmd_train_step(model, opt, mesh, "cross_entropy",
                                       example_batch=placed, donate=False)
    losses = []
    for _ in range(nsteps):
        state, loss = step(state, placed)
        losses.append(float(jax.device_get(loss)))
    return jax.device_get(state), losses


def test_param_specs_shard_the_right_axes(devices):
    mesh = make_mesh(MeshConfig(data=2, tensor=2, fsdp=2), devices=devices)
    model = _tiny_transformer()
    params = model.init(prng.init_key(0))
    specs = tp.param_specs(model, params, mesh)
    blk = specs["blocks"][0]
    assert blk["qkv"]["w"] == P("fsdp", "tensor")       # column-parallel
    assert blk["attn_out"]["w"] == P("tensor", "fsdp")  # row-parallel
    assert blk["ff_in"]["w"] == P("fsdp", "tensor")
    assert blk["ff_out"]["w"] == P("tensor", "fsdp")
    assert blk["qkv"]["b"] == P("tensor")
    assert blk["ln1"]["scale"] == P()
    assert specs["embed"]["table"] == P("fsdp")


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),                     # pure DP baseline placement
    MeshConfig(data=2, tensor=4),           # DP x TP
    MeshConfig(data=2, tensor=2, fsdp=2),   # DP x TP x FSDP
    MeshConfig(data=1, fsdp=8),             # pure FSDP (ZeRO-ish)
])
def test_gspmd_transformer_matches_single_device(devices, mesh_cfg, mesh1):
    model = _tiny_transformer()
    batch = _lm_batch()
    mesh = make_mesh(mesh_cfg, devices=devices)
    s_multi, l_multi = _run_steps(mesh, model, batch)
    s_one, l_one = _run_steps(mesh1, model, batch)
    np.testing.assert_allclose(l_multi, l_one, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s_multi.params),
                    jax.tree_util.tree_leaves(s_one.params)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


def test_gspmd_fsdp_mlp_adam(devices, mesh1):
    """FSDP shards a generic MLP's weights and adam mirrors the sharding."""
    model = wide_mlp(in_features=8, width=32, depth=2)
    rng = np.random.default_rng(1)
    batch = {"x": rng.standard_normal((16, 8)).astype(np.float32),
             "y": rng.standard_normal((16, 1)).astype(np.float32),
             "mask": np.ones((16,), np.float32)}
    mesh = make_mesh(MeshConfig(data=2, fsdp=4), devices=devices)

    opt = optim.adam(0.01)
    state = TrainState.create(model, opt, prng.init_key(0))
    sharded = gspmd.shard_state(model, state, opt, mesh)
    # momentum slots carry the params' fsdp sharding
    mu_leaf = jax.tree_util.tree_leaves(sharded.opt_state.mu)[0]
    p_leaf = jax.tree_util.tree_leaves(sharded.params)[0]
    assert mu_leaf.sharding == p_leaf.sharding

    placed = gspmd.shard_batch(mesh, batch)
    step = gspmd.make_gspmd_train_step(model, opt, mesh, "mse",
                                       example_batch=placed, donate=False)
    state2, loss = step(sharded, placed)
    assert np.isfinite(float(jax.device_get(loss)))


def test_gspmd_eval_matches_dp_eval(devices, mesh1):
    """The GSPMD eval step must agree with the shard_map eval on loss and
    accuracy (params sharded vs replicated)."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
    )

    model = _tiny_transformer()
    batch = _lm_batch()
    opt = optim.sgd(0.01)
    state = TrainState.create(model, opt, prng.init_key(0))

    mesh = make_mesh(MeshConfig(data=2, tensor=2, fsdp=2), devices=devices)
    sharded = gspmd.shard_state(model, state, opt, mesh)
    placed = gspmd.shard_batch(mesh, batch)
    ev = gspmd.make_gspmd_eval_step(model, mesh, "cross_entropy",
                                    with_accuracy=True, example_batch=placed)
    got = jax.device_get(ev(sharded.params, placed))

    ref_state = dp.replicate_state(state, mesh1)
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        sharding as shd,
    )

    ev1 = dp.make_eval_step(model, mesh1, "cross_entropy", with_accuracy=True)
    ref = jax.device_get(ev1(ref_state.params, shd.shard_batch(mesh1, batch)))
    np.testing.assert_allclose(float(got["loss"]), float(ref["loss"]),
                               rtol=2e-5)
    np.testing.assert_allclose(float(got["accuracy"]), float(ref["accuracy"]),
                               rtol=2e-5)
    assert float(got["count"]) == float(ref["count"])


def test_actual_device_local_shapes(devices):
    """TP really splits the tensors: local shard of a column-parallel weight
    has out_dim / tp columns."""
    mesh = make_mesh(MeshConfig(data=1, tensor=4), devices=devices[:4])
    model = _tiny_transformer()
    opt = optim.sgd(0.01)
    state = TrainState.create(model, opt, prng.init_key(0))
    sharded = gspmd.shard_state(model, state, opt, mesh)
    qkv_w = sharded.params["blocks"][0]["qkv"]["w"]  # (32, 96) global
    assert qkv_w.addressable_shards[0].data.shape == (32, 24)


def test_mlp_tensor_parallel_through_trainer(devices):
    """Megatron alternating col/row TP on the wide-MLP family: hidden
    weights actually shard over 'tensor' and training matches pure DP."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    def run(mesh_cfg):
        # lr=1e-4, momentum=0: the raw-scale regression targets (std ~50)
        # make momentum-0.9 lr>=0.003 trajectories CHAOTIC — the TP/fsdp
        # and DP layouts reduce in different orders, and near the
        # stability boundary those ulp-level differences amplify
        # exponentially until one run diverges to NaN while the other
        # doesn't.  In the stable regime the layouts agree per-step to
        # ~1e-4 relative (the property this test actually pins).
        cfg = TrainConfig(
            nepochs=2, batch_size=16, full_batch=False, shuffle=False,
            lr=1e-4, momentum=0.0, mesh=mesh_cfg,
            data=DataConfig(dataset="regression", n_samples=64,
                            n_features=8),
            model=ModelConfig(arch="mlp", in_features=8, hidden=(16, 16),
                              out_features=1),
        )
        t = Trainer(cfg)
        result = t.fit()
        return t, result

    t_tp, r_tp = run(MeshConfig(data=2, tensor=2, fsdp=2))
    assert t_tp.gspmd
    # first Linear (column-parallel): w (8,16) -> local (4,8) under fsdp x tensor
    w0 = t_tp.state.params[0]["w"]
    assert w0.addressable_shards[0].data.shape == (4, 8)
    # second Linear (row-parallel): w (16,16) -> local (8,8)
    w1 = t_tp.state.params[2]["w"]
    assert w1.addressable_shards[0].data.shape == (8, 8)
    t_dp, r_dp = run(MeshConfig(data=8))
    # reduction-order noise between the two layouts bounds the match
    assert r_tp["final_loss"] == pytest.approx(r_dp["final_loss"], rel=2e-3)


# ---- vocab parallelism (megatron.vocab_parallel_*) -----------------------


def test_vocab_parallel_embed_and_ce_match_dense(devices):
    """Sharded embedding lookup, sharded-softmax cross-entropy, and sharded
    argmax accuracy vs their dense counterparts on a pure 'tensor' mesh —
    values AND gradients (the embed table / head grads must land in the
    owning shard)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from neural_networks_parallel_training_with_mpi_tpu.ops import losses
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    mesh = make_mesh(MeshConfig(data=1, tensor=4), devices=devices[:4])
    rng = np.random.default_rng(0)
    v, d, b, t = 32, 16, 2, 8
    table = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
    head = jnp.asarray(rng.standard_normal((d, v)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, v, (b, t)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, v, (b, t)), jnp.int32)
    mask = jnp.ones((b,), jnp.float32)

    def sharded(table, head, ids, tgt, mask):
        x = megatron.vocab_parallel_embed(table, ids)
        logits_local = megatron.vocab_parallel_logits(x, head)
        s, c = megatron.vocab_parallel_cross_entropy(logits_local, tgt, mask)
        hs, hc = megatron.vocab_parallel_accuracy(logits_local, tgt, mask)
        return s / c, hs / hc

    def dense(table, head, ids, tgt, mask):
        x = jnp.take(table, ids, axis=0)
        logits = (x @ head).astype(jnp.float32)
        s, c = losses.softmax_cross_entropy(logits, tgt, mask)
        hs, hc = losses.accuracy(logits, tgt, mask)
        return s / c, hs / hc

    f = jax.jit(jax.shard_map(
        sharded, mesh=mesh,
        in_specs=(P("tensor", None), P(None, "tensor"), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    ))
    (loss_s, acc_s) = f(table, head, ids, tgt, mask)
    (loss_d, acc_d) = dense(table, head, ids, tgt, mask)
    np.testing.assert_allclose(float(loss_s), float(loss_d), rtol=1e-5)
    np.testing.assert_allclose(float(acc_s), float(acc_d), rtol=1e-6)

    # gradients: grad taken INSIDE shard_map (each device differentiates
    # its replica of the global scalar — exactly how the train steps use
    # these helpers); the shard-local table/head grads reassembled must
    # equal the dense grads
    def loss_sharded(table_local, head_local):
        return sharded(table_local, head_local, ids, tgt, mask)[0]

    def loss_dense_fn(table, head):
        return dense(table, head, ids, tgt, mask)[0]

    g_s = jax.jit(jax.shard_map(
        jax.grad(loss_sharded, argnums=(0, 1)),
        mesh=mesh, in_specs=(P("tensor", None), P(None, "tensor")),
        out_specs=(P("tensor", None), P(None, "tensor")),
        check_vma=False,
    ))(table, head)
    g_d = jax.grad(loss_dense_fn, argnums=(0, 1))(table, head)
    for a, bb in zip(g_s, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=2e-5, atol=2e-6)
