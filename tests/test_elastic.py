"""Elastic degraded-capacity restart (DESIGN.md §10).

The load-bearing properties:

* a world that cannot re-form raises TYPED errors with a bounded timeout
  (``CoordinatorUnreachable`` vs ``PeerMissing``) instead of the native
  fatal abort, so the supervisor's exit-43 peer-loss streak can drive
  the elastic probe-and-shrink policy;
* cross-world checkpoint resharding: an N-device snapshot restores onto
  M != N devices bitwise-identically for replicated DP, and zero1's flat
  per-dp-padded buffers re-pad without ever dropping a nonzero entry;
* topology lineage: a shrunken world's own saves carry ``saved_world``
  AND ``restored_world`` so they never shadow where the job started;
* data-order continuity: ``consumed_samples`` is the world-size-
  independent progress coordinate — a resumed run with a different batch
  size walks the SAME per-epoch sample permutation;
* the chaos lane proves the acceptance scenario end to end: peer_kill
  mid-run -> supervised relaunch at world=1 -> resharded restore ->
  finite loss -> exit 0; with --min_devices 2 the same scenario exits 46
  without a degraded relaunch.
"""

import json
import os
import pathlib
import socket
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, TrainConfig, build_argparser, config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
    ShardedLoader,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    distributed,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
    CoordinatorUnreachable, PeerMissing, WorldFormationError, make_mesh,
    world_setup,
)
from neural_networks_parallel_training_with_mpi_tpu.train import (
    resilience,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
    Trainer,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    checkpoint as ckpt,
    ckpt_manifest,
    faults as faults_lib,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _mesh(devices, dp):
    return make_mesh(MeshConfig(data=dp), devices=devices[:dp])


def _cfg(dp, ckpt_dir, **kw):
    base = dict(nepochs=1, full_batch=False, batch_size=8, lr=1e-3,
                momentum=0.9, data=DataConfig(n_samples=64),
                mesh=MeshConfig(data=dp), checkpoint_dir=str(ckpt_dir),
                checkpoint_every=2, elastic=True, resume=True)
    base.update(kw)
    return TrainConfig(**base)


def _host_leaves(state):
    return [np.asarray(x) for x in
            jax.tree_util.tree_leaves(jax.device_get(state))]


# ------------------------------------------------- exit-code contract


def test_exit_capacity_pinned():
    assert resilience.EXIT_CAPACITY == 46
    assert resilience.EXIT_CAPACITY in resilience._NO_RETRY
    # the elastic streak counts explicit peer loss AND watchdog hangs (a
    # dead peer often presents as a stalled collective killed as 42)
    assert set(resilience._PEER_LOSS_CODES) == {42, 43}


def test_strip_supervisor_flags_keeps_elastic():
    argv = ["--elastic", "--min_devices", "2", "--supervise", "3",
            "--supervise_backoff_max=5", "--supervise_backoff", "1",
            "--lr", "0.1"]
    # the child keeps the elastic flags (it enforces the floor itself);
    # only the supervisor-loop knobs are stripped
    assert resilience.strip_supervisor_flags(argv) == [
        "--elastic", "--min_devices", "2", "--lr", "0.1"]


def test_is_peer_error_classification():
    class XlaRuntimeError(Exception):
        pass

    assert resilience.is_peer_error(XlaRuntimeError("INTERNAL: foo"))
    assert resilience.is_peer_error(
        ValueError("UNKNOWN: Gloo all-reduce failed: Connection reset"))
    assert resilience.is_peer_error(PeerMissing("rank 1 missing"))
    assert resilience.is_peer_error(CoordinatorUnreachable("down"))
    assert resilience.is_peer_error(
        distributed.CollectiveTimeout("barrier did not complete"))
    assert not resilience.is_peer_error(ValueError("bad model config"))
    assert not resilience.is_peer_error(ZeroDivisionError())
    # ordinary crashes whose message merely CONTAINS a network-ish word
    # must stay crashes (traceback, rc 1) — a bare-substring match here
    # burned the restart budget, and the elastic shrink streak, on bugs
    # a relaunch can never fix
    assert not resilience.is_peer_error(
        FileNotFoundError("No such file: /data/peer_reviews.npz"))
    assert not resilience.is_peer_error(RuntimeError("CUDA unavailable"))
    assert not resilience.is_peer_error(
        ValueError("distributed loader misconfigured"))
    assert not resilience.is_peer_error(
        RuntimeError("deadline for run exceeded by scheduler"))
    # non-transport statuses beat the type match: an OOM also arrives
    # as XlaRuntimeError, and reading it as peer loss would feed the
    # shrink streak (whose global-batch policy GROWS per-device rows)
    assert not resilience.is_peer_error(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory allocating "
                        "1073741824 bytes"))
    assert not resilience.is_peer_error(
        XlaRuntimeError("INVALID_ARGUMENT: shape mismatch"))
    # bare network-adjacent words are not enough either
    assert not resilience.is_peer_error(
        OSError("could not bind socket on port 8080"))
    assert not resilience.is_peer_error(
        RuntimeError("collective ops module failed to import"))
    assert not resilience.is_peer_error(
        ValueError("invalid coordinator_address format"))


def test_degrade_env():
    env = {"COORDINATOR_ADDRESS": "h:1", "JAX_COORDINATOR_ADDRESS": "h:1",
           "NNPT_NUM_PROCESSES": "4", "NNPT_PROCESS_ID": "2", "KEEP": "x"}
    out = resilience.degrade_env(env, {"n_processes": 1, "n_devices": 2})
    assert out is env
    assert "COORDINATOR_ADDRESS" not in out
    assert "JAX_COORDINATOR_ADDRESS" not in out
    assert out["NNPT_NUM_PROCESSES"] == "1"
    assert out["NNPT_PROCESS_ID"] == "0"
    assert out[resilience.DEGRADED_ENV] == "2"
    assert out["KEEP"] == "x"
    # a degraded multi-process world is unsupported (no probe can answer
    # rank reassignment): refuse loudly rather than relaunch a child with
    # a stale, possibly out-of-range NNPT_PROCESS_ID
    env2 = {"COORDINATOR_ADDRESS": "h:1", "NNPT_NUM_PROCESSES": "4"}
    with pytest.raises(ValueError, match="n_processes=2"):
        resilience.degrade_env(env2, {"n_processes": 2, "n_devices": 4})


# ------------------------------------------------------- supervisor


def _run_supervise(code_seq, **kw):
    """Drive supervise() with a scripted child; returns (rc, log lines,
    per-launch envs, slept delays)."""
    it = iter(code_seq)
    envs, delays, logs = [], [], []

    def fake_call(cmd, env=None):
        envs.append(dict(env) if env is not None else None)
        return next(it)

    orig = resilience.subprocess.call
    resilience.subprocess.call = fake_call
    try:
        rc = resilience.supervise(
            ["x"], log=logs.append, _sleep=delays.append,
            **{"max_restarts": 5, "backoff": 1.0, **kw})
    finally:
        resilience.subprocess.call = orig
    return rc, logs, envs, delays


def test_backoff_jitter_and_cap():
    """Satellite: jittered exponential backoff, capped at backoff_cap —
    a pod's worth of supervisors must not relaunch in lockstep.  Jitter
    is DOWNWARD-only ([1-jitter, 1]) so the cap stays a hard bound and
    the spread survives once the doubling saturates at the cap."""
    rands = iter([0.0, 1.0, 0.5, 0.5, 1.0])
    rc, _, _, delays = _run_supervise(
        [1, 1, 1, 1, 1, 0], backoff=1.0, backoff_cap=4.0, jitter=0.5,
        _rand=lambda: next(rands))
    assert rc == 0
    # base delays 1,2,4(cap),4(cap),4(cap); factors 1, 0.5, 0.75,
    # 0.75, 0.5 — never above the cap, still spread AT the cap
    assert delays == [1.0, 1.0, 3.0, 3.0, 2.0]
    assert all(d <= 4.0 for d in delays)
    # jitter=0 is the exact historical doubling
    rc, _, _, delays = _run_supervise([1, 1, 0], backoff=1.0,
                                      backoff_cap=60.0, jitter=0.0)
    assert delays == [1.0, 2.0]


def test_supervise_elastic_degrades_after_streak():
    """Two consecutive peer-loss exits trigger the probe; a degraded
    probe rewrites the child env to the shrunken world."""
    probes = []

    def probe():
        probes.append(1)
        return {"n_processes": 1, "n_devices": 2, "local_devices": 2,
                "degraded": True}

    rc, logs, envs, _ = _run_supervise(
        [43, 42, 0], elastic=True, min_devices=1, probe=probe, backoff=0.0,
        env={"COORDINATOR_ADDRESS": "h:1", "NNPT_NUM_PROCESSES": "2",
             "NNPT_PROCESS_ID": "0"})
    assert rc == 0 and probes == [1]
    assert "COORDINATOR_ADDRESS" not in envs[2]
    assert envs[2]["NNPT_NUM_PROCESSES"] == "1"
    assert any("DEGRADED" in m for m in logs)
    # a lone peer loss followed by a crash never probes (streak resets)
    probes.clear()
    rc, _, _, _ = _run_supervise([43, 1, 43, 0], elastic=True, probe=probe,
                                 backoff=0.0)
    assert rc == 0 and probes == []


def test_supervise_elastic_fences_nonzero_rank():
    """Split-brain fence: during a partition EVERY surviving host's
    supervisor sees a peer-loss streak and a degraded local probe — if
    all of them relaunched as process 0, two divergent leaders would
    interleave writes over the same shared checkpoint dir.  Only the
    original rank 0 may continue alone; the rest retry at the current
    world until their budget runs out."""
    probes = []

    def probe():
        probes.append(1)
        return {"n_processes": 1, "n_devices": 2, "local_devices": 2,
                "degraded": True}

    rc, logs, envs, _ = _run_supervise(
        [43] * 6, elastic=True, probe=probe, backoff=0.0,
        env={"COORDINATOR_ADDRESS": "h:1", "NNPT_NUM_PROCESSES": "2",
             "NNPT_PROCESS_ID": "1"})
    assert rc == 43 and probes == []            # never probed, never shrank
    assert all(e["COORDINATOR_ADDRESS"] == "h:1" for e in envs)
    assert all(e["NNPT_PROCESS_ID"] == "1" for e in envs)
    assert any("fenced from degraded relaunch" in m for m in logs)
    # a multi-process world whose rank came from some OTHER channel
    # (no NNPT_PROCESS_ID) fences too: "every host assumes it is rank
    # 0" is exactly the split brain the fence exists to prevent
    rc, logs, _, _ = _run_supervise(
        [43] * 6, elastic=True, probe=probe, backoff=0.0,
        env={"COORDINATOR_ADDRESS": "h:1", "NNPT_NUM_PROCESSES": "2"})
    assert rc == 43 and probes == []
    assert any("rank unknown" in m for m in logs)
    # a single-process original world has no peers to split-brain with:
    # degrading (fewer local devices) stays allowed
    probes.clear()
    rc, _, _, _ = _run_supervise(
        [43, 43, 0], elastic=True, probe=probe, backoff=0.0, env={})
    assert rc == 0 and probes == [1]


def test_supervise_probe_failure_retries_same_world():
    probes = []

    def probe():
        probes.append(1)
        return None

    rc, logs, envs, _ = _run_supervise(
        [43, 43, 0], elastic=True, probe=probe, backoff=0.0,
        env={"COORDINATOR_ADDRESS": "h:1", "NNPT_PROCESS_ID": "0"})
    assert rc == 0 and probes == [1]
    assert envs[2]["COORDINATOR_ADDRESS"] == "h:1"  # world unchanged
    assert any("retrying at the current world" in m for m in logs)


def test_supervise_capacity_exhaustion_exits_46():
    """A probe that can never meet --min_devices parks, consumes the
    restart budget, and exits 46 naming the shortfall."""
    rc, logs, envs, delays = _run_supervise(
        [43, 43], max_restarts=4, elastic=True, min_devices=4, backoff=0.0,
        probe=lambda: {"n_processes": 1, "n_devices": 1,
                       "local_devices": 1, "degraded": True})
    assert rc == 46
    assert len(envs) == 2  # never relaunched below the floor
    assert any("capacity shortfall" in m and "--min_devices 4" in m
               for m in logs)
    assert any("exiting 46" in m for m in logs)


def test_supervise_parked_probe_failure_keeps_parking():
    """Once PARKED on a known shortfall, a transient probe failure must
    keep parking (consuming the budget), not relaunch below the floor —
    the child's own floor check would turn that relaunch into a
    permanent no-retry exit 46 while capacity is merely slow to
    return."""
    answers = iter([
        {"n_processes": 1, "n_devices": 1, "degraded": True},  # shortfall
        None,                                                  # blip
        {"n_processes": 1, "n_devices": 1, "degraded": True},  # shortfall
        None,
    ])
    rc, logs, envs, _ = _run_supervise(
        [43, 43], max_restarts=5, elastic=True, min_devices=2,
        backoff=0.0, probe=lambda: next(answers))
    assert rc == 46
    assert len(envs) == 2           # never relaunched below the floor
    assert any("no topology answer (probe failed)" in m for m in logs)
    assert any("exiting 46 (capacity abort)" in m for m in logs)


def test_supervise_does_not_retry_exit_46():
    rc, logs, envs, _ = _run_supervise([46], elastic=False)
    assert rc == 46 and len(envs) == 1
    assert any("not retrying" in m for m in logs)


# ------------------------------------------- world formation (typed)


def test_world_setup_dead_coordinator_typed_error():
    """Satellite regression: a dead coordinator address raises the TYPED
    CoordinatorUnreachable within the timeout — never a hang, never the
    native fatal abort (the preflight rendezvous fires before
    jax.distributed.initialize can)."""
    t0 = time.monotonic()
    with pytest.raises(CoordinatorUnreachable) as ei:
        world_setup(coordinator_address=f"127.0.0.1:{_free_port()}",
                    num_processes=2, process_id=1, timeout_s=3)
    assert time.monotonic() - t0 < 30
    assert "coordinator" in str(ei.value).lower()
    assert isinstance(ei.value, WorldFormationError)


def test_world_setup_missing_peer_typed_error():
    """The coordinator role distinguishes its failure mode: the peers
    never checked in -> PeerMissing naming the missing ranks."""
    with pytest.raises(PeerMissing) as ei:
        world_setup(coordinator_address=f"127.0.0.1:{_free_port()}",
                    num_processes=2, process_id=0, timeout_s=2)
    assert "rank(s) [1]" in str(ei.value)


def test_world_setup_busy_preflight_port_typed_error():
    """A coordinator that cannot bind the preflight rendezvous port must
    fail TYPED (exit-43 retryable), never silently skip: the peers still
    require the rendezvous, so a one-sided skip would make a fully
    healthy world unformable whenever coordinator_port+1 is taken."""
    blocker = None
    for _ in range(10):
        port = _free_port()
        blocker = socket.socket()
        blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            blocker.bind(("", port + 1))
            blocker.listen(1)
            break
        except OSError:
            blocker.close()
            blocker = None
    assert blocker is not None, "no adjacent free port pair found"
    try:
        t0 = time.monotonic()
        with pytest.raises(WorldFormationError) as ei:
            world_setup(coordinator_address=f"127.0.0.1:{port}",
                        num_processes=2, process_id=0, timeout_s=2)
        assert time.monotonic() - t0 < 30
        assert str(port + 1) in str(ei.value)
        assert "NNPT_PREFLIGHT_PORT" in str(ei.value)
    finally:
        blocker.close()


def test_collective_timeout_bounded():
    """distributed._bounded: the containment primitive under every
    cross-host barrier/allgather — overruns raise CollectiveTimeout,
    completions pass through, exceptions re-raise, 0 = inline."""
    assert distributed._bounded(lambda: 7, "t", timeout_s=5.0) == 7
    assert distributed._bounded(lambda: 7, "t", timeout_s=0) == 7
    with pytest.raises(distributed.CollectiveTimeout):
        distributed._bounded(lambda: time.sleep(30), "stall",
                             timeout_s=0.2)
    with pytest.raises(ValueError):
        distributed._bounded(lambda: (_ for _ in ()).throw(
            ValueError("boom")), "t", timeout_s=5.0)
    # config plumbing: explicit override wins over env
    distributed.set_collective_timeout(12.5)
    try:
        assert distributed.collective_timeout_s() == 12.5
    finally:
        distributed.set_collective_timeout(None)
    os.environ[distributed.COLLECTIVE_TIMEOUT_ENV] = "3"
    try:
        assert distributed.collective_timeout_s() == 3.0
    finally:
        del os.environ[distributed.COLLECTIVE_TIMEOUT_ENV]


def test_capacity_fault_kinds_parse():
    plan = faults_lib.FaultPlan.parse(
        "peer_kill@5?proc=1,peer_hang@7?proc=0,device_loss@3?once=/tmp/x")
    kinds = {f.kind: f for f in plan.faults}
    assert kinds["peer_kill"].proc == 1
    assert kinds["peer_hang"].proc == 0
    assert kinds["device_loss"].once_marker == "/tmp/x"
    # proc-gating: a fault owned by another process never fires here
    plan2 = faults_lib.FaultPlan.parse("peer_kill@1?proc=7")
    plan2.apply(1, {})  # would SIGKILL this process if mis-gated


# ------------------------------------------------- data-order continuity


def test_consumed_samples_and_inverse(mesh8):
    data = {"x": np.random.randn(64, 2).astype(np.float32),
            "y": np.random.randn(64, 1).astype(np.float32)}
    ld8 = ShardedLoader(mesh8, data, batch_size=8)
    assert ld8.steps_per_epoch == 8
    assert ld8.consumed_samples(0) == 0
    assert ld8.consumed_samples(3) == 24
    assert ld8.consumed_samples(8) == 64      # exactly one epoch
    assert ld8.consumed_samples(11) == 64 + 24
    # inverse under the SAME batch size: exact roundtrip
    for step in (0, 3, 8, 11):
        ep, st = ld8.start_for_samples(ld8.consumed_samples(step))
        assert ep * ld8.steps_per_epoch + st == step
    # a batch-size change rounds DOWN to the batch boundary (re-train up
    # to bs-1 samples, never skip any)
    ld16 = ShardedLoader(mesh8, data, batch_size=16)
    assert ld16.start_for_samples(24) == (0, 1)   # 24 = 1.5 x 16
    assert ld16.start_for_samples(64) == (1, 0)
    assert ld16.start_for_samples(64 + 24) == (1, 1)


def test_same_epoch_permutation_across_batch_sizes(mesh8):
    """The world-size-independence claim itself: (seed, epoch, salt)
    fully determine the per-epoch sample order, so loaders with
    different batch sizes walk the SAME permutation."""
    data = {"x": np.arange(64, dtype=np.float32).reshape(64, 1),
            "y": np.zeros((64, 1), np.float32)}
    a = ShardedLoader(mesh8, data, batch_size=8)
    b = ShardedLoader(mesh8, data, batch_size=16)
    a.order_salt = b.order_salt = 1234
    np.testing.assert_array_equal(a._epoch_order(3), b._epoch_order(3))


# ------------------------------------------- cross-world resharding


@pytest.mark.slow
@pytest.mark.parametrize("dp_from,dp_to", [(4, 2), (2, 1), (2, 4)])
def test_elastic_restore_replicated_bitwise(tmp_path, devices,
                                            dp_from, dp_to):
    """Satellite: params restored N->M (shrink AND grow-back) are
    bitwise-identical to the saved host state for replicated DP."""
    t_from = Trainer(_cfg(dp_from, tmp_path, resume=False),
                     mesh=_mesh(devices, dp_from))
    t_from.fit()
    saved = _host_leaves(t_from.state)

    t_to = Trainer(_cfg(dp_to, tmp_path), mesh=_mesh(devices, dp_to))
    t_to.init_state()
    assert t_to.maybe_resume() == 8
    for a, b in zip(saved, _host_leaves(t_to.state)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_elastic_restore_zero1_reshard(tmp_path, devices):
    """zero1's flat per-dp-padded buffers re-pad for the new data-axis
    size; the reassembled-then-resharded state round-trips bitwise back
    to the original world, and only zeros ever move."""
    t4 = Trainer(_cfg(4, tmp_path, resume=False, update_sharding="zero1"),
                 mesh=_mesh(devices, 4))
    t4.fit()
    saved = _host_leaves(t4.state)

    d2 = tmp_path / "w2"
    t2 = Trainer(_cfg(2, tmp_path, update_sharding="zero1"),
                 mesh=_mesh(devices, 2))
    t2.init_state()
    assert t2.maybe_resume() == 8
    # re-save from the shrunken world (the layout facts the Trainer's
    # own save path would record)
    ckpt.save(str(d2), t2.state,
              extra_meta={"saved_world": {"dp": 2,
                                          "update_sharding": "zero1"}})

    # grow back 2 -> 4: bitwise round trip against the original state
    t4b = Trainer(_cfg(4, str(d2), update_sharding="zero1"),
                  mesh=_mesh(devices, 4))
    t4b.init_state()
    assert t4b.maybe_resume() == 8
    for a, b in zip(saved, _host_leaves(t4b.state)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_zero1_mismatch_refused_without_elastic(tmp_path, devices):
    """Without --elastic a cross-world zero1 snapshot stays the loud
    shape error it always was (and the message points at --elastic)."""
    t4 = Trainer(_cfg(4, tmp_path, resume=False, update_sharding="zero1"),
                 mesh=_mesh(devices, 4))
    t4.fit()
    t2 = Trainer(_cfg(2, tmp_path, update_sharding="zero1", elastic=False),
                 mesh=_mesh(devices, 2))
    t2.init_state()
    with pytest.raises(ValueError, match="--elastic"):
        ckpt.restore(str(tmp_path), t2.state, elastic=False)


def test_zero1_repad_restricted_to_opt_state(tmp_path):
    """The elastic repad gate applies ONLY to opt-state flat buffers: a
    1-D model param (bias, norm scale) whose length changed is a config
    mismatch that must refuse loudly, never be silently zero-extended."""
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )

    world = {"saved_world": {"dp": 4, "update_sharding": "zero1"}}
    saved = TrainState(step=jnp.asarray(3, jnp.int32),
                       params={"b": jnp.arange(4, dtype=jnp.float32)},
                       opt_state={"m": jnp.arange(8, dtype=jnp.float32)})
    ckpt.save(str(tmp_path), saved, extra_meta=world)

    # an opt-state flat buffer growing for the new dp: reshards
    grown_opt = TrainState(step=jnp.zeros((), jnp.int32),
                           params={"b": jnp.zeros(4, jnp.float32)},
                           opt_state={"m": jnp.zeros(12, jnp.float32)})
    out = ckpt.restore(str(tmp_path), grown_opt, elastic=True)
    np.testing.assert_array_equal(
        np.asarray(out.opt_state["m"]),
        np.concatenate([np.arange(8, dtype=np.float32),
                        np.zeros(4, np.float32)]))

    # the SAME length mismatch on a 1-D param leaf stays a loud error
    grown_param = TrainState(step=jnp.zeros((), jnp.int32),
                             params={"b": jnp.zeros(6, jnp.float32)},
                             opt_state={"m": jnp.zeros(8, jnp.float32)})
    with pytest.raises(ValueError, match="wrong model config"):
        ckpt.restore(str(tmp_path), grown_param, elastic=True)


def test_repad_axis_never_drops_state():
    from neural_networks_parallel_training_with_mpi_tpu.utils.checkpoint import (  # noqa: E501
        _repad_axis,
    )

    buf = np.array([1., 2., 3., 0., 0., 0.], np.float32)
    np.testing.assert_array_equal(_repad_axis(buf, (4,), 0),
                                  [1., 2., 3., 0.])
    np.testing.assert_array_equal(_repad_axis(buf, (8,), 0),
                                  [1., 2., 3., 0., 0., 0., 0., 0.])
    with pytest.raises(ValueError, match="nonzero"):
        _repad_axis(np.array([1., 2., 3., 4.], np.float32), (3,), 0)
    # per-leaf ('sharded') layouts pad an interior dim of an n-D leaf:
    # the one differing dim is re-padded, zeros only
    m = np.zeros((4, 3), np.float32)
    m[:2] = 1.0
    np.testing.assert_array_equal(_repad_axis(m, (2, 3), 0),
                                  np.ones((2, 3), np.float32))
    grown = _repad_axis(m, (6, 3), 0)
    assert grown.shape == (6, 3) and np.all(grown[4:] == 0)
    with pytest.raises(ValueError, match="nonzero"):
        _repad_axis(np.ones((4, 3), np.float32), (2, 3), 0)


# ------------------------------------------------- topology lineage


@pytest.mark.slow
def test_saved_world_recorded_and_lineage_not_shadowed(tmp_path, devices):
    """Satellite: checkpoint meta written by a shrunken world exposes
    BOTH saved_world (the shrunken saver) and restored_world (the
    original topology), and the fsck audit line renders them."""
    t4 = Trainer(_cfg(4, tmp_path, resume=False), mesh=_mesh(devices, 4))
    t4.fit()
    meta = ckpt.read_meta(str(tmp_path))
    assert meta["saved_world"]["dp"] == 4
    assert meta["saved_world"]["n_devices"] == jax.device_count()
    assert meta["consumed_samples"] == 64
    assert "restored_world" not in meta
    # the manifest carries the world too (stdlib side, for the
    # supervisor's relaunch log)
    man = json.loads(
        (tmp_path / "ckpt-8" / ckpt_manifest.MANIFEST).read_text())
    assert man["saved_world"]["dp"] == 4

    t2 = Trainer(_cfg(2, tmp_path, nepochs=2), mesh=_mesh(devices, 2))
    t2.fit()  # resumes dp=4 snapshot, trains epoch 2, saves as dp=2
    meta2 = ckpt.read_meta(str(tmp_path))
    assert meta2["saved_world"]["dp"] == 2
    assert meta2["restored_world"]["dp"] == 4  # lineage carried forward

    line = ckpt_manifest.world_line(meta2)
    assert "dp=2" in line and "restored_world" in line and "dp=4" in line

    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "ckpt_fsck.py"),
         str(tmp_path)], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "saved_world" in out.stdout and "dp=2" in out.stdout


def test_world_line_rendering():
    assert ckpt_manifest.world_line({}) == ""
    assert ckpt_manifest.world_line(
        {"saved_world": {"n_devices": 8, "n_processes": 2, "dp": 8,
                         "update_sharding": "zero1"}}) == \
        "saved_world 8d/2p/dp=8/zero1"
    line = ckpt_manifest.world_line(
        {"saved_world": {"n_devices": 1, "dp": 1},
         "restored_world": {"n_devices": 2, "dp": 2}})
    assert line == "saved_world 1d/dp=1, restored_world 2d/dp=2"


# ------------------------------------------------- batch policy


@pytest.mark.slow
def test_elastic_batch_policy_global_raises_accum(tmp_path, devices):
    t4 = Trainer(_cfg(4, tmp_path, resume=False), mesh=_mesh(devices, 4))
    t4.fit()
    t2 = Trainer(_cfg(2, tmp_path, elastic_batch="global"),
                 mesh=_mesh(devices, 2))
    assert t2.cfg.batch_size == 8          # global batch preserved
    assert t2.cfg.accum_steps == 2         # memory bounded via accum
    assert t2._topology_change["policy"] == "global"
    assert t2._topology_change["accum_steps"] == [1, 2]


@pytest.mark.slow
def test_elastic_batch_policy_per_device_shrinks_batch(tmp_path, devices):
    t4 = Trainer(_cfg(4, tmp_path, resume=False), mesh=_mesh(devices, 4))
    t4.fit()
    t2 = Trainer(_cfg(2, tmp_path, elastic_batch="per_device"),
                 mesh=_mesh(devices, 2))
    assert t2.cfg.batch_size == 4          # per-device rows preserved
    assert t2.cfg.accum_steps == 1
    assert t2._topology_change["batch_size"] == [8, 4]
    # the resumed stream continues from the consumed-sample coordinate
    t2.init_state()
    start = t2.maybe_resume()
    assert start == 8
    # 64 samples consumed = exactly 1 epoch of the new 16-step loader
    assert t2._resume_plan == (1, 0)
    assert (start + t2._step_offset) == 16


@pytest.mark.slow
def test_rollback_remaps_step_offset(tmp_path, devices):
    """An anomaly rollback re-derives the step->position offset from the
    generation it actually lands on: the fallback chain can restore an
    older (old-world) snapshot than the one the elastic resume was keyed
    to, and a stale offset would walk the wrong sample window."""
    t4 = Trainer(_cfg(4, tmp_path, resume=False), mesh=_mesh(devices, 4))
    t4.fit()
    t2 = Trainer(_cfg(2, tmp_path, elastic_batch="per_device"),
                 mesh=_mesh(devices, 2))
    t2.init_state()
    start = t2.maybe_resume()
    want = t2._step_offset
    t2._step_offset = 999          # poison: rollback must not keep it
    t2._resume_plan = None
    assert t2._rollback() == start
    assert t2._step_offset == want
    assert t2._resume_plan == (1, 0)


@pytest.mark.slow
def test_topology_event_reaches_telemetry_and_summary(tmp_path, devices):
    """The effective-batch change is logged to telemetry (kind=topology)
    and tools/metrics_summary.py renders it."""
    t4 = Trainer(_cfg(4, tmp_path, resume=False), mesh=_mesh(devices, 4))
    t4.fit()
    td = tmp_path / "telem"
    t2 = Trainer(_cfg(2, tmp_path, nepochs=2, telemetry_dir=str(td),
                      elastic_batch="global"), mesh=_mesh(devices, 2))
    t2.fit()
    recs = [json.loads(l)
            for l in (td / "metrics.jsonl").read_text().splitlines()]
    (topo,) = [r for r in recs if r.get("kind") == "topology"]
    assert topo["policy"] == "global"
    assert topo["from_world"]["dp"] == 4 and topo["to_world"]["dp"] == 2
    assert topo["accum_steps"] == [1, 2]

    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "metrics_summary.py"),
         str(td)], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "topology:" in out.stdout and "dp 4 -> 2" in out.stdout


# ------------------------------------------------- CLI plumbing


def test_cli_flags_plumbed():
    args = build_argparser().parse_args(
        ["--elastic", "--min_devices", "2", "--elastic_batch",
         "per_device", "--collective_timeout", "30",
         "--supervise_backoff_max", "7"])
    cfg = config_from_args(args)
    assert cfg.elastic and cfg.min_devices == 2
    assert cfg.elastic_batch == "per_device"
    assert cfg.collective_timeout == 30.0
    assert args.supervise_backoff_max == 7.0
    # defaults: elastic off, no floor, unbounded collectives
    cfg0 = config_from_args(build_argparser().parse_args([]))
    assert not cfg0.elastic and cfg0.min_devices == 0
    assert cfg0.collective_timeout == 0.0


def test_tools_supervise_elastic_flags():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "supervise.py"), "--help"],
        capture_output=True, text=True)
    assert out.returncode == 0
    assert "--elastic" in out.stdout and "--min-devices" in out.stdout
    assert "--probe-timeout" in out.stdout


def test_trainer_enforces_min_devices_floor(tmp_path, devices):
    """The capacity floor is the CHILD's own contract too: a Trainer
    constructed below --min_devices raises CapacityAbort (-> exit 46)."""
    with pytest.raises(resilience.CapacityAbort, match="min_devices"):
        Trainer(_cfg(2, tmp_path, resume=False, min_devices=99),
                mesh=_mesh(devices, 2))


@pytest.mark.slow
def test_cli_min_devices_floor_exits_46(tmp_path):
    """The CHILD enforces the capacity floor itself (even under a dumb
    generic supervisor): a world below --min_devices exits 46."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop(faults_lib.ENV_VAR, None)
    out = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "2", "--dataset",
         "regression", "--n_samples", "16", "--nepochs", "1",
         "--min_devices", "99"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=str(REPO))
    assert out.returncode == 46, (out.stdout, out.stderr)
    assert "capacity abort" in out.stdout + out.stderr


# ------------------------------------------------- probes (subprocess)


@pytest.mark.slow
def test_default_probe_reports_local_topology():
    res = resilience.default_probe(timeout_s=120)
    assert res is not None
    assert res["n_devices"] >= 1 and res["degraded"] is False


@pytest.mark.slow
def test_probe_world_dead_coordinator_degrades_locally():
    """probe_world against a dead coordinator must neither hang nor
    poison the caller: bounded subprocess, local-topology fallback with
    degraded=True."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (  # noqa: E501
        probe_world,
    )

    logs = []
    res = probe_world(coordinator_address=f"127.0.0.1:{_free_port()}",
                      num_processes=2, process_id=0, timeout_s=8,
                      log=logs.append)
    assert res is not None and res["degraded"] is True
    assert res["n_processes"] == 1 and res["n_devices"] >= 1
    assert any("local topology" in m for m in logs)


# ------------------------------------------------- chaos lane (e2e)


def _spawn_elastic_pair(tmp_path, extra_common=(), kill_step=5,
                        nepochs=6, timeout_s=420):
    """The acceptance scenario: a 2-process world (1 CPU device each)
    where process 0 runs under the integrated elastic supervisor and
    process 1 is SIGKILLed mid-run.  Returns (supervisor result, victim
    result)."""
    port = _free_port()
    ck = tmp_path / "ckpt"
    common = ["--platform", "cpu", "--dataset", "regression",
              "--n_samples", "32", "--batch_size", "8", "--no-full-batch",
              "--nepochs", str(nepochs), "--checkpoint_dir", str(ck),
              "--checkpoint_every", "2", "--elastic",
              "--hang_timeout", "15", "--collective_timeout", "10",
              *extra_common]

    def env_for(pid):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop(faults_lib.ENV_VAR, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NNPT_NUM_PROCESSES"] = "2"
        env["NNPT_PROCESS_ID"] = str(pid)
        env["NNPT_WORLD_TIMEOUT_S"] = "12"
        return env

    pkg = "neural_networks_parallel_training_with_mpi_tpu"
    sup = subprocess.Popen(
        [sys.executable, "-m", pkg, *common, "--supervise", "4",
         "--supervise_backoff", "0.2", "--supervise_backoff_max", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env_for(0), cwd=str(REPO))
    victim = subprocess.Popen(
        [sys.executable, "-m", pkg, *common,
         "--faults", f"peer_kill@{kill_step}"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env_for(1), cwd=str(REPO))
    try:
        v_out, _ = victim.communicate(timeout=timeout_s)
        s_out, _ = sup.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        victim.kill()
        sup.kill()
        pytest.fail("elastic chaos scenario did not complete in time")
    return (sup.returncode, s_out), (victim.returncode, v_out)


@pytest.mark.chaos
@pytest.mark.slow
def test_peer_kill_degrades_to_world1_and_completes(tmp_path):
    """Acceptance: peer_kill mid-run -> supervised relaunch at world=1
    via the topology probe -> resharded restore of the last verified
    snapshot -> finite loss -> exit 0."""
    (sup_rc, sup_out), (v_rc, v_out) = _spawn_elastic_pair(tmp_path)
    assert v_rc == -9 or v_rc == 137, (v_rc, v_out[-500:])
    assert "injected peer_kill" in v_out
    assert sup_rc == 0, sup_out[-4000:]
    # the probe found the shrunken world and the supervisor degraded
    assert "topology probe: 1 healthy device(s)" in sup_out
    assert "DEGRADED world" in sup_out
    # the relaunch log names the saving topology of the restore target
    assert "saved_world 2d/2p/dp=2" in sup_out
    # the child rode the reshard path and the batch policy
    assert "resuming a dp=2 checkpoint on dp=1" in sup_out
    assert "elastic restore of a 2-device snapshot onto 1 device(s)" \
        in sup_out
    assert "done: final loss" in sup_out
    assert "nan" not in sup_out.split("done: final loss", 1)[1][:40]
    # the run really finished all epochs on the shrunken world
    assert ckpt.latest_step(str(tmp_path / "ckpt")) == 24


@pytest.mark.chaos
@pytest.mark.slow
def test_peer_kill_below_min_devices_exits_46(tmp_path):
    """Acceptance: the same scenario with --min_devices 2 exits 46
    without a degraded relaunch, and the log names the shortfall."""
    (sup_rc, sup_out), (v_rc, v_out) = _spawn_elastic_pair(
        tmp_path, extra_common=("--min_devices", "2"), kill_step=3,
        nepochs=4)
    assert v_rc in (-9, 137), (v_rc, v_out[-500:])
    assert sup_rc == 46, sup_out[-4000:]
    assert "capacity shortfall" in sup_out
    assert "--min_devices 2" in sup_out
    assert "exiting 46 (capacity abort)" in sup_out
    assert "DEGRADED world" not in sup_out  # never relaunched below floor


@pytest.mark.chaos
def test_device_loss_supervised_retry_resumes(tmp_path):
    """device_loss: the runtime-lost-a-chip stand-in exits 43 and the
    supervisor retries; with `once=` the relaunch resumes from the
    newest snapshot and completes."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop(faults_lib.ENV_VAR, None)
    marker = tmp_path / "lost"
    out = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "2", "--dataset",
         "regression", "--n_samples", "32", "--batch_size", "8",
         "--no-full-batch", "--nepochs", "4",
         "--checkpoint_dir", str(tmp_path / "c"),
         "--checkpoint_every", "3",
         "--faults", f"device_loss@9?once={marker}",
         "--supervise", "2", "--supervise_backoff", "0.1"],
        capture_output=True, text=True, timeout=360, env=env,
        cwd=str(REPO))
    text = out.stdout + out.stderr
    assert out.returncode == 0, text[-3000:]
    assert "injected device_loss" in text
    assert "child exit 43 (peer loss)" in text
    assert "[supervise] attempt 2" in text
    assert marker.exists()
    assert "[supervise] child completed" in text
