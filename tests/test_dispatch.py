"""Multi-step dispatch (--steps_per_dispatch, VERDICT r4 item 6): k
optimizer steps per host dispatch via ``lax.scan`` over a device-staged
batch stack replays the SAME batches in the SAME order while cutting host
round trips k-fold (the reference pays one gather-average-send per step,
:149-211).  Trajectory contract by layout: BITWISE-identical final weights
on the plain-DP shard_map path (the scan body is the very same shard_map
program); same-math-within-compile-noise on the scanned GSPMD and
ring-attention SP bodies, where XLA's fusion order inside the scan differs
from the standalone step (ULP-level drift, bounded below)."""

import jax
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig, build_argparser,
    config_from_args,
)
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
    Trainer,
)


def _base_cfg(**kw):
    return TrainConfig(
        lr=0.01, momentum=0.9, nepochs=2, batch_size=5, full_batch=False,
        shuffle=True, log_every=0,
        data=DataConfig(dataset="regression"),
        model=ModelConfig(),          # the reference 2->3->1 MLP
        mesh=MeshConfig(data=8),
        **kw)


def _fit_params(cfg):
    tr = Trainer(cfg)
    res = tr.fit()
    return jax.device_get(tr.state.params), res


def _assert_tree_equal(a, b):
    fa, _ = jax.tree_util.tree_flatten(a)
    fb, _ = jax.tree_util.tree_flatten(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_k3_trajectory_identical_dp():
    """DP MLP: 16 samples / batch 5 -> 4 steps/epoch (uneven tail), k=3
    -> groups of 3+1 per epoch.  Final weights bitwise-equal to k=1."""
    p1, r1 = _fit_params(_base_cfg())
    p3, r3 = _fit_params(_base_cfg(steps_per_dispatch=3))
    assert r1["steps"] == r3["steps"]
    _assert_tree_equal(p1, p3)
    np.testing.assert_allclose(r1["final_loss"], r3["final_loss"],
                               rtol=1e-6)


@pytest.mark.slow  # 4 jit compiles of the GSPMD LM step (~60s); the
# bitwise DP parity above is the core-lane guard (VERDICT r4 item 8)
def test_k2_trajectory_identical_transformer_tensor():
    """GSPMD tensor=2 transformer LM: the scan wraps a jit+annotation
    step.  Unlike the explicit shard_map DP path (bitwise above), XLA
    compiles the scanned GSPMD body with different fusion order than the
    standalone step — measured ULP-level (~1e-8) per-step differences
    that adam's ~grad/sqrt(v) normalization amplifies on near-zero-v
    early steps.  The contract is therefore same-math-within-compile-
    noise: close to float32 fusion tolerance after 22 steps, not
    bitwise."""
    import tempfile

    text = (b"the quick brown fox jumps over the lazy dog. " * 60)
    with tempfile.NamedTemporaryFile(suffix=".txt", delete=False) as f:
        f.write(text)
        path = f.name

    def cfg(k):
        return TrainConfig(
            lr=1e-3, nepochs=2, batch_size=8, full_batch=False,
            optimizer="adam", loss="cross_entropy", log_every=0,
            steps_per_dispatch=k,
            data=DataConfig(dataset="text", text_file=path, seq_len=32),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=256,
                              max_seq_len=32),
            mesh=MeshConfig(data=4, tensor=2))

    p1, r1 = _fit_params(cfg(1))
    p2, r2 = _fit_params(cfg(2))
    assert r1["steps"] == r2["steps"]
    for x, y in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-3, rtol=1e-2)
    np.testing.assert_allclose(r1["final_loss"], r2["final_loss"],
                               rtol=1e-3)


def test_cli_flag_plumbed():
    args = build_argparser().parse_args(["--steps_per_dispatch", "4"])
    assert config_from_args(args).steps_per_dispatch == 4
    assert TrainConfig().steps_per_dispatch == 1   # default off


@pytest.mark.slow  # two SP shard_map fits (~40s); lane budget (round 5)
def test_k2_trajectory_identical_seq_parallel():
    """Ring-attention SP layout: epoch_groups stacks through
    spmd.place_batch_stack (seq-sharded dim 2) and the scan replays the
    SAME batches in the SAME order — but unlike the plain-DP shard_map
    path (bitwise above), XLA compiles the scanned ring-attention body
    with different fusion order than the standalone step, so the contract
    is same-math-within-compile-noise: the argued tolerance its GSPMD
    sibling (test_k2_trajectory_identical_transformer_tensor) already
    uses, with adam's ~grad/sqrt(v) normalization amplifying ULP-level
    per-step drift on near-zero-v early steps."""

    def cfg(k):
        return TrainConfig(
            lr=1e-3, nepochs=2, batch_size=8, full_batch=False,
            optimizer="adam", loss="cross_entropy", log_every=0,
            steps_per_dispatch=k,
            data=DataConfig(dataset="lm", seq_len=32, n_samples=48),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=256,
                              max_seq_len=32, attention="ring"),
            mesh=MeshConfig(data=4, seq=2))

    p1, r1 = _fit_params(cfg(1))
    p2, r2 = _fit_params(cfg(2))
    assert r1["steps"] == r2["steps"]
    for x, y in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-3, rtol=1e-2)
    np.testing.assert_allclose(r1["final_loss"], r2["final_loss"],
                               rtol=1e-3)


def test_checkpoint_boundary_crossing():
    """checkpoint_every=2 with k=3: dispatches end at steps 3, 4 (epoch
    tail), 7, 8 — the crossing rule must fire at 3 (crosses 2), 4 (crosses
    4), 7 (crosses 6), 8 (crosses 8): every multiple is covered even when
    no dispatch lands on it exactly."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        cfg = _base_cfg(steps_per_dispatch=3, checkpoint_every=2,
                        checkpoint_dir=d)
        _, res = _fit_params(cfg)
        assert res["steps"] == 8
        import os

        assert os.path.exists(os.path.join(d, "checkpoint.npz")) or \
            os.listdir(d)
