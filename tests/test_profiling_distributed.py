"""Profiling utilities and single-host degradation of the multi-host
runtime helpers."""

import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.parallel import distributed
from neural_networks_parallel_training_with_mpi_tpu.utils import profiling


def test_step_timer_stats():
    import time

    t = profiling.StepTimer(skip_first=1)
    for _ in range(12):
        t.tick()
        time.sleep(0.002)
    s = t.stats()
    assert s["step_time_p50_ms"] >= 1.5
    assert s["step_time_p95_ms"] >= s["step_time_p50_ms"]
    assert s["steps_per_sec"] > 0


def test_trace_noop_without_dir():
    with profiling.trace(None):
        pass  # must not raise or start a profiler


def test_annotate_context():
    with profiling.annotate("unit-test-region"):
        x = np.ones(4).sum()
    assert x == 4


def test_single_host_degradation():
    assert not distributed.is_multi_host()
    distributed.barrier()  # no-op
    x = {"a": np.arange(3)}
    assert distributed.broadcast_host_array(x)["a"].tolist() == [0, 1, 2]
    gathered = distributed.allgather_host_array(x)
    assert gathered["a"].shape == (1, 3)  # leading process axis
    distributed.assert_same_across_hosts(x)  # no-op single host
    assert distributed.global_device_count() >= 1


def test_throughput_excludes_warmup():
    """samples_per_sec is steady-state: the first add() (the compile step)
    only starts the clock; its samples are not counted (VERDICT r1 item 8)."""
    import time

    from neural_networks_parallel_training_with_mpi_tpu.utils.logging import (
        Throughput,
    )

    thr = Throughput()
    assert thr.samples_per_sec == 0.0
    time.sleep(0.05)          # "compile" happens before the first add
    thr.add(1000)             # warmup batch: excluded, clock starts here
    t0 = time.perf_counter()
    time.sleep(0.02)
    thr.add(100)
    elapsed = time.perf_counter() - t0
    rate = thr.samples_per_sec
    assert rate > 0
    # only the 100 steady samples over ~elapsed; the 1000 warmup samples and
    # the 0.05s pre-warmup sleep must not appear in the rate
    assert rate <= 100 / elapsed * 1.01
    assert rate > 100 / (elapsed + 0.04)
