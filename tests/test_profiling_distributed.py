"""Profiling utilities and single-host degradation of the multi-host
runtime helpers."""

import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.parallel import distributed
from neural_networks_parallel_training_with_mpi_tpu.utils import profiling


def test_step_timer_stats():
    import time

    t = profiling.StepTimer(skip_first=1)
    for _ in range(12):
        t.tick()
        time.sleep(0.002)
    s = t.stats()
    assert s["step_time_p50_ms"] >= 1.5
    assert s["step_time_p95_ms"] >= s["step_time_p50_ms"]
    assert s["steps_per_sec"] > 0


def test_trace_noop_without_dir():
    with profiling.trace(None):
        pass  # must not raise or start a profiler


def test_annotate_context():
    with profiling.annotate("unit-test-region"):
        x = np.ones(4).sum()
    assert x == 4


def test_single_host_degradation():
    assert not distributed.is_multi_host()
    distributed.barrier()  # no-op
    x = {"a": np.arange(3)}
    assert distributed.broadcast_host_array(x)["a"].tolist() == [0, 1, 2]
    gathered = distributed.allgather_host_array(x)
    assert gathered["a"].shape == (1, 3)  # leading process axis
    distributed.assert_same_across_hosts(x)  # no-op single host
    assert distributed.global_device_count() >= 1
