"""Fleet autopilot tests (serve/autopilot.py + the PR's seams).

Core lane (fast, in-process):
* weight snapshots — manifest-verified roundtrip; corruption, missing
  leaves, and shape drift all refuse with ValueError.
* exit-code contract extension — EXIT_DECOMMISSION (47) is terminal
  for both ``supervise()`` and ``GroupSupervisor`` (no relaunch, no
  backoff burn); crash codes still retry under capped backoff;
  ``retire()`` makes ANY subsequent exit terminal (including SIGKILL)
  and cancels a pending relaunch.
* drain/death race regression — a replica whose ``drained`` report
  races its process exit must not double-requeue in-flight work.
* control loop — hysteresis holds, cooldown, bounded action backoff,
  scale-out/in decisions, stalled-drain escalation, canary judge
  promote/rollback — all on a fake-clock fleet stand-in over the REAL
  ``FleetRouter``, so the actuation surface is the tested one.
* generation-aware traffic — hashed canary slice, placement
  preference, per-completion generation attribution.

Slow/chaos lane (subprocess replicas, out of tier-1): the fleet fault
kinds (``replica_kill``, ``stall_drain``) and the corrupted-canary
rollback, end to end.
"""

import math
import pathlib
import sys
import time
import types

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    Autopilot, AutopilotConfig, FleetRouter, InprocReplica, LoadSignal,
    Scheduler, ServeConfig, launch_fleet, load_weight_snapshot,
    make_requests, save_weight_snapshot,
)
from neural_networks_parallel_training_with_mpi_tpu.serve.fleet import (
    GEN_STRIDE, ReplicaHandle,
)
from neural_networks_parallel_training_with_mpi_tpu.train.resilience import (
    EXIT_ANOMALY, EXIT_DECOMMISSION, ChildSpec, GroupSupervisor,
    supervise,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    ckpt_manifest, prng,
)

pytestmark = pytest.mark.fleet

REPO = pathlib.Path(__file__).resolve().parent.parent
V = 64


@pytest.fixture(scope="module")
def lm():
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    return model, model.init(prng.init_key(0))


def _sched(model, params, *, slots=4, queue_depth=16, replica=None):
    return Scheduler(model, params, ServeConfig(
        slots=slots, num_blocks=1 + slots * 4, block_size=16,
        prefill_chunk=16, queue_depth=queue_depth, replica=replica))


# ---------------------------------------------------------------------------
# weight snapshots
# ---------------------------------------------------------------------------

def test_snapshot_roundtrip_and_refusals(tmp_path):
    params = {"w": np.ones((3, 4), np.float32),
              "b": {"x": np.arange(5, dtype=np.int32)}}
    snap = save_weight_snapshot(tmp_path, params, step=3,
                                meta={"note": "t"})
    assert pathlib.Path(snap).name == "ckpt-3"
    assert ckpt_manifest.verify(snap) == []
    out = load_weight_snapshot(snap, params)
    assert np.array_equal(out["w"], params["w"])
    assert np.array_equal(out["b"]["x"], params["b"]["x"])
    # missing leaf: the template grew a head the snapshot never had
    grown = dict(params, extra=np.zeros((2,), np.float32))
    with pytest.raises(ValueError, match="missing leaf"):
        load_weight_snapshot(snap, grown)
    # shape drift
    drifted = dict(params, w=np.ones((3, 5), np.float32))
    with pytest.raises(ValueError, match="shape"):
        load_weight_snapshot(snap, drifted)
    # payload corruption: the manifest's sha256 catches it BEFORE any
    # bytes are deserialized
    p = pathlib.Path(snap) / "weights.npz"
    raw = bytearray(p.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    p.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="verification"):
        load_weight_snapshot(snap, params)


# ---------------------------------------------------------------------------
# exit-code contract: 47 is terminal (satellite: supervise coverage)
# ---------------------------------------------------------------------------

def test_supervise_decommission_terminal_crash_still_retries():
    """supervise(): 47 stops immediately (one attempt, no backoff
    burn); a crash code still retries with the capped exponential
    backoff schedule."""
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        resilience as res,
    )

    def run(code_seq, **kw):
        it = iter(code_seq)
        calls, sleeps = [], []

        def fake_call(cmd, env=None):
            rc = next(it)
            calls.append(rc)
            return rc

        orig = res.subprocess.call
        res.subprocess.call = fake_call
        try:
            rc = supervise(["x"], _sleep=sleeps.append,
                           _rand=lambda: 0.0, **kw)
        finally:
            res.subprocess.call = orig
        return rc, calls, sleeps

    rc, calls, sleeps = run([EXIT_DECOMMISSION], max_restarts=3,
                            backoff=0.5)
    assert rc == EXIT_DECOMMISSION
    assert len(calls) == 1          # terminal: no relaunch attempt
    assert sleeps == []             # and no backoff burned
    # the crash path is unchanged: capped exponential backoff, budget
    # spent, last code surfaced
    rc, calls, sleeps = run([1, 1, 1], max_restarts=2, backoff=0.5,
                            backoff_cap=0.6)
    assert rc == 1 and len(calls) == 3
    assert len(sleeps) == 2
    assert sleeps[0] == pytest.approx(0.5)
    assert sleeps[1] == pytest.approx(0.6)   # doubled, then capped


def _pump_group(g, until, timeout_s=15.0):
    evs = []
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        evs += g.poll()
        if until(evs):
            return evs
        time.sleep(0.02)
    raise AssertionError(f"condition never met; events={evs}")


def test_group_supervisor_exit47_terminal():
    spec = ChildSpec(name="decomm",
                     cmd=[sys.executable, "-c",
                          "raise SystemExit(47)"],
                     max_restarts=3, backoff=0.05)
    g = GroupSupervisor([spec], log=lambda m: None)
    g.start()
    evs = _pump_group(g, lambda evs: not g.running())
    kinds = [(e["child"], e["event"]) for e in evs]
    assert ("decomm", "stopped") in kinds
    assert ("decomm", "relaunch") not in kinds
    assert g.done("decomm") == EXIT_DECOMMISSION


def test_group_supervisor_retire_makes_any_exit_terminal():
    """retire(): the autopilot marks a child decommissioned BEFORE
    asking it to drain; even a SIGKILL escalation (rc outside the
    no-retry set) must then stop, not relaunch."""
    spec = ChildSpec(name="victim",
                     cmd=[sys.executable, "-c",
                          "import time; time.sleep(60)"],
                     max_restarts=3, backoff=0.05)
    g = GroupSupervisor([spec], log=lambda m: None)
    g.start()
    try:
        g.retire("victim")
        g.proc("victim").kill()
        evs = _pump_group(g, lambda evs: not g.running())
        kinds = [(e["child"], e["event"]) for e in evs]
        assert ("victim", "stopped") in kinds
        assert ("victim", "relaunch") not in kinds
        assert g.done("victim") is not None
    finally:
        g.terminate_all()


def test_group_supervisor_retire_cancels_pending_relaunch():
    """A child sitting in its backoff window when retire() lands must
    finalize at its last exit code instead of relaunching."""
    spec = ChildSpec(name="crashy",
                     cmd=[sys.executable, "-c",
                          "raise SystemExit(9)"],
                     max_restarts=5, backoff=30.0, backoff_cap=30.0)
    g = GroupSupervisor([spec], log=lambda m: None)
    g.start()
    try:
        _pump_group(g, lambda evs: any(e["event"] == "exit"
                                       for e in evs))
        # now inside the 30s backoff window: relaunch is pending
        g.retire("crashy")
        evs = _pump_group(g, lambda evs: not g.running(),
                          timeout_s=5.0)
        assert not any(e["event"] == "relaunch" for e in evs)
        assert g.done("crashy") == 9
    finally:
        g.terminate_all()


def test_group_supervisor_add_and_remove_child():
    g = GroupSupervisor([], log=lambda m: None)
    g.start()
    try:
        g.add_child(ChildSpec(
            name="late", cmd=[sys.executable, "-c",
                              "raise SystemExit(0)"],
            max_restarts=0, backoff=0.05))
        with pytest.raises(ValueError):
            g.add_child(ChildSpec(name="late", cmd=["x"]))
        _pump_group(g, lambda evs: g.done("late") is not None)
        g.remove_child("late")
        with pytest.raises(KeyError):
            g.done("late")
    finally:
        g.terminate_all()


# ---------------------------------------------------------------------------
# drain/death race regression (satellite)
# ---------------------------------------------------------------------------

class _RacyHandle(ReplicaHandle):
    """Completion events buffer like a subprocess pipe; ``drained`` can
    be populated like a worker's consumed-token report."""

    def __init__(self, name="racy"):
        self.name = name
        self._assigned = {}
        self.events = []
        self.drained = None
        self._alive = True

    def alive(self):
        return self._alive

    def accepting(self):
        return self._alive

    def load(self):
        return LoadSignal.from_report({
            "kind": "rollup", "role": "serve",
            "now": {"queue_depth": 0,
                    "in_flight": len(self._assigned),
                    "free_slots": max(0, 4 - len(self._assigned)),
                    "slots": 4, "queue_cap": 16, "free_blocks": 100,
                    "block_utilization": 0.0}})

    def submit(self, req):
        if not self._alive:
            return False
        self._assigned[req.rid] = req
        return True

    def pump(self):
        out, self.events = self.events, []
        for rec in out:
            self._assigned.pop(int(rec["rid"]), None)
        return out

    def assigned(self):
        return list(self._assigned)

    def take_assigned(self):
        rids = list(self._assigned)
        self._assigned.clear()
        return rids


def test_drained_report_racing_death_requeues_exactly_once(lm):
    """REGRESSION (drain/death race): a decommissioned replica emits
    its ``drained`` consumed-token report and exits; the death notice
    arrives with the report still buffered.  In-flight requests must
    requeue EXACTLY once — the drained report is observability, never a
    second requeue source — and a completion that raced the exit is
    honored, not re-run."""
    model, params = lm
    racy = _RacyHandle()
    router = FleetRouter([racy], queue_depth=16)
    rids = [router.submit([1 + i, 2], 3) for i in range(4)]
    router.pump()
    assert sorted(racy.assigned()) == sorted(rids)
    # worker story: completed rids[0], drained the rest, exited 47
    racy.events.append({"ev": "done", "rid": rids[0],
                        "tokens": [1, 2, 9], "ttft_ms": 1.0,
                        "itl_ms": 1.0})
    racy._assigned.pop(rids[0])
    racy.drained = [{"rid": rids[0], "prompt": [1, 2], "max_new": 3,
                     "slo_ms": None}]     # stale: includes the done one
    racy._alive = False
    router.on_replica_down(racy.name)
    assert router.requeued == 3           # exactly the in-flight set
    assert racy.drained is None           # consumed as observability
    # idempotent: a second death notice must not requeue again
    router.on_replica_down(racy.name)
    assert router.requeued == 3
    # the raced completion surfaces from the next pump, never re-runs
    done = router.pump()
    assert rids[0] in done
    assert router.done(rids[0])
    assert router.result(rids[0]) == [1, 2, 9]   # result() consumes
    # the requeued three complete on a replacement replica
    sink = InprocReplica(_sched(model, params, replica=1), name="sink")
    router.add_replica(sink)
    for _ in range(500):
        router.pump()
        if all(router.done(r) for r in rids[1:]):
            break
    assert all(router.done(r) for r in rids[1:])
    assert router.requeued == 3           # still exactly once
    sink.close()


# ---------------------------------------------------------------------------
# control loop on a fake clock (the real FleetRouter is the substrate)
# ---------------------------------------------------------------------------

class _CtrlReplica(ReplicaHandle):
    """A load-signal stub whose occupancy/readiness the test scripts."""

    def __init__(self, name, generation=0, slots=4, in_flight=0,
                 ready=True):
        self.name = name
        self.generation = generation
        self.slots = slots
        self.in_flight = in_flight
        self.ready = ready
        self.report = None

    def alive(self):
        return True

    def accepting(self):
        return self.ready

    def load(self):
        return LoadSignal.from_report({
            "kind": "rollup", "role": "serve",
            "now": {"queue_depth": 0, "in_flight": self.in_flight,
                    "free_slots": max(0, self.slots - self.in_flight),
                    "slots": self.slots, "queue_cap": 16,
                    "free_blocks": 100, "block_utilization": 0.0}})

    def submit(self, req):
        return False

    def pump(self):
        return []

    def assigned(self):
        return []

    def take_assigned(self):
        return []


class _FakeFleet:
    """The Fleet actuation surface over a real router, with scripted
    process lifecycle (spawn/exit) so no subprocess is needed."""

    def __init__(self, router):
        self.router = router
        self.spawned = []
        self.decommissioned = []
        self.killed = []
        self.done_rc = {}
        self.fail_spawn = False
        self._k = len(router.replicas)

    def add_replica(self, *, generation=0, ckpt=None, faults=None,
                    step_sleep_ms=None):
        if self.fail_spawn:
            raise RuntimeError("spawn refused")
        rid = generation * GEN_STRIDE + self._k
        self._k += 1
        h = _CtrlReplica(f"replica-{rid}", generation=generation,
                         ready=False)
        h.ckpt = ckpt
        self.router.add_replica(h, generation=generation)
        self.spawned.append(h)
        return h

    def decommission(self, name):
        self.decommissioned.append(name)
        return True

    def force_kill(self, name):
        self.killed.append(name)

    def replica_done(self, name):
        return self.done_rc.get(name)

    def remove_replica(self, name):
        try:
            self.router.remove_replica(name)
        except KeyError:
            pass


def _autopilot(handles, cfg, t0=0.0):
    clock = [t0]
    router = FleetRouter(handles, queue_depth=64)
    fleet = _FakeFleet(router)
    ap = Autopilot(fleet, cfg, now_fn=lambda: clock[0])
    return ap, fleet, router, clock


def _actions(ap):
    return [d["action"] for d in ap.decisions]


def test_scale_out_requires_hold_then_fires_once():
    """Hysteresis: the high signal must HOLD scale_out_hold_s — a blip
    resets the timer; after the action, cooldown guards the next."""
    cfg = AutopilotConfig(min_replicas=1, max_replicas=3,
                          interval_s=0.0, scale_out_hold_s=1.0,
                          cooldown_s=5.0)
    h = _CtrlReplica("replica-0", in_flight=8)      # occupancy 2.0
    ap, fleet, router, clock = _autopilot([h], cfg)
    ap.tick()
    assert _actions(ap) == []          # high noted, hold not met
    clock[0] = 0.6
    h.in_flight = 2                    # blip down: occ 0.5, mid-band
    ap.tick()
    clock[0] = 1.2                     # 1.2s since t=0 but hold RESET
    h.in_flight = 8
    ap.tick()
    assert _actions(ap) == []
    clock[0] = 2.3                     # held high 1.1s since t=1.2
    ap.tick()
    assert _actions(ap) == ["scale_out"]
    assert len(fleet.spawned) == 1
    assert fleet.spawned[0].generation == 0
    # still high, but one action is in flight + cooldown: no second
    clock[0] = 2.5
    ap.tick()
    assert _actions(ap) == ["scale_out"]
    # new replica reports ready -> reaction decision with timing
    fleet.spawned[0].ready = True
    clock[0] = 3.0
    ap.tick()
    assert _actions(ap)[-1] == "scale_out_ready"
    assert ap.decisions[-1]["reaction_s"] == pytest.approx(0.7)


def test_scale_out_failure_arms_exponential_backoff():
    cfg = AutopilotConfig(min_replicas=1, max_replicas=3,
                          interval_s=0.0, scale_out_hold_s=0.5,
                          cooldown_s=0.0, action_backoff_s=1.0,
                          action_backoff_cap_s=2.5)
    h = _CtrlReplica("replica-0", in_flight=8)
    ap, fleet, router, clock = _autopilot([h], cfg)
    fleet.fail_spawn = True
    ap.tick()
    clock[0] = 0.6
    ap.tick()
    assert _actions(ap) == ["action_backoff"]
    assert ap.decisions[-1]["backoff_s"] == pytest.approx(1.0)
    clock[0] = 2.3                     # past backoff; hold since 0.6
    ap.tick()
    clock[0] = 3.0
    ap.tick()
    assert ap.decisions[-1]["backoff_s"] == pytest.approx(2.0)
    clock[0] = 6.0
    ap.tick()
    clock[0] = 7.0
    ap.tick()
    assert ap.decisions[-1]["backoff_s"] == pytest.approx(2.5)  # cap


def test_scale_in_decommissions_newest_and_respects_min():
    cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                          interval_s=0.0, scale_in_hold_s=1.0,
                          cooldown_s=0.0, drain_timeout_s=5.0)
    a = _CtrlReplica("replica-0", in_flight=0)
    b = _CtrlReplica("replica-1", in_flight=0)
    ap, fleet, router, clock = _autopilot([a, b], cfg)
    ap.tick()
    clock[0] = 1.1
    ap.tick()
    assert _actions(ap) == ["scale_in"]
    assert fleet.decommissioned == ["replica-1"]    # newest out first
    # drain completes -> removed from the router, decision carries rc
    fleet.done_rc["replica-1"] = EXIT_DECOMMISSION
    clock[0] = 1.5
    ap.tick()
    assert _actions(ap)[-1] == "drained"
    assert ap.decisions[-1]["rc"] == EXIT_DECOMMISSION
    assert [h.name for h in router.replicas] == ["replica-0"]
    # at min_replicas now: the persisting low signal must NOT shrink
    clock[0] = 10.0
    ap.tick()
    clock[0] = 12.0
    ap.tick()
    assert "scale_in" not in _actions(ap)[1:]


def test_stalled_drain_escalates_to_force_kill():
    cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                          interval_s=0.0, scale_in_hold_s=0.5,
                          cooldown_s=0.0, drain_timeout_s=2.0)
    a = _CtrlReplica("replica-0")
    b = _CtrlReplica("replica-1")
    ap, fleet, router, clock = _autopilot([a, b], cfg)
    ap.tick()
    clock[0] = 0.6
    ap.tick()
    assert fleet.decommissioned == ["replica-1"]
    clock[0] = 2.7                     # past drain_timeout: escalate
    ap.tick()
    assert _actions(ap)[-1] == "drain_stalled_kill"
    assert fleet.killed == ["replica-1"]
    fleet.done_rc["replica-1"] = -9
    clock[0] = 3.0
    ap.tick()
    assert ap.decisions[-1]["action"] == "drained"
    assert ap.decisions[-1]["forced"] is True


def _slow_samples(router, t0, slow="replica-1", fast="replica-0",
                  n=6, slow_ms=100.0):
    for i in range(n):
        router.recent.append({"t": t0 + 0.1 * i, "replica": fast,
                              "ttft_ms": 20.0})
        router.recent.append({"t": t0 + 0.1 * i, "replica": slow,
                              "ttft_ms": slow_ms})


def test_health_eviction_replace_then_drain_respects_floor():
    """Degraded-replica eviction (DESIGN §11): windowed-TTFT verdict
    must HOLD evict_hold_s, the replacement spawns BEFORE the victim
    drains (the fleet never dips below min_replicas, even when the
    victim IS the floor), and the whole move shares the autoscaler's
    one-action-in-flight gate and cooldown."""
    cfg = AutopilotConfig(min_replicas=2, max_replicas=2,
                          interval_s=0.0, cooldown_s=5.0,
                          health_eviction=True, evict_ttft_ratio=3.0,
                          health_window_s=60.0, evict_hold_s=1.0,
                          evict_min_samples=4, drain_timeout_s=30.0)
    a = _CtrlReplica("replica-0", in_flight=2)
    b = _CtrlReplica("replica-1", in_flight=2)
    ap, fleet, router, clock = _autopilot([a, b], cfg)
    _slow_samples(router, 0.0)            # replica-1: 5x peer median
    clock[0] = 1.0
    ap.tick()
    assert _actions(ap) == []             # hysteresis: must hold first
    clock[0] = 2.1                        # unhealthy held 1.1s
    ap.tick()
    assert _actions(ap) == ["health_evict"]
    d = ap.decisions[-1]
    assert d["replica"] == "replica-1"
    assert d["replacement"] == fleet.spawned[0].name
    assert d["ttft_ratio"] == pytest.approx(5.0)
    # replace-then-drain: the victim still serves, width is +1 not -1
    assert fleet.decommissioned == []
    assert len(router.replicas) == 3
    # one-action gate: the pending replacement blocks a second eviction
    clock[0] = 2.2
    ap.tick()
    assert _actions(ap) == ["health_evict"]
    # replacement accepts -> victim drains; floor never violated
    fleet.spawned[0].ready = True
    clock[0] = 3.0
    ap.tick()
    assert _actions(ap)[-1] == "scale_out_ready"
    assert fleet.decommissioned == ["replica-1"]
    fleet.done_rc["replica-1"] = EXIT_DECOMMISSION
    clock[0] = 3.5
    ap.tick()
    assert _actions(ap)[-1] == "drained"
    assert ap.decisions[-1]["rc"] == EXIT_DECOMMISSION
    assert sorted(h.name for h in router.replicas) == \
        sorted(["replica-0", fleet.spawned[0].name])
    # cooldown (armed at the evict decision) gates the NEXT move: even
    # with a fresh degraded verdict, nothing fires before it expires
    _slow_samples(router, 4.0, slow="replica-0",
                  fast=fleet.spawned[0].name)
    clock[0] = 5.0
    ap.tick()
    clock[0] = 6.5
    ap.tick()
    assert _actions(ap).count("health_evict") == 1


def test_health_eviction_needs_peers_and_min_samples():
    """A lone replica is never evicted (no peers to compare against),
    and a thin sample window never convicts."""
    cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                          interval_s=0.0, cooldown_s=0.0,
                          health_eviction=True, evict_ttft_ratio=3.0,
                          health_window_s=60.0, evict_hold_s=0.0,
                          evict_min_samples=4)
    a = _CtrlReplica("replica-0", in_flight=2)
    ap, fleet, router, clock = _autopilot([a], cfg)
    _slow_samples(router, 0.0, slow="replica-0", fast="replica-0",
                  slow_ms=500.0)
    clock[0] = 1.0
    ap.tick()
    assert "health_evict" not in _actions(ap)      # no peers
    b = _CtrlReplica("replica-1", in_flight=2)
    router.add_replica(b)
    router.recent.clear()
    _slow_samples(router, 1.0, n=2)                # < evict_min_samples
    clock[0] = 2.0
    ap.tick()
    assert "health_evict" not in _actions(ap)


def test_rollout_rejects_unverified_snapshot(tmp_path):
    """A bad manifest refuses BEFORE any spawn: the serving generation
    is never touched."""
    cfg = AutopilotConfig(interval_s=0.0)
    a = _CtrlReplica("replica-0")
    ap, fleet, router, clock = _autopilot([a], cfg)
    bad = tmp_path / "nothing"
    bad.mkdir()
    assert ap.start_rollout(bad) is False
    assert fleet.spawned == []
    assert router._primary_gen == 0
    assert _actions(ap) == ["rollout_rejected", "action_backoff"]


def _good_snapshot(tmp_path):
    return save_weight_snapshot(
        tmp_path, {"w": np.ones((2, 2), np.float32)}, step=1)


def test_canary_judge_promotes_healthy_generation(tmp_path):
    cfg = AutopilotConfig(interval_s=0.0, canary_window_s=2.0,
                          canary_min_completed=3, canary_fraction=0.25,
                          canary_max_p50_ratio=3.0)
    a = _CtrlReplica("replica-0")
    b = _CtrlReplica("replica-1")
    ap, fleet, router, clock = _autopilot([a, b], cfg)
    assert ap.start_rollout(_good_snapshot(tmp_path)) is True
    canary = fleet.spawned[0]
    assert canary.generation == 1
    assert canary.ckpt is not None
    # not ready yet: no traffic shift
    ap.tick()
    assert router._canary is None
    canary.ready = True
    clock[0] = 1.0
    ap.tick()
    assert "canary_traffic" in _actions(ap)
    assert router._canary == (1, 0.25)
    assert router._primary_gen == 0    # canary slice only
    # a healthy window: canary completions, no misses, comparable TTFT
    router._completed_by[canary.name] = 6
    for i in range(6):
        router.recent.append({"t": 2.0 + 0.1 * i, "replica":
                              canary.name, "generation": 1,
                              "ttft_ms": 55.0, "missed": False})
        router.recent.append({"t": 2.0 + 0.1 * i, "replica": "replica-0",
                              "generation": 0, "ttft_ms": 50.0,
                              "missed": False})
    clock[0] = 3.1                     # window elapsed
    ap.tick()
    assert "canary_promote" in _actions(ap)
    assert ap.decisions[-1]["p50_ratio"] == pytest.approx(1.1)
    assert router._primary_gen == 1
    assert router._canary is None
    # old generation drains out; a replacement grew to the old width
    assert sorted(fleet.decommissioned) == ["replica-0", "replica-1"]
    assert len(fleet.spawned) == 2     # canary + 1 growth spawn
    fleet.done_rc["replica-0"] = EXIT_DECOMMISSION
    fleet.done_rc["replica-1"] = EXIT_DECOMMISSION
    clock[0] = 3.5
    ap.tick()
    assert _actions(ap)[-1] == "rollout_complete"
    assert {h.generation for h in router.replicas} == {1}


def test_canary_judge_rolls_back_on_slo_burn(tmp_path):
    cfg = AutopilotConfig(interval_s=0.0, canary_window_s=2.0,
                          canary_min_completed=3,
                          canary_max_miss_frac=0.25)
    a = _CtrlReplica("replica-0")
    ap, fleet, router, clock = _autopilot([a], cfg)
    ap.start_rollout(_good_snapshot(tmp_path))
    canary = fleet.spawned[0]
    canary.ready = True
    clock[0] = 1.0
    ap.tick()
    router._completed_by[canary.name] = 4
    router._missed_by[canary.name] = 2           # 50% miss rate
    clock[0] = 3.1
    ap.tick()
    assert _actions(ap)[-2] == "canary_rollback"
    assert "SLO burn" in ap.decisions[-2]["reason"]
    assert router._primary_gen == 0              # traffic restored
    assert router._canary is None
    assert fleet.decommissioned == [canary.name]
    # backoff armed: an immediate retry is refused by the guard
    assert ap.decisions[-1]["action"] == "action_backoff"


def test_canary_death_rolls_back_old_gen_untouched(tmp_path):
    """The corrupted-checkpoint shape: the canary child dies terminally
    (exit 44 from a failed weight load) before ever serving — rollback,
    with the old generation's replicas never decommissioned."""
    cfg = AutopilotConfig(interval_s=0.0)
    a = _CtrlReplica("replica-0")
    ap, fleet, router, clock = _autopilot([a], cfg)
    ap.start_rollout(_good_snapshot(tmp_path))
    canary = fleet.spawned[0]
    fleet.done_rc[canary.name] = EXIT_ANOMALY
    clock[0] = 0.5
    ap.tick()
    roll = [d for d in ap.decisions
            if d["action"] == "canary_rollback"]
    assert roll and "died (rc 44)" in roll[0]["reason"]
    assert router._primary_gen == 0
    assert [h.name for h in router.replicas] == ["replica-0"]
    assert fleet.decommissioned == []            # old gen untouched


def test_rollout_in_progress_is_exclusive(tmp_path):
    cfg = AutopilotConfig(interval_s=0.0)
    a = _CtrlReplica("replica-0")
    ap, fleet, router, clock = _autopilot([a], cfg)
    ap.start_rollout(_good_snapshot(tmp_path))
    with pytest.raises(RuntimeError, match="in progress"):
        ap.start_rollout(_good_snapshot(tmp_path))


# ---------------------------------------------------------------------------
# generation-aware traffic + attribution
# ---------------------------------------------------------------------------

def test_canary_slice_is_uniform_over_sequential_rids():
    """The hashed rid slice must hit ~fraction of ANY contiguous rid
    range — sequentially issued rids included (a plain modulo slice
    would put the whole canary share in a prefix that's already
    served)."""
    router = FleetRouter([_CtrlReplica("replica-0")], queue_depth=8)
    router.set_traffic(0, canary_generation=1, canary_fraction=0.25)
    for lo in (0, 500, 5000):
        hits = sum(
            1 for rid in range(lo, lo + 1000)
            if router._desired_gen(
                types.SimpleNamespace(rid=rid)) == 1)
        assert 180 <= hits <= 320, (lo, hits)
    router.set_traffic(0)              # canary cleared
    assert all(router._desired_gen(types.SimpleNamespace(rid=r)) == 0
               for r in range(100))


def test_generation_attribution_and_placement_preference(lm):
    """With a 100% canary slice every request prefers (and lands on)
    the new generation; each completion carries its generation and the
    per-generation ledger sums to the total."""
    model, params = lm
    old = InprocReplica(_sched(model, params, replica=0), name="old")
    new = InprocReplica(_sched(model, params, replica=1), name="new")
    router = FleetRouter([old], queue_depth=32)
    router.add_replica(new, generation=1)
    assert new.generation == 1
    router.set_traffic(0, canary_generation=1, canary_fraction=1.0)
    # <= the canary's slot budget, so generation preference is never
    # forced to spill to the feasible-but-off-generation replica
    plan = make_requests(2, 2, vocab_size=V, prompt_lens=(3, 8),
                         max_new=(4, 6), seed=13)
    rids = [router.submit(r["prompt"], r["max_new"])
            for client in plan for r in client]
    for _ in range(500):
        router.pump()
        if all(router.done(r) for r in rids):
            break
    assert all(router.done(r) for r in rids)
    per_gen = router.per_generation_completed()
    assert per_gen == {1: len(rids)}
    assert all(router.reqs[r].generation == 1 for r in rids)
    # flow-trace identity: strided replica ids recover the generation
    assert (1 * GEN_STRIDE + 2) // GEN_STRIDE == 1
    old.close()
    new.close()


def test_generation_preference_yields_to_availability(lm):
    """A request whose preferred generation is saturated still serves:
    generation ranks below feasibility/above load — never a partition
    that strands traffic."""
    model, params = lm
    only = InprocReplica(_sched(model, params, replica=0), name="only")
    router = FleetRouter([only], queue_depth=32)
    # every request desires generation 1; no gen-1 replica exists
    router.set_traffic(0, canary_generation=1, canary_fraction=1.0)
    rid = router.submit([1, 2, 3], 4)
    for _ in range(200):
        router.pump()
        if router.done(rid):
            break
    assert router.done(rid)
    assert router.reqs[rid].generation == 0      # served by gen 0
    only.close()


def test_autopilot_breakdown_matches_obs_shape(lm):
    """The judge's per-replica rows carry the obs_agg per-writer
    breakdown fields, built from the same rollup records."""
    model, params = lm
    h = InprocReplica(_sched(model, params, replica=0), name="r0")
    router = FleetRouter([h], queue_depth=8)
    fleet = _FakeFleet(router)
    ap = Autopilot(fleet, AutopilotConfig(interval_s=0.0))
    rid = router.submit([1, 2, 3], 4)
    for _ in range(200):
        router.pump()
        if router.done(rid):
            break
    rows = ap.breakdown()
    assert len(rows) == 1
    row = rows[0]
    assert row["name"] == "r0" and row["generation"] == 0
    assert row["role"] == "serve"
    assert row["ttft_ms_p50"] is not None
    assert "queue_depth" in row and "block_utilization" in row
    h.close()


# ---------------------------------------------------------------------------
# fleet fault kinds (utils/faults.py) — plan-level pins
# ---------------------------------------------------------------------------

def test_fault_plan_fleet_kinds_fire_once_and_match_proc():
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        faults as faults_lib,
    )

    plan = faults_lib.FaultPlan.from_config(
        "replica_kill@3?proc=1002&max=1,stall_drain@0")
    assert not plan.fire_if_due("replica_kill", 2, proc=1002)  # window
    assert not plan.fire_if_due("replica_kill", 3, proc=7)   # proc gate
    assert plan.fire_if_due("replica_kill", 3, proc=1002)
    assert not plan.fire_if_due("replica_kill", 3, proc=1002)  # max=1
    assert plan.fire_if_due("stall_drain", 0, proc=1002)
    # fleet kinds never leak into the in-step apply() path
    assert faults_lib.FLEET_KINDS == (
        "replica_kill", "stall_drain", "handoff_kill",
        "handoff_kill_post", "decode_kill", "handoff_stall")


# ---------------------------------------------------------------------------
# slow/chaos: subprocess fleets under the autopilot
# ---------------------------------------------------------------------------

MODEL_FLAGS = dict(vocab=V, seq=64, layers=2, d_model=32, heads=4,
                   d_ff=64, init_seed=0)
SERVE_FLAGS = dict(slots=4, num_blocks=17, block_size=16,
                   prefill_chunk=16, queue_depth=16)


def _drive(fleet, plan, *, timeout_s=300, mid=None, mid_at=3):
    """Closed-loop drive of a subprocess fleet; ``mid`` runs once after
    ``mid_at`` completions.  Returns {key: tokens}."""
    clients = len(plan)
    rids, results = {}, {}
    next_i = {ci: 0 for ci in range(clients)}
    outstanding = {ci: None for ci in range(clients)}
    fired = False
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        for ci in range(clients):
            if outstanding[ci] is not None or \
                    next_i[ci] >= len(plan[ci]):
                continue
            r = plan[ci][next_i[ci]]
            rid = fleet.submit(r["prompt"], r["max_new"])
            if rid is None:
                continue
            rids[(ci, next_i[ci])] = rid
            outstanding[ci] = rid
            next_i[ci] += 1
        for rid in fleet.pump():
            for ci in range(clients):
                if outstanding[ci] == rid:
                    outstanding[ci] = None
        n_done = sum(1 for r in rids.values() if fleet.done(r))
        if not fired and mid is not None and n_done >= mid_at:
            fired = True
            mid()
        if (len(rids) == sum(len(p) for p in plan)
                and all(fleet.done(r) for r in rids.values())):
            for key, rid in rids.items():
                results[key] = fleet.result(rid)
            return results
        time.sleep(0.005)
    raise AssertionError(
        f"fleet never drained: {len(results)}/{sum(map(len, plan))}")


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_replica_kill_fault_mid_scale_out(tmp_path):
    """A fleet-fault replica (``replica_kill@N``) SIGKILLs itself
    mid-load while an autopilot scale-out is still in flight: the
    supervisor relaunches the crashed replica (SIGKILL is a retry
    code), the scale-out completes, and every request finishes exactly
    once."""
    fleet = launch_fleet(1, model=MODEL_FLAGS, serve=SERVE_FLAGS,
                         telemetry_root=str(tmp_path),
                         backoff=0.2, backoff_cap=1.0,
                         log=lambda m: None)
    try:
        fleet.wait_ready(300)
        # a second replica that kills itself on its 3rd accepted submit
        h = fleet.add_replica(faults="replica_kill@3")
        ap = Autopilot(fleet, AutopilotConfig(
            min_replicas=2, max_replicas=2, interval_s=0.1))
        fleet.autopilot = ap
        plan = make_requests(6, 4, vocab_size=V, prompt_lens=(3, 10),
                             max_new=(4, 8), seed=21)
        results = _drive(fleet, plan)
        assert len(results) == 24
        # the relaunch may still be in its backoff window: pump it in
        t0 = time.time()
        while time.time() - t0 < 30:
            fleet.pump()
            if any(e["event"] == "relaunch" and e["child"] == h.name
                   for e in fleet.events):
                break
            time.sleep(0.05)
        evs = [(e["event"], e["child"]) for e in fleet.events]
        assert ("relaunch", h.name) in evs       # crash code retried
        assert fleet.router.requeued >= 1        # the killed in-flights
    finally:
        fleet.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_stalled_drain_escalates_and_ledger_exact(tmp_path):
    """A ``stall_drain`` replica swallows its decommission op; the
    autopilot escalates to SIGKILL after drain_timeout_s, the retired
    child stays down (no relaunch), and its in-flight work completes
    on the survivor — no request lost or duplicated."""
    fleet = launch_fleet(1, model=MODEL_FLAGS, serve=SERVE_FLAGS,
                         telemetry_root=str(tmp_path),
                         log=lambda m: None)
    try:
        fleet.wait_ready(300)
        # window spans every accepted-submit count: the drain stalls no
        # matter when the decommission op lands
        h = fleet.add_replica(faults="stall_drain@0-1000000")
        # scale-in hysteresis pinned far out: the idle wait below must
        # not let the loop decommission the stall replica on its own
        # before the scripted mid-load decommission exercises the
        # escalation path
        ap = Autopilot(fleet, AutopilotConfig(
            min_replicas=1, max_replicas=2, interval_s=0.1,
            drain_timeout_s=2.0, scale_in_hold_s=3600.0))
        fleet.autopilot = ap
        t0 = time.time()
        while time.time() - t0 < 120 and not h.accepting():
            fleet.pump()
            time.sleep(0.01)
        assert h.accepting()

        def mid():
            ap._begin_decommission(ap._now(), h.name,
                                   kind="test_scale_in")

        plan = make_requests(6, 4, vocab_size=V, prompt_lens=(3, 10),
                             max_new=(4, 8), seed=22)
        results = _drive(fleet, plan, mid=mid)
        assert len(results) == 24                # ledger-exact
        acts = [d["action"] for d in ap.decisions]
        assert "drain_stalled_kill" in acts
        drained = [d for d in ap.decisions if d["action"] == "drained"]
        assert drained and drained[0]["forced"] is True
        evs = [(e["event"], e["child"]) for e in fleet.events]
        assert ("relaunch", h.name) not in evs   # retired: terminal
    finally:
        fleet.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_corrupt_canary_checkpoint_rolls_back_e2e(tmp_path):
    """ACCEPTANCE e2e: a canary checkpoint that passes the autopilot's
    pre-spawn manifest verify but fails in the worker (payload
    corrupted, manifest re-committed) exits 44; the rollout rolls back
    automatically and the old generation serves every request,
    undisturbed."""
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    snap = save_weight_snapshot(
        tmp_path / "push", model.init(prng.init_key(0)), step=1)
    p = pathlib.Path(snap) / "weights.npz"
    raw = bytearray(p.read_bytes())
    raw[0:4] = b"XXXX"                 # np.load fails deterministically
    p.write_bytes(bytes(raw))
    ckpt_manifest.commit(pathlib.Path(snap),
                         {"step": 1, "kind": "weights"})
    assert ckpt_manifest.verify(snap) == []      # TOCTOU shape
    fleet = launch_fleet(1, model=MODEL_FLAGS, serve=SERVE_FLAGS,
                         telemetry_root=str(tmp_path),
                         log=lambda m: None)
    try:
        fleet.wait_ready(300)
        ap = Autopilot(fleet, AutopilotConfig(
            min_replicas=1, max_replicas=2, interval_s=0.1))
        fleet.autopilot = ap

        def mid():
            assert ap.start_rollout(snap) is True

        plan = make_requests(4, 4, vocab_size=V, prompt_lens=(3, 10),
                             max_new=(4, 8), seed=23)
        results = _drive(fleet, plan, mid=mid)
        assert len(results) == 16
        t0 = time.time()
        while time.time() - t0 < 60 and ap._rollout is not None:
            fleet.pump()
            time.sleep(0.01)
        roll = [d for d in ap.decisions
                if d["action"] == "canary_rollback"]
        assert roll and "rc 44" in roll[0]["reason"]
        assert fleet.router._primary_gen == 0
        assert fleet.router.per_generation_completed() == {0: 16}
        assert [h.name for h in fleet.router.replicas] == ["replica-0"]
    finally:
        fleet.close()
