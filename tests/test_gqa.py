"""Grouped-query attention (TransformerConfig.n_kv_heads — Ainslie et
al. 2023).  GQA is mathematically MHA with each K/V head tiled across a
group of query heads, so the load-bearing test is EXACT equivalence: a
GQA model must produce the same logits as the MHA twin whose fused-qkv
K/V columns are tiled group-wise.  The serving win — the KV cache
holding kv_heads instead of n_heads — is pinned on the decode path.
Under Megatron TP (round 4) the K/V heads shard over the tensor axis
(n_kv_heads % tp == 0 required, ValueError otherwise): the contiguous
head-aligned permutation keeps each rank's query-head groups on its own
K/V heads, pinned here by trajectory parity through the real seq x
tensor path and by token-exact native-TP decode (test_generate_tp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate, init_kv_cache,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig, repeat_kv, split_qkv,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

D, H, KV, HD, VOCAB, T = 32, 4, 2, 8, 64, 16


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=32, n_layers=2, d_model=D,
                n_heads=H, d_ff=64)
    base.update(kw)
    return TransformerConfig(**base)


def _tile_qkv_params(gqa_params, c_gqa):
    """Tile a GQA param tree's fused-qkv K/V columns group-wise into the
    MHA layout (d, 3d) — the exact-equivalence construction."""
    g = c_gqa.n_heads // c_gqa.kv_heads
    kvw = c_gqa.kv_heads * c_gqa.head_dim

    def tile_w(w):                      # (d_in, qkv_dim) -> (d_in, 3d)
        d_in = w.shape[0]
        qw = w[:, :c_gqa.d_model]
        kw = w[:, c_gqa.d_model:c_gqa.d_model + kvw]
        vw = w[:, c_gqa.d_model + kvw:]
        t = lambda x: jnp.repeat(
            x.reshape(d_in, c_gqa.kv_heads, c_gqa.head_dim), g,
            axis=1).reshape(d_in, c_gqa.n_heads * c_gqa.head_dim)
        return jnp.concatenate([qw, t(kw), t(vw)], axis=1)

    def tile_b(b):                      # (qkv_dim,) -> (3d,)
        qb = b[:c_gqa.d_model]
        kb = b[c_gqa.d_model:c_gqa.d_model + kvw]
        vb = b[c_gqa.d_model + kvw:]
        t = lambda x: jnp.repeat(
            x.reshape(c_gqa.kv_heads, c_gqa.head_dim), g,
            axis=0).reshape(-1)
        return jnp.concatenate([qb, t(kb), t(vb)])

    out = jax.tree_util.tree_map(lambda x: x, gqa_params)  # deep copy
    for blk in out["blocks"]:
        blk["qkv"] = {"w": tile_w(blk["qkv"]["w"]),
                      "b": tile_b(blk["qkv"]["b"])}
    return out


def test_param_shapes_and_default_unchanged():
    gqa = Transformer(_cfg(n_kv_heads=KV)).init(prng.init_key(0))
    assert gqa["blocks"][0]["qkv"]["w"].shape == (D, D + 2 * KV * HD)
    mha = Transformer(_cfg()).init(prng.init_key(0))
    # default (n_kv_heads=None) keeps the pre-GQA treedef byte-identical
    assert mha["blocks"][0]["qkv"]["w"].shape == (D, 3 * D)
    with pytest.raises(AssertionError, match="not divisible"):
        Transformer(_cfg(n_kv_heads=3)).init(prng.init_key(0))


def test_split_and_repeat_helpers():
    c = _cfg(n_kv_heads=KV)
    qkv = jnp.arange(2 * 4 * c.qkv_dim, dtype=jnp.float32).reshape(
        2, 4, c.qkv_dim)
    q, k, v = split_qkv(c, qkv)
    assert q.shape == (2, 4, H, HD)
    assert k.shape == v.shape == (2, 4, KV, HD)
    rk = repeat_kv(c, k)
    assert rk.shape == (2, 4, H, HD)
    # group layout: query heads 2g, 2g+1 share kv head g
    np.testing.assert_array_equal(np.asarray(rk[..., 0, :]),
                                  np.asarray(rk[..., 1, :]))
    np.testing.assert_array_equal(np.asarray(rk[..., 0, :]),
                                  np.asarray(k[..., 0, :]))


@pytest.mark.parametrize("attention", ["dense", "flash"])
def test_gqa_equals_tiled_mha(attention):
    """The exact-equivalence identity: GQA(params) == MHA(tiled params).
    Tiling K/V weight columns group-wise commutes with the matmul, so
    both models compute identical per-head k/v — logits match to f32
    roundoff."""
    c_gqa = _cfg(n_kv_heads=KV, attention=attention)
    model_gqa = Transformer(c_gqa)
    params = model_gqa.init(prng.init_key(0))
    model_mha = Transformer(_cfg(attention=attention))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, T)),
                      jnp.int32)
    got = model_gqa.apply(params, ids)
    want = model_mha.apply(_tile_qkv_params(params, c_gqa), ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_kv_cache_shrinks_and_decode_matches_tiled_mha():
    """init_kv_cache allocates kv_heads (the serving win: half the cache
    bytes at KV = H/2), and the grouped-einsum decode loop emits exactly
    the tokens the tiled-MHA twin does (greedy)."""
    c_gqa = _cfg(n_kv_heads=KV)
    model_gqa = Transformer(c_gqa)
    params = model_gqa.init(prng.init_key(0))
    cache = init_kv_cache(model_gqa, batch=1, max_len=8)
    assert cache[0]["k"].shape == (1, 8, KV, HD)
    mha_cache = init_kv_cache(Transformer(_cfg()), batch=1, max_len=8)
    assert mha_cache[0]["k"].shape == (1, 8, H, HD)

    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    got = generate(model_gqa, params, prompt, 8)
    want = generate(Transformer(_cfg()), _tile_qkv_params(params, c_gqa),
                    prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gqa_trains_under_dp():
    """One jitted DP train step on the GQA model: loss finite, grads
    update every param (the fused qkv's uneven split must backprop)."""
    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import (
        TrainState,
    )

    model = Transformer(_cfg(n_kv_heads=KV))
    mesh = mesh_lib.make_mesh(MeshConfig(data=2),
                              devices=jax.devices()[:2])
    opt = optim.sgd(lr=1e-2, momentum=0.0)
    state = dp.replicate_state(TrainState.create(model, opt,
                                                 prng.init_key(0)), mesh)
    step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                              "global_mean")
    rng = np.random.default_rng(0)
    batch = shd.shard_batch(mesh, {
        "x": rng.integers(0, VOCAB, (4, T)).astype(np.int32),
        "y": rng.integers(0, VOCAB, (4, T)).astype(np.int32),
        "mask": np.ones((4,), np.float32)})
    before = jax.device_get(state.params["blocks"][0]["qkv"]["w"])
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))
    after = jax.device_get(state.params["blocks"][0]["qkv"]["w"])
    assert np.abs(after - before).max() > 0  # qkv actually updated


def test_gqa_tp_validation():
    """GQA shards K/V heads over the tensor axis (round 4): legal when
    n_kv_heads % tp == 0, loud otherwise."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
    )

    megatron.validate_tp(_cfg(n_kv_heads=KV), tp=2)        # 2 % 2 == 0
    megatron.validate_tp(_cfg(), tp=2)                     # MHA fine
    with pytest.raises(ValueError, match="n_kv_heads % tp"):
        megatron.validate_tp(_cfg(n_kv_heads=1), tp=2)


def test_gqa_qkv_tp_permutation_roundtrip():
    """The GQA-aware column permutation: rank slices hold whole heads
    with per-rank widths [q: H/tp, k: KV/tp, v: KV/tp] * head_dim, it
    inverts exactly, and kv_heads=n_heads reduces to the classic
    equal-thirds layout."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel.megatron import (
        qkv_tp_permutation,
    )

    tp = 2
    perm = qkv_tp_permutation(D, H, tp, kv_heads=KV)
    qkv_dim = D + 2 * KV * HD
    assert sorted(perm.tolist()) == list(range(qkv_dim))
    per_rank = qkv_dim // tp
    # rank 0's slice: q heads 0..H/tp-1, then k/v heads 0..KV/tp-1
    r0 = perm[:per_rank].tolist()
    assert r0[:D // tp] == list(range(0, D // tp))                    # q
    assert r0[D // tp:D // tp + HD] == list(range(D, D + HD))         # k
    assert r0[D // tp + HD:] == list(range(D + KV * HD,
                                           D + KV * HD + HD))        # v
    np.testing.assert_array_equal(
        qkv_tp_permutation(D, H, tp, kv_heads=H),
        qkv_tp_permutation(D, H, tp))                      # MHA reduces


@pytest.mark.slow
def test_gqa_sp_tp_trainer_matches_dp():
    """GQA through the REAL Megatron seq x tensor path (Trainer routes
    DP x SP x TP to init_sp_tp_state + make_sp_tp_train_step: the
    GQA-aware qkv permutation, tp_block_apply's per-rank [q|k|v] split
    with kv_local heads, and the rank-local group repeat) — the full
    training trajectory must match plain DP on the identical GQA model.
    A wrong slice boundary in the TP split would diverge at step 1."""
    import dataclasses

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    def cfg(**mesh_kw):
        return TrainConfig(
            nepochs=2, batch_size=32, full_batch=False, shuffle=False,
            loss="cross_entropy", optimizer="adam", lr=1e-3,
            data=DataConfig(dataset="lm", n_samples=64, seq_len=16,
                            vocab_size=VOCAB),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=D,
                              n_heads=H, n_kv_heads=KV, d_ff=64,
                              vocab_size=VOCAB, max_seq_len=16),
            mesh=MeshConfig(**mesh_kw))

    r_dp = Trainer(cfg(data=8)).fit()
    c3 = cfg(data=2, seq=2, tensor=2)
    c3.model = dataclasses.replace(c3.model, attention="ring")
    t3 = Trainer(c3)
    assert t3.sp_tp and not t3.gspmd
    r_3d = t3.fit()
    assert np.isfinite(r_3d["final_loss"])
    assert r_3d["final_loss"] == pytest.approx(r_dp["final_loss"],
                                               rel=2e-4)


def test_gqa_composes_with_int8_quant():
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    model = Transformer(_cfg(n_kv_heads=KV))
    params = model.init(prng.init_key(0))
    q = quantize_params(params)
    assert q["blocks"][0]["qkv"]["w"].dtype == jnp.int8
    ids = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, T)),
                      jnp.int32)
    full = model.apply(params, ids)
    quant = model.apply(q, ids)
    assert np.asarray(jnp.abs(quant - full)).max() < 0.15
    out = generate(model, q, jnp.asarray([[1, 2, 3]], jnp.int32), 4)
    assert out.shape == (1, 7)


def test_cli_n_kv_heads_flag():
    """--n_kv_heads reaches TransformerConfig via ModelConfig/registry."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        build_argparser, config_from_args,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.registry import (
        build_model,
    )

    args = build_argparser().parse_args(
        ["--dataset", "lm", "--n_heads", "4", "--n_kv_heads", "2"])
    model = build_model(config_from_args(args).model)
    assert model.cfg.kv_heads == 2
    args0 = build_argparser().parse_args(["--dataset", "lm"])
    assert build_model(config_from_args(args0).model).cfg.kv_heads == 4
