"""LR schedules, global-norm clipping, gradient accumulation.

Framework extensions beyond the reference's constant ``--lr``
(dataParallelTraining_NN_MPI.py:245, :91); accumulation must be bit-exact
against the unsplit step because losses are (sum, count) pairs and sums are
associative (ops.losses module docstring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.data.datasets import (
    regression_dataset,
)
from neural_networks_parallel_training_with_mpi_tpu.models.mlp import MLP
from neural_networks_parallel_training_with_mpi_tpu.ops import optim, schedules
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    data_parallel as dp,
)
from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
from neural_networks_parallel_training_with_mpi_tpu.train.trainer import Trainer
from neural_networks_parallel_training_with_mpi_tpu.utils import prng


# ---- schedules ---------------------------------------------------------


def test_constant_schedule():
    s = schedules.make("constant", 0.1)
    assert float(s(jnp.asarray(0))) == pytest.approx(0.1)
    assert float(s(jnp.asarray(10_000))) == pytest.approx(0.1)


def test_cosine_schedule_endpoints_and_warmup():
    s = schedules.make("cosine", 1.0, total_steps=100, warmup_steps=10,
                       min_lr=0.1)
    # warmup: linear from lr/warmup to lr
    assert float(s(jnp.asarray(0))) == pytest.approx(0.1, abs=1e-6)
    assert float(s(jnp.asarray(9))) == pytest.approx(1.0, abs=1e-6)
    # midpoint of decay: (lr+min)/2
    assert float(s(jnp.asarray(55))) == pytest.approx(0.55, abs=1e-6)
    # end and beyond: min_lr
    assert float(s(jnp.asarray(100))) == pytest.approx(0.1, abs=1e-6)
    assert float(s(jnp.asarray(500))) == pytest.approx(0.1, abs=1e-6)


def test_linear_schedule_decay():
    s = schedules.make("linear", 1.0, total_steps=10, warmup_steps=0)
    assert float(s(jnp.asarray(0))) == pytest.approx(1.0)
    assert float(s(jnp.asarray(5))) == pytest.approx(0.5)
    assert float(s(jnp.asarray(10))) == pytest.approx(0.0, abs=1e-7)


def test_scheduled_sgd_uses_per_step_lr():
    """Two steps of schedule-driven SGD (no momentum) == manual updates with
    the schedule's lr at counts 0 and 1."""
    sched = schedules.make("linear", 1.0, total_steps=4)  # lr: 1.0, 0.75, ...
    opt = optim.sgd(sched)
    p = {"w": jnp.asarray([2.0])}
    g = {"w": jnp.asarray([1.0])}
    st = opt.init(p)
    p1, st = opt.update(g, st, p)
    p2, _ = opt.update(g, st, p1)
    assert float(p1["w"][0]) == pytest.approx(2.0 - 1.0)
    assert float(p2["w"][0]) == pytest.approx(2.0 - 1.0 - 0.75)


# ---- clipping ----------------------------------------------------------


def test_clip_by_global_norm():
    g = {"a": jnp.asarray([3.0, 0.0]), "b": jnp.asarray([4.0])}  # norm 5
    clipped = optim.clip_by_global_norm(g, 1.0)
    assert float(optim.global_norm(clipped)) == pytest.approx(1.0, rel=1e-5)
    # direction preserved
    assert float(clipped["a"][0]) == pytest.approx(0.6, rel=1e-5)
    # under the cap: untouched
    same = optim.clip_by_global_norm(g, 10.0)
    assert float(same["b"][0]) == pytest.approx(4.0)


def test_clipped_optimizer_bounds_update():
    opt = optim.with_clipping(optim.sgd(1.0), max_norm=1.0)
    p = {"w": jnp.asarray([0.0])}
    st = opt.init(p)
    p1, _ = opt.update({"w": jnp.asarray([100.0])}, st, p)
    assert float(p1["w"][0]) == pytest.approx(-1.0, rel=1e-5)


# ---- gradient accumulation --------------------------------------------


def _toy_state_and_batch(mesh, rows=16):
    model = MLP(in_features=2, hidden=(3,), out_features=1)
    opt = optim.sgd(lr=0.05, momentum=0.9)
    state = TrainState.create(model, opt, prng.init_key(0))
    state = dp.replicate_state(state, mesh)
    rng = np.random.default_rng(0)
    batch = {
        "x": jnp.asarray(rng.normal(size=(rows, 2)), jnp.float32),
        "y": jnp.asarray(rng.normal(size=(rows, 1)), jnp.float32),
        "mask": jnp.ones((rows,), jnp.float32),
    }
    return model, opt, state, batch


def test_accumulation_matches_unsplit_step(mesh8):
    model, opt, state, batch = _toy_state_and_batch(mesh8, rows=32)
    step1 = dp.make_train_step(model, opt, mesh8, loss_name="mse",
                               donate=False, accum_steps=1)
    step2 = dp.make_train_step(model, opt, mesh8, loss_name="mse",
                               donate=False, accum_steps=2)
    s1, l1 = step1(state, batch)
    s2, l2 = step2(state, batch)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_accumulation_rejects_indivisible_rows(mesh8):
    model, opt, state, batch = _toy_state_and_batch(mesh8, rows=24)
    # 24 rows / 8 devices = 3 rows/device, not divisible by 2
    step = dp.make_train_step(model, opt, mesh8, loss_name="mse",
                              donate=False, accum_steps=2)
    with pytest.raises(ValueError, match="not divisible"):
        step(state, batch)


# ---- Trainer integration ----------------------------------------------


def test_trainer_with_schedule_clip_accum(tmp_path):
    cfg = TrainConfig(
        lr=0.01, nepochs=2, batch_size=16, full_batch=False,
        lr_schedule="cosine", warmup_steps=2, grad_clip=1.0, accum_steps=2,
        data=DataConfig(dataset="regression", n_samples=64),
        mesh=MeshConfig(data=8),
        metrics_jsonl=str(tmp_path / "m.jsonl"),
    )
    t = Trainer(cfg)
    result = t.fit()
    assert np.isfinite(result["final_loss"])
    # schedule count advanced one per optimizer step
    count = int(jax.device_get(t.state.opt_state.count))
    assert count == result["steps"]


def test_trainer_accum_on_gspmd_path_trains():
    """Round 2 lifted the round-1 guard: accumulation is wired on the GSPMD
    path (trajectory parity vs unaccumulated is pinned in
    tests/test_composition.py::TestAccumulation)."""
    cfg = TrainConfig(
        nepochs=1, accum_steps=2, full_batch=False, batch_size=32,
        data=DataConfig(dataset="regression", n_samples=64),
        mesh=MeshConfig(data=4, fsdp=2),
    )
    r = Trainer(cfg).fit()
    assert np.isfinite(r["final_loss"])


def test_label_smoothing_loss_math():
    """CE@s against the smoothed target: s=0 reduces to plain CE; s>0 on a
    confident logit is strictly larger (uniform mass penalizes peaking)."""
    from neural_networks_parallel_training_with_mpi_tpu.ops import losses

    logits = jnp.asarray([[4.0, 0.0, 0.0, 0.0]])
    labels = jnp.asarray([0])
    plain = losses.get("cross_entropy")
    smooth = losses.get("cross_entropy@0.2")
    s0, c0 = plain(logits, labels)
    s1, c1 = smooth(logits, labels)
    assert float(c0) == float(c1) == 1.0
    assert float(s1) > float(s0)
    # closed form: logz - (1-s)*gold - s*mean(logits)
    import numpy as np
    logz = np.log(np.exp(4.0) + 3.0)
    want = logz - 0.8 * 4.0 - 0.2 * 1.0
    assert float(s1) == pytest.approx(want, rel=1e-6)


def test_label_smoothing_trains_and_eval_unsmoothed():
    cfg = TrainConfig(
        nepochs=2, batch_size=32, full_batch=False, optimizer="adam",
        lr=1e-3, loss="cross_entropy", label_smoothing=0.1,
        data=DataConfig(dataset="digits", val_fraction=0.2),
        model=ModelConfig(arch="mlp", in_features=64, hidden=(32,),
                          out_features=10),
        mesh=MeshConfig(data=8), eval_every=2,
    )
    r = Trainer(cfg).fit()
    assert np.isfinite(r["final_loss"])
    assert np.isfinite(r["val_loss"])  # eval path: plain CE


def test_label_smoothing_rejects_mse():
    cfg = TrainConfig(nepochs=1, label_smoothing=0.1,
                      data=DataConfig(dataset="regression", n_samples=16),
                      mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="label_smoothing"):
        Trainer(cfg)


def test_label_smoothing_rejects_out_of_range():
    for bad in (-0.1, 1.0, 1.5):
        cfg = TrainConfig(nepochs=1, loss="cross_entropy",
                          label_smoothing=bad,
                          data=DataConfig(dataset="digits"),
                          model=ModelConfig(arch="mlp", in_features=64,
                                            hidden=(32,), out_features=10),
                          mesh=MeshConfig(data=8))
        with pytest.raises(ValueError, match="label_smoothing"):
            Trainer(cfg)
