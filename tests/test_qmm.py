"""Quantized-matmul seam (ops.qmm, DESIGN.md §14): qdot fwd/bwd numerics
for int8 and fp8, the fp8 delayed-scaling state machine (init, roll,
non-finite guard, uncalibrated fallback), training wiring across the DP
layouts (qstate riding TrainState through the jitted step, replicas
identical), the bf16 no-op pin, the compile-ledger calibration pin,
checkpoint/elastic round-trips, and the serving int8-compute decode's
greedy parity against the PTQ path."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.ops import optim, qmm
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    data_parallel as dp,
    mesh as mesh_lib,
    sharding as shd,
)
from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    checkpoint as ckpt_lib,
    compile_ledger as ledger_lib,
    prng,
)

pytestmark = pytest.mark.quant


# ---------------------------------------------------------------------------
# qdot numerics
# ---------------------------------------------------------------------------

def _xw(seed=0, shape=(4, 16, 32), out=24):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    w = jnp.asarray(rng.standard_normal((shape[-1], out)) * 0.1, jnp.float32)
    return x, w


@pytest.mark.parametrize("fmt", ["int8", "fp8"])
def test_qdot_forward_close(fmt):
    x, w = _xw()
    y = qmm.qdot(x, w, fmt=fmt)
    ref = x @ w
    # int8: per-row/per-channel symmetric scales bound the relative error
    # tightly; fp8 e4m3 carries a 3-bit mantissa — looser but bounded
    tol = 0.03 if fmt == "int8" else 0.15
    assert float(jnp.max(jnp.abs(y - ref))) < tol
    assert y.dtype == jnp.float32


@pytest.mark.parametrize("fmt", ["int8", "fp8"])
def test_qdot_grads_close(fmt):
    """The custom_vjp backward (quantized transposed contractions) tracks
    the exact gradient in direction and magnitude."""
    x, w = _xw(1)

    def f(x, w):
        return jnp.sum(qmm.qdot(x, w, fmt=fmt) ** 2)

    def fr(x, w):
        return jnp.sum((x @ w) ** 2)

    gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(fr, argnums=(0, 1))(x, w)
    for g, r in ((gx, rx), (gw, rw)):
        rel = float(jnp.linalg.norm(g - r) / jnp.linalg.norm(r))
        assert rel < 0.08, (fmt, rel)
        # bf16-storage callers get their dtype back through the cast vjp
    gxb = jax.grad(lambda x, w: jnp.sum(qmm.qdot(x, w, fmt=fmt)),
                   argnums=0)(x.astype(jnp.bfloat16),
                              w.astype(jnp.bfloat16))
    assert gxb.dtype == jnp.bfloat16


def test_qdot_rejects_bf16_and_unknown():
    x, w = _xw(2, shape=(2, 8), out=4)
    with pytest.raises(ValueError, match="plain"):
        qmm.qdot(x, w, fmt="bf16")
    with pytest.raises(ValueError, match="unknown"):
        qmm.qdot(x, w, fmt="int4")


def test_int8_serve_dot_vs_dequant():
    """The serving dot (dynamic per-token activation scales x PTQ
    weights) stays within the activation-rounding bound of the
    dequant-then-f32 reference."""
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        dequantize_array, quantize_array,
    )

    x, w = _xw(3)
    wq, ws = quantize_array(w)
    ref = x @ dequantize_array(wq, ws)
    got = qmm.int8_serve_dot(x, wq, ws)
    assert float(jnp.max(jnp.abs(got - ref))) < 0.03


# ---------------------------------------------------------------------------
# delayed-scaling state machine
# ---------------------------------------------------------------------------

def _tiny(fmt="fp8", **kw):
    return Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=32, n_layers=2, d_model=32, n_heads=4,
        d_ff=64, matmul_dtype=fmt, **kw))


def test_qstate_init_and_roles():
    m = _tiny()
    qs = qmm.init_qstate(m)
    assert set(qs["amax"]) == {"qkv", "attn_out", "ff_in", "ff_out", "head"}
    for h in qs["amax"].values():
        assert h.shape == (qmm.HISTORY,) and float(h.sum()) == 0.0
    assert qmm.init_qstate(_tiny("bf16")) == ()
    assert qmm.init_qstate(_tiny("int8")) == ()
    # swiglu adds the gate projection's role
    assert "ff_gate" in qmm.init_qstate(
        _tiny(activation="swiglu"))["amax"]


def test_qstate_update_rolls_and_guards_nonfinite():
    m = _tiny()
    qs = qmm.init_qstate(m, history=4)
    obs = {r: jnp.asarray(float(i + 1))
           for i, r in enumerate(sorted(qs["amax"]))}
    qs = qmm.update_qstate(qs, obs)
    first = sorted(qs["amax"])[0]
    np.testing.assert_allclose(np.asarray(qs["amax"][first]),
                               [1.0, 0.0, 0.0, 0.0])
    assert float(qmm.delayed_amax(qs)[first]) == 1.0
    # a non-finite observation re-records the current delayed amax
    bad = {r: jnp.asarray(np.inf) for r in qs["amax"]}
    qs2 = qmm.update_qstate(qs, bad)
    assert np.isfinite(np.asarray(qs2["amax"][first])).all()
    np.testing.assert_allclose(np.asarray(qs2["amax"][first]),
                               [1.0, 1.0, 0.0, 0.0])


def test_uncalibrated_fp8_scale_is_safe():
    """amax <= 0 (fresh history) must mean scale 1.0, not a huge scale
    that saturates everything to the format max."""
    x = jnp.asarray([[300.0, -2.0]], jnp.float32)  # within e4m3 range
    w = jnp.eye(2, dtype=jnp.float32)
    y = qmm.qdot(x, w, fmt="fp8", scales=jnp.asarray(0.0))
    # scale 1: 300 is representable in e4m3 (no clip to 448 * tiny)
    assert abs(float(y[0, 0]) - 300.0) < 20.0
    assert abs(float(y[0, 1]) + 2.0) < 0.2


# ---------------------------------------------------------------------------
# training wiring (DP mesh)
# ---------------------------------------------------------------------------

def _mesh(n=4):
    return mesh_lib.make_mesh(MeshConfig(data=n), devices=jax.devices()[:n])


def _lm_batch(mesh, rows=8, seq=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return shd.shard_batch(mesh, {
        "x": rng.integers(0, vocab, (rows, seq)).astype(np.int32),
        "y": rng.integers(0, vocab, (rows, seq)).astype(np.int32),
        "mask": np.ones((rows,), np.float32)})


def test_bf16_default_is_exact_noop():
    """The seam must be invisible when not engaged: default-config state
    carries zero extra leaves, and the default model trains bitwise
    identically to an explicit matmul_dtype='bf16' one."""
    mesh = _mesh()
    batch = _lm_batch(mesh)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    params = {}
    for fmt_kw in ({}, {"matmul_dtype": "bf16"}):
        m = Transformer(TransformerConfig(
            vocab_size=64, max_seq_len=32, n_layers=2, d_model=32,
            n_heads=4, d_ff=64, **fmt_kw))
        state = dp.replicate_state(
            TrainState.create(m, opt, prng.init_key(0)), mesh)
        assert state.qstate == ()
        assert len(jax.tree_util.tree_leaves(state.qstate)) == 0
        step = dp.make_train_step(m, opt, mesh, "cross_entropy",
                                  donate=False)
        for _ in range(2):
            state, _ = step(state, batch)
        params[bool(fmt_kw)] = jax.device_get(state.params)
    for a, b in zip(jax.tree_util.tree_leaves(params[False]),
                    jax.tree_util.tree_leaves(params[True])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("fmt", ["int8", "fp8"])
def test_quant_train_tracks_bf16_loss(fmt):
    """Loss-curve parity envelope at tiny scale: the quantized arm's loss
    stays within a documented band of the bf16 arm's over a short run
    (the bench pins the same at CPU-bench scale)."""
    mesh = _mesh()
    batch = _lm_batch(mesh)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    losses = {}
    for f in ("bf16", fmt):
        m = _tiny(f)
        state = dp.replicate_state(
            TrainState.create(m, opt, prng.init_key(0)), mesh)
        step = dp.make_train_step(m, opt, mesh, "cross_entropy")
        ls = []
        for _ in range(6):
            state, loss = step(state, batch)
            ls.append(float(loss))
        losses[f] = ls
        if f == "fp8":
            # the history rolled: slot 0 holds this step's (pmax'd) amax
            hist = jax.device_get(state.qstate["amax"]["qkv"])
            assert hist[0] > 0.0
    deltas = [abs(a - b) for a, b in zip(losses["bf16"], losses[fmt])]
    assert all(np.isfinite(losses[fmt]))
    assert max(deltas) < 0.05, (losses, deltas)
    # both arms actually train
    assert losses[fmt][-1] < losses[fmt][0]


def test_fp8_qstate_replicated_and_sharded_update():
    """fp8 composes with update_sharding='sharded' (+ bf16 master
    weights): the calibration leaves stay replicated and identical on
    every device while the opt state is scattered."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        update_sharding as us,
    )

    mesh = _mesh()
    batch = _lm_batch(mesh)
    m = _tiny("fp8")
    opt = optim.with_master_weights(optim.sgd(lr=1e-2, momentum=0.9))
    params = m.init(prng.init_key(0))
    plan = us.plan_updates(params, 4)
    host = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=us.init_opt_state(opt, params, plan),
                      qstate=qmm.init_qstate(m))
    state = us.place_state(host, mesh, opt, plan)
    step = dp.make_train_step(m, opt, mesh, "cross_entropy",
                              update_sharding="sharded", update_plan=plan)
    for _ in range(2):
        state, loss = step(state, batch)
    assert np.isfinite(float(loss))
    hist = state.qstate["amax"]["ff_in"]
    assert hist.sharding.is_fully_replicated
    shards = [np.asarray(s.data) for s in hist.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    assert float(np.asarray(shards[0])[0]) > 0.0


def test_ledger_calibration_flips_add_zero_events():
    """Compile-ledger pin (acceptance): each (format, layout) pair
    compiles once; flipping the calibration state values adds ZERO
    ledger events, and a format change shows up as a NEW event whose
    name carries matmul_dtype."""
    mesh = _mesh(2)
    batch = _lm_batch(mesh)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    led = ledger_lib.Ledger(None)
    ledger_lib.install(led)
    try:
        for fmt in ("fp8", "bf16"):
            m = _tiny(fmt)
            state = dp.replicate_state(
                TrainState.create(m, opt, prng.init_key(0)), mesh)
            tag = "dp" + (f"+matmul_dtype={fmt}" if fmt != "bf16" else "")
            step = ledger_lib.instrument(
                dp.make_train_step(m, opt, mesh, "cross_entropy",
                                   donate=False),
                f"train_step[{tag}]")
            for _ in range(3):  # amax history values change every step
                state, _ = step(state, batch)
            assert len(led.events_for(f"train_step[{tag}]")) == 1
    finally:
        ledger_lib.install(None)
    names = [e["name"] for e in led.events]
    assert names == ["train_step[dp+matmul_dtype=fp8]", "train_step[dp]"]


# ---------------------------------------------------------------------------
# checkpoint / elastic round-trips
# ---------------------------------------------------------------------------

def test_fp8_qstate_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Acceptance: delayed-scaling state survives checkpoint/restore —
    the resumed run's losses match the uninterrupted run's exactly (same
    program, replicated state, calibration history restored bitwise)."""
    mesh = _mesh()
    batch = _lm_batch(mesh)
    m = _tiny("fp8")
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    step = dp.make_train_step(m, opt, mesh, "cross_entropy", donate=False)
    state = dp.replicate_state(
        TrainState.create(m, opt, prng.init_key(0)), mesh)
    for _ in range(3):
        state, _ = step(state, batch)
    ckpt_lib.save(str(tmp_path), state, keep=0)
    straight = state
    straight_losses = []
    for _ in range(3):
        straight, loss = step(straight, batch)
        straight_losses.append(float(loss))
    template = dp.replicate_state(
        TrainState.create(m, opt, prng.init_key(0)), mesh)
    restored = ckpt_lib.restore(str(tmp_path), template)
    assert restored is not None
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state.qstate["amax"]["qkv"])),
        np.asarray(restored.qstate["amax"]["qkv"]))
    resumed = dp.replicate_state(restored, mesh)
    resumed_losses = []
    for _ in range(3):
        resumed, loss = step(resumed, batch)
        resumed_losses.append(float(loss))
    np.testing.assert_allclose(resumed_losses, straight_losses, rtol=0,
                               atol=0)


def test_legacy_pre_qstate_checkpoint_restores(tmp_path):
    """A snapshot written BEFORE TrainState grew the qstate field (its
    treedef has 3 children) must still restore against the new 4-field
    template — checkpoint._treedef_compatible bridges the defaulted
    leafless field.  Emulated faithfully: a shadow 3-field NamedTuple
    whose __module__/__qualname__ point at the real TrainState pickles
    (and unpickles) exactly like a pre-round-13 treedef."""
    from typing import Any, NamedTuple

    class LegacyTrainState(NamedTuple):
        step: Any
        params: Any
        opt_state: Any

    LegacyTrainState.__module__ = TrainState.__module__
    LegacyTrainState.__qualname__ = TrainState.__qualname__
    LegacyTrainState.__name__ = TrainState.__name__

    m = _tiny("bf16")
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    real = TrainState.create(m, opt, prng.init_key(0))
    legacy = LegacyTrainState(real.step, real.params, real.opt_state)
    # pickle stores classes by module+qualname and verifies the lookup:
    # park the shadow at the real location for the save, so the written
    # treedef.pkl carries exactly the reference a pre-round-13 build
    # wrote — and resolves to the REAL 4-field class on restore
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        state as state_mod,
    )

    state_mod.TrainState = LegacyTrainState
    try:
        ckpt_lib.save(str(tmp_path), legacy, keep=0)
    finally:
        state_mod.TrainState = TrainState
    restored = ckpt_lib.restore(str(tmp_path), real)
    assert restored is not None
    assert isinstance(restored, TrainState) and restored.qstate == ()
    for a, b in zip(jax.tree_util.tree_leaves(real.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a GENUINE structure mismatch still refuses: wrong optimizer
    bad_template = TrainState.create(m, optim.adam(lr=1e-3),
                                     prng.init_key(0))
    with pytest.raises(ValueError, match="structure mismatch"):
        ckpt_lib.restore(str(tmp_path), bad_template)


def test_fp8_qstate_elastic_reshard(tmp_path):
    """Acceptance: the calibration leaves ride the elastic N->M reshard
    (replicated scalar-ish vectors — world-shape-independent), next to
    opt state that does get re-padded."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        update_sharding as us,
    )

    m = _tiny("fp8")
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    mesh4 = _mesh(4)
    batch = _lm_batch(mesh4)
    params = m.init(prng.init_key(0))
    plan = us.plan_updates(params, 4)
    host = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=us.init_opt_state(opt, params, plan),
                      qstate=qmm.init_qstate(m))
    state = us.place_state(host, mesh4, opt, plan)
    step = dp.make_train_step(m, opt, mesh4, "cross_entropy",
                              update_sharding="sharded", update_plan=plan)
    for _ in range(2):
        state, _ = step(state, batch)
    ckpt_lib.save(str(tmp_path), state, keep=0)
    saved_hist = np.asarray(jax.device_get(state.qstate["amax"]["head"]))

    # restore onto a 2-device world: sharded opt leaves re-pad, qstate
    # restores bitwise (shape-identical)
    mesh2 = _mesh(2)
    params2 = m.init(prng.init_key(0))
    plan2 = us.plan_updates(params2, 2)
    template = TrainState(step=jnp.zeros((), jnp.int32), params=params2,
                          opt_state=us.init_opt_state(opt, params2, plan2),
                          qstate=qmm.init_qstate(m))
    restored = ckpt_lib.restore(str(tmp_path), template, elastic=True)
    assert restored is not None
    np.testing.assert_array_equal(
        np.asarray(restored.qstate["amax"]["head"]), saved_hist)
    state2 = us.place_state(restored, mesh2, opt, plan2)
    step2 = dp.make_train_step(m, opt, mesh2, "cross_entropy",
                               update_sharding="sharded",
                               update_plan=plan2)
    state2, loss = step2(state2, _lm_batch(mesh2))
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# serving: int8 compute on the decode path
# ---------------------------------------------------------------------------

def test_int8_compute_decode_greedy_matches_ptq():
    """The true int8 activation x weight decode (matmul_dtype='int8' over
    ops.quant PTQ params) pins greedy-token parity against the
    dequant-then-f32 PTQ path on the bench prompt."""
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    cfg = TransformerConfig(vocab_size=64, max_seq_len=48, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64)
    params = Transformer(cfg).init(prng.init_key(0))
    qp = quantize_params(params)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    ptq = generate(Transformer(cfg), qp, prompt, 16)
    q8 = generate(Transformer(dataclasses.replace(cfg,
                                                  matmul_dtype="int8")),
                  qp, prompt, 16)
    np.testing.assert_array_equal(np.asarray(ptq), np.asarray(q8))


@pytest.mark.slow
@pytest.mark.parametrize("kw", [{"n_kv_heads": 2}, {"scan_layers": True},
                                {"pos_encoding": "rope"}])
def test_int8_compute_decode_variants_close(kw):
    """GQA / scan / rope variants: the int8-compute decode stays within
    the stated token-agreement tolerance of the PTQ path (activation
    rounding can flip near-tie argmaxes)."""
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )

    cfg = TransformerConfig(vocab_size=64, max_seq_len=48, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64, **kw)
    params = Transformer(cfg).init(prng.init_key(1))
    qp = quantize_params(params)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    a = generate(Transformer(cfg), qp, prompt, 12, kv_quant=True)
    b = generate(Transformer(dataclasses.replace(cfg,
                                                 matmul_dtype="int8")),
                 qp, prompt, 12, kv_quant=True)
    agree = (np.asarray(a) == np.asarray(b)).mean()
    assert agree >= 0.8, (kw, np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# layout matrix + trainer validation
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("fmt", ["int8", "fp8"])
@pytest.mark.parametrize("layout", ["gspmd", "spmd", "zero1"])
def test_quant_layout_matrix(fmt, layout):
    """Per-format x per-layout wiring: GSPMD (tp x fsdp), DP x SP, and
    zero1 all run the quantized step with finite loss and (fp8) a
    rolling calibration history."""
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        gspmd, spmd,
    )

    opt = optim.sgd(lr=1e-2, momentum=0.9)
    rng = np.random.default_rng(0)
    raw = {"x": rng.integers(0, 64, (8, 16)).astype(np.int32),
           "y": rng.integers(0, 64, (8, 16)).astype(np.int32),
           "mask": np.ones((8,), np.float32)}
    if layout == "gspmd":
        m = _tiny(fmt)
        mesh = mesh_lib.make_mesh(MeshConfig(data=2, fsdp=2),
                                  devices=jax.devices()[:4])
        state = gspmd.shard_state(
            m, TrainState.create(m, opt, prng.init_key(0)), opt, mesh)
        batch = shd.shard_batch(mesh, raw)
        step = gspmd.make_gspmd_train_step(m, opt, mesh, "cross_entropy",
                                           example_batch=batch)
    elif layout == "spmd":
        m = _tiny(fmt, attention="ring")
        mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=2),
                                  devices=jax.devices()[:4])
        state = dp.replicate_state(
            TrainState.create(m, opt, prng.init_key(0)), mesh)
        batch = spmd.place_batch(mesh, raw, "seq")
        step = spmd.make_spmd_train_step(m, opt, mesh, "cross_entropy",
                                         seq_axis="seq",
                                         example_batch=batch)
    else:  # zero1
        m = _tiny(fmt)
        mesh = _mesh(4)
        params = m.init(prng.init_key(0))
        host = TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=dp.zero1_opt_state(opt, params, mesh, place=False),
            qstate=qmm.init_qstate(m))
        state = dp.place_zero1_state(host, mesh, opt)
        batch = shd.shard_batch(mesh, raw)
        step = dp.make_train_step(m, opt, mesh, "cross_entropy",
                                  update_sharding="zero1")
    for _ in range(2):
        state, loss = step(state, batch)
    assert np.isfinite(float(loss)), (fmt, layout, float(loss))
    if fmt == "fp8":
        assert float(jax.device_get(
            state.qstate["amax"]["qkv"])[0]) > 0.0


def test_matmul_skip_keeps_sites_full_precision():
    """matmul_skip (the compute analogue of ops.quant's `skip`, wired
    from --quantize_skip): a skipped role runs the plain matmul — with
    EVERY role skipped, a quantized-format model is bitwise the bf16
    model — and skipped roles carry no fp8 calibration history."""
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 12)),
                      jnp.int32)
    ref = None
    all_roles = ("qkv", "attn_out", "ff_in", "ff_out", "head")
    for fmt in ("bf16", "int8", "fp8"):
        m = _tiny(fmt, matmul_skip=all_roles if fmt != "bf16" else ())
        logits = m.apply(m.init(prng.init_key(0)), ids)
        if ref is None:
            ref = np.asarray(logits)
        else:
            np.testing.assert_array_equal(np.asarray(logits), ref)
    m = _tiny("fp8", matmul_skip=("head",))
    assert "head" not in qmm.quant_roles(m)
    assert m._mm("head") == "bf16" and m._mm("qkv") == "fp8"
    # and the partial-skip model still trains with a head-less qstate
    mesh = _mesh(2)
    opt = optim.sgd(lr=1e-2, momentum=0.9)
    state = dp.replicate_state(
        TrainState.create(m, opt, prng.init_key(0)), mesh)
    step = dp.make_train_step(m, opt, mesh, "cross_entropy")
    state, loss = step(state, _lm_batch(mesh))
    assert np.isfinite(float(loss))
    assert set(state.qstate["amax"]) == {"qkv", "attn_out", "ff_in",
                                         "ff_out"}


def test_trainer_refuses_unwired_quant_layouts():
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        DataConfig, ModelConfig, TrainConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.trainer import (
        Trainer,
    )

    def cfg(**model_kw):
        return TrainConfig(
            nepochs=1, loss="cross_entropy",
            data=DataConfig(dataset="lm", seq_len=16, n_samples=8,
                            vocab_size=64),
            model=ModelConfig(arch="transformer", n_layers=2, d_model=32,
                              n_heads=4, d_ff=64, vocab_size=64,
                              max_seq_len=16, attention="dense",
                              **model_kw),
            mesh=MeshConfig(data=-1))

    with pytest.raises(ValueError, match="moe"):
        Trainer(cfg(matmul_dtype="fp8", moe_experts=2))
    with pytest.raises(ValueError, match="ce_chunk"):
        Trainer(cfg(matmul_dtype="fp8", ce_chunk=8))
    with pytest.raises(ValueError, match="transformer"):
        Trainer(dataclasses.replace(
            cfg(), model=ModelConfig(arch="mlp", matmul_dtype="int8")))
