"""Continuous-batching scheduler (serve/scheduler.py) + loadgen + the
serving telemetry channel.

The property the fuzz test pins (the subsystem's acceptance invariant):
under random arrivals, lengths, and pool geometries, the scheduler never
leaks a block (allocator balance returns to zero after drain), never
starves an accepted request (everything submitted completes), and never
violates a stream's max_len — while every greedy result stays
token-identical to the single-stream decode (referenced through the
dense ``DecodeServer``, which tests/test_serve.py pins == generate())."""

import json
import os

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
    DecodeServer,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    Scheduler, ServeConfig, run_closed_loop,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

VOCAB = 64


def _model(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=64, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    base.update(kw)
    return Transformer(TransformerConfig(**base))


def _reference(model, params, prompt, n):
    """Single-stream greedy decode via the dense slot server (jitted
    programs lru-shared across calls; == generate() per test_serve.py)."""
    srv = DecodeServer(model, params, slots=1)
    rid = srv.submit(list(prompt), max_new_tokens=n)
    while not srv.done(rid):
        srv.step()
    return srv.result(rid)


class VClock:
    """Deterministic virtual clock: deadline policy without wall-time
    flakiness."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt=0.001):
        self.t += dt


def test_end_to_end_ragged_exact_tokens():
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=40, block_size=8, prefill_chunk=8))
    want = {}
    for prompt, n in (([1, 2, 3], 10), ([5, 9, 11, 13, 2, 2, 2, 2, 2], 9),
                      ([7], 6)):
        rid = sched.submit(prompt, n)
        want[rid] = (prompt, n)
    sched.run_until_drained()
    for rid, (prompt, n) in want.items():
        assert sched.result(rid) == _reference(model, params, prompt, n)
        st = sched.stats(rid)
        assert st.ttft_ms is not None and st.itl_ms is not None
    sched.server.allocator.assert_drained()
    assert sched.completed == 3 and sched.tokens_out == 10 + 9 + 6


def test_single_token_request_completes_at_prefill():
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=20, block_size=8))
    rid = sched.submit([4, 5, 6], 1)
    sched.run_until_drained()
    assert sched.result(rid) == _reference(model, params, [4, 5, 6], 1)


def test_chunked_prefill_interleaves_with_decode():
    """Admitting a LONG prompt must not stall an in-flight stream: the
    prompt prefills one chunk per tick while the running stream keeps
    producing a token per tick."""
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=40, block_size=8, prefill_chunk=4))
    a = sched.submit([1, 2, 3], 24)
    for _ in range(4):
        sched.tick()                         # a is decoding
    srv = sched.server
    srv_a = sched._srv_rid[a]
    pos_before = int(srv._pos_host[srv._slot_of[srv_a]])
    b = sched.submit(list(range(1, 17)), 8)   # 16-token prompt, 4 chunks
    ticks_to_first = 0
    while sched.stats(b).t_first is None:
        sched.tick()
        ticks_to_first += 1
        assert ticks_to_first < 20
    assert ticks_to_first >= 4                # prefill really was chunked
    pos_after = int(srv._pos_host[srv._slot_of[srv_a]])
    # the in-flight stream advanced ~1 token per tick throughout
    assert pos_after - pos_before >= ticks_to_first - 1
    sched.run_until_drained()
    assert sched.result(a) == _reference(model, params, [1, 2, 3], 24)
    assert sched.result(b) == _reference(model, params,
                                         list(range(1, 17)), 8)


def test_bounded_queue_rejects_overload():
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=1, num_blocks=20, block_size=8, queue_depth=2))
    rids = [sched.submit([1, 2], 4) for _ in range(5)]
    accepted = [r for r in rids if r is not None]
    assert len(accepted) == 2 and sched.rejected == 3
    sched.run_until_drained()
    for rid in accepted:
        assert len(sched.result(rid)) == 6
    sched.server.allocator.assert_drained()


def test_token_budget_gates_admission():
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=40, block_size=8, token_budget=20))
    a = sched.submit([1, 2, 3], 10)          # 13 committed tokens
    b = sched.submit([4, 5, 6], 10)          # would commit 26 > 20
    sched.tick()
    assert sched.in_flight() == 1 and sched.pending() == 1
    sched.run_until_drained()                # b admits after a retires
    assert sched.result(a) == _reference(model, params, [1, 2, 3], 10)
    assert sched.result(b) == _reference(model, params, [4, 5, 6], 10)


def test_slo_eviction_prefers_latest_deadline():
    """Pool exhaustion must evict the LATEST-deadline stream, requeue it
    at the queue front, and still complete it exactly once capacity
    frees — and the tight-SLO stream must never be the victim."""
    model = _model()
    params = model.init(prng.init_key(0))
    clock = VClock()
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=6, block_size=8, max_len=32,
        prefill_chunk=16), now_fn=clock)
    a = sched.submit([1, 2, 3, 4], 28, slo_ms=100.0)    # tight: protected
    clock.advance()
    b = sched.submit([9, 8, 7, 6], 28, slo_ms=500.0)    # loose: victim
    while sched.pending() or sched.in_flight():
        clock.advance()
        sched.tick()
    assert sched.evicted >= 1
    assert sched.stats(a).evictions == 0
    assert sched.stats(b).evictions >= 1
    assert sched.result(a) == _reference(model, params, [1, 2, 3, 4], 28)
    assert sched.result(b) == _reference(model, params, [9, 8, 7, 6], 28)
    sched.server.allocator.assert_drained()


def _fuzz_once(seed: int, model, params, random_geometry: bool,
               attn_impl: str = "gathered", prefix_cache: bool = False):
    """One fuzz round: random arrivals, prompt/output lengths, SLOs and
    (in the serve lane) pool geometry; asserts the no-leak /
    no-starvation / max_len / exact-tokens invariants after drain.  The
    core-lane round pins the geometry the parity tests already compiled,
    so it adds steps to the budgeted lane, not programs.

    With ``prefix_cache=True`` half the prompts extend one of two shared
    system prefixes (and some are exact regenerations — the full-hit +
    CoW path), so admit/decode/CoW/evict/readmit sequences run with
    blocks genuinely shared: ``assert_drained`` then pins REFCOUNTS at
    zero, token exactness pins that no stream ever read a block another
    stream wrote after its fork, and evicted+readmitted shared requests
    stay token-exact (tests/test_prefix_cache.py carries the dedicated
    counter/LRU pins)."""
    rng = np.random.default_rng(seed)
    if random_geometry:
        block_size = int(rng.choice([4, 8, 16]))
        max_len = int(rng.choice([32, 48, 64]))
    else:
        block_size, max_len = 8, 64
    slots = int(rng.integers(2, 5))
    max_blocks_per_stream = -(-max_len // block_size)
    # pool between "one stream barely fits" and "plenty": forces the
    # whole admission/eviction surface
    lo = max_blocks_per_stream + 1
    num_blocks = int(rng.integers(lo, lo + 3 * max_blocks_per_stream))
    clock = VClock()
    sched = Scheduler(model, params, ServeConfig(
        slots=slots, num_blocks=num_blocks, block_size=block_size,
        max_len=max_len, prefill_chunk=int(rng.choice([4, 8, 32])),
        queue_depth=64, attn_impl=attn_impl,
        prefix_cache=prefix_cache), now_fn=clock)
    shared_prefixes = [rng.integers(0, VOCAB, (int(ln),)).tolist()
                       for ln in (9, 14)]
    want = {}
    n_reqs = 10
    arrivals = sorted(int(t) for t in rng.integers(0, 30, n_reqs))
    submitted = 0
    tick = 0
    while submitted < n_reqs or sched.pending() or sched.in_flight():
        while submitted < n_reqs and arrivals[submitted] <= tick:
            draw = rng.random()
            if prefix_cache and draw < 0.5:
                base = shared_prefixes[int(rng.integers(0, 2))]
                sfx = rng.integers(
                    0, VOCAB, (int(rng.integers(0, 6)),)).tolist()
                prompt = base + sfx
            elif prefix_cache and draw < 0.65 and want:
                prompt = list(next(iter(want.values()))[0])  # regen
            else:
                p = int(rng.integers(1, 20))
                prompt = rng.integers(0, VOCAB, (p,)).tolist()
            p = len(prompt)
            n = int(rng.integers(1, min(max_len - p, 24) + 1))
            slo = (None if rng.random() < 0.3
                   else float(rng.integers(1, 1000)))
            rid = sched.submit(prompt, n, slo_ms=slo)
            assert rid is not None            # queue_depth 64 >> n_reqs
            want[rid] = (prompt, n)
            submitted += 1
        clock.advance()
        sched.tick()
        tick += 1
        assert tick < 5000, "starvation: not drained"
    # no leak: every block reference returned (under prefix_cache this
    # is the refcount-drain invariant — shared blocks count per reader)
    sched.server.allocator.assert_drained()
    # no starvation: every accepted request completed, with max_len and
    # length contracts intact (greedy => token-exact against the
    # single-stream reference)
    for rid, (prompt, n) in want.items():
        toks = sched.result(rid)
        assert len(toks) == len(prompt) + n
        assert len(toks) <= max_len
        assert toks == _reference(model, params, prompt, n), (
            seed, rid, prompt, n)
    return sched.evicted


def test_scheduler_fuzz_property():
    """One seeded fuzz round in the core lane (more, with random pool
    geometry, in the serve lane): random arrivals/lengths -> zero leaked
    blocks, zero starved requests, exact tokens."""
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_once(0, model, params, random_geometry=False)


@pytest.mark.serve
@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_scheduler_fuzz_property_more_seeds(seed):
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_once(seed, model, params, random_geometry=True)


def test_scheduler_fuzz_prefix_cache_property():
    """The shared-prefix fuzz in the core lane: admit/decode/CoW/evict/
    readmit sequences with prefix_cache on — refcounts drain to zero at
    quiesce, no stream ever reads a block another stream wrote after
    its CoW fork (token exactness + the server's in-step write-safety
    asserts), and evict/readmit under sharing keeps tokens exact."""
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_once(0, model, params, random_geometry=False,
               prefix_cache=True)


@pytest.mark.serve
@pytest.mark.slow
@pytest.mark.parametrize("seed", [8, 9, 10])
def test_scheduler_fuzz_prefix_cache_more_seeds(seed):
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_once(seed, model, params, random_geometry=True,
               prefix_cache=True)


@pytest.mark.serve
@pytest.mark.slow
@pytest.mark.pallas
@pytest.mark.parametrize("seed", [5, 6, 7])
def test_scheduler_fuzz_fused_kernel(seed):
    """The same no-leak / no-starvation / exact-tokens invariants with
    the Pallas paged-attention kernel active (attn_impl='fused') under
    random pool geometry — eviction, re-admission and block growth all
    hitting the kernel's table/length plumbing."""
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_once(seed, model, params, random_geometry=True,
               attn_impl="fused")


def test_attended_keys_accounting_and_records(tmp_path):
    """The serving-telemetry satellite: kind="serve" records carry
    attended/padded/kernel key counters whose values match the
    scheduler's block accounting exactly (single deterministic stream:
    closed-form sums), the final snapshot carries the ratio, and
    metrics_summary renders it."""
    model = _model()
    params = model.init(prng.init_key(0))
    tdir = str(tmp_path / "t")
    p, n, bs = 5, 6, 8
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=20, block_size=bs, max_len=64,
        telemetry_dir=tdir, metrics_every=1, attn_impl="fused"))
    rid = sched.submit(list(range(1, p + 1)), n)
    sched.run_until_drained()
    sched.result(rid)
    sched.close()
    t_cap = sched.server.t_cap
    # one stream, prefill emits token 1, then n-1 decode steps at
    # positions p .. p+n-2, each attending pos+1 keys
    want_attended = sum(range(p + 1, p + n))
    want_padded = (n - 1) * t_cap
    want_kernel = sum(-(-(k) // bs) * bs for k in range(p + 1, p + n))
    assert sched.attended_keys == want_attended
    assert sched.padded_keys == want_padded
    assert sched.kernel_keys == want_kernel
    records = [json.loads(line) for line in
               open(os.path.join(tdir, "metrics.jsonl"))]
    finals = [r for r in records if r.get("kind") == "serve"
              and r.get("final")]
    assert finals and finals[-1]["attended_keys"] == want_attended
    assert finals[-1]["padded_keys"] == want_padded
    assert finals[-1]["attended_ratio"] == round(
        want_attended / want_padded, 4)
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "metrics_summary", os.path.join(
            os.path.dirname(__file__), "..", "tools", "metrics_summary.py"))
    ms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ms)
    summary = ms.summarize(records)
    assert "attended_ratio" in summary["serving_ticks"]
    text = ms.render_text(summary, records, None, None, None)
    assert "attended keys" in text


def test_telemetry_serve_records_and_heartbeat(tmp_path):
    """Serving metrics ride the PR 2 channel: kind="serve" tick records
    + kind="serve_req" completions in metrics.jsonl, and the standard
    heartbeat.json the PR 1 supervisor's staleness monitor understands."""
    model = _model()
    params = model.init(prng.init_key(0))
    tdir = str(tmp_path / "t")
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=20, block_size=8, telemetry_dir=tdir,
        metrics_every=2))
    a = sched.submit([1, 2, 3], 8)
    b = sched.submit([4, 5], 5)
    sched.run_until_drained()
    sched.close()
    records = [json.loads(line) for line in
               open(os.path.join(tdir, "metrics.jsonl"))]
    serves = [r for r in records if r["kind"] == "serve"]
    reqs = [r for r in records if r["kind"] == "serve_req"]
    assert serves and len(reqs) == 2
    assert {r["rid"] for r in reqs} == {a, b}
    for r in reqs:
        assert r["ttft_ms"] >= 0 and r["itl_ms"] >= 0
    last = serves[-1]
    assert last["completed"] == 2 and last["tokens_out"] == 13
    assert last["block_utilization"] >= 0
    # per-role heartbeat file (fleet plane): a serving process owns
    # heartbeat-serve-p<P>.json; the legacy shared path still resolves
    # through the back-compat read
    hb = json.load(open(os.path.join(tdir, "heartbeat-serve-p0.json")))
    assert hb["final"] is True and hb["step"] == sched.tick_no
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        telemetry as telemetry_lib,
    )
    legacy = telemetry_lib.read_heartbeat(
        os.path.join(tdir, "heartbeat.json"))
    assert legacy == hb
    # the stdlib summary tool renders the serving section
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "metrics_summary", os.path.join(
            os.path.dirname(__file__), "..", "tools", "metrics_summary.py"))
    ms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ms)
    summary = ms.summarize(records)
    assert summary["serving"]["requests"] == 2
    assert summary["serving"]["ttft_ms"]["p50"] >= 0
    text = ms.render_text(summary, records, None, None, None)
    assert "serving" in text and "ttft" in text


def test_completed_history_bounded():
    """Per-request state must not grow without bound in a long-lived
    serving process: completed Requests (and never-consumed results)
    beyond ``completed_history`` are pruned; recent ones stay readable
    for stats()/result()."""
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=20, block_size=8, completed_history=3))
    rids = []
    for i in range(6):
        rid = sched.submit([1 + i, 2, 3], 2)
        rids.append(rid)
        sched.run_until_drained()
    assert len(sched.reqs) == 3                 # only the newest 3 kept
    assert sched.stats(rids[-1]).t_done is not None
    with pytest.raises(KeyError):
        sched.stats(rids[0])                    # pruned
    assert len(sched.result(rids[-1])) == 5
    sched.server.allocator.assert_drained()


def test_loadgen_closed_loop_smoke():
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=40, block_size=8))
    row = run_closed_loop(sched, clients=2, requests_per_client=2,
                          vocab_size=VOCAB, prompt_lens=(2, 6),
                          max_new=(4, 8), seed=0)
    assert row["requests"] == 4
    assert row["tokens_per_sec"] > 0
    assert row["ttft_ms_p50"] is not None and row["itl_ms_p99"] is not None
    assert row["evicted"] == 0
    sched.server.allocator.assert_drained()


@pytest.mark.serve
@pytest.mark.slow
def test_bench_serve_writes_artifact(tmp_path, monkeypatch):
    """bench.py --serve end to end at bench scale (the slow serve lane):
    the artifact carries >= 3 load points with the percentile fields and
    the capacity A/B."""
    import pathlib
    import sys
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import bench

    monkeypatch.chdir(tmp_path)
    path = bench.bench_serve(str(tmp_path / "BENCH_SERVE.json"))
    doc = json.load(open(path))
    assert len(doc["load_sweep"]) >= 3
    for row in doc["load_sweep"]:
        for k in ("tokens_per_sec", "ttft_ms_p50", "ttft_ms_p99",
                  "itl_ms_p50", "itl_ms_p99"):
            assert row[k] is not None
    cap = doc["capacity_equal_memory"]
    assert cap["paged_streams_admitted"] > cap["dense_streams_admitted"]
    assert doc["dense_host_sync_fix"]["tokens_per_sec_host_tracked"] > 0


# ---------------------------------------------------------------------------
# drain/requeue semantics (the fleet router's replica-death contract,
# pinned in ISOLATION: one scheduler, no router, no subprocesses)
# ---------------------------------------------------------------------------

def test_drain_returns_inflight_with_consumed_state():
    """drain() hands back every unfinished request in submission order
    with its consumed-token state (prefilled/generated), leaves the
    allocator fully drained, and keeps completed results readable."""
    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=17, block_size=16, prefill_chunk=8,
        queue_depth=8))
    done_rid = sched.submit([1, 2, 3], 2)       # will complete pre-drain
    mid_rid = sched.submit(list(range(1, 21)), 8)   # long prompt: will
    #                                                 be mid-prefill
    for _ in range(40):
        sched.tick()
        if sched.done(done_rid):
            break
    assert sched.done(done_rid)
    queued_rid = sched.submit([7, 8, 9], 4)
    sched.tick()
    drained = sched.drain()
    sched.server.allocator.assert_drained()
    assert sched.in_flight() == 0 and sched.pending() == 0
    by_rid = {d["rid"]: d for d in drained}
    assert set(by_rid) == {mid_rid, queued_rid}
    assert [d["rid"] for d in drained] == [mid_rid, queued_rid]  # order
    # consumed-token state: the long prompt made progress; the one
    # still queued at drain time consumed nothing
    assert 0 < by_rid[mid_rid]["prefilled"] + by_rid[mid_rid]["generated"]
    assert by_rid[queued_rid]["prefilled"] == 0
    assert by_rid[queued_rid]["generated"] == 0
    assert by_rid[mid_rid]["prompt"] == list(range(1, 21))
    # the completed request survived the drain
    assert sched.result(done_rid)[:3] == [1, 2, 3]
    sched.close()


def test_drain_readmission_reproduces_identical_tokens():
    """Re-admitting a drained request on a FRESH scheduler reproduces
    byte-identical tokens (greedy determinism — the requeue-exactness
    argument the fleet router relies on), including requests drained
    mid-decode."""
    model = _model()
    params = model.init(prng.init_key(0))
    subs = [([3, 1, 4, 1, 5], 12), (list(range(2, 14)), 14),
            ([9, 2, 6], 10)]
    refs = [_reference(model, params, p, n) for p, n in subs]
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=33, block_size=16, prefill_chunk=8,
        queue_depth=8))
    rids = [sched.submit(p, n) for p, n in subs]
    assert all(r is not None for r in rids)
    for _ in range(6):   # far enough that some streams are DECODING
        sched.tick()
    assert any(sched.server.active)   # at least one mid-decode
    drained = sched.drain()
    sched.server.allocator.assert_drained()
    assert len(drained) == len(subs)
    fresh = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=33, block_size=16, prefill_chunk=8,
        queue_depth=8))
    rid2 = {d["rid"]: fresh.submit(d["prompt"], d["max_new"],
                                   slo_ms=d["slo_ms"])
            for d in drained}
    fresh.run_until_drained()
    for old_rid, ref in zip(rids, refs):
        assert fresh.result(rid2[old_rid]) == ref
    fresh.server.allocator.assert_drained()
    sched.close()
    fresh.close()


def test_drain_with_prefix_cache_refcounts_drain():
    """drain() under prefix sharing: shared/borrowed blocks release
    through the refcount path — assert_drained (all refcounts zero)
    holds even when streams were sharing prefix blocks at drain time."""
    model = _model()
    params = model.init(prng.init_key(0))
    shared = list(range(1, 33))     # two full shared blocks
    sched = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=33, block_size=16, prefill_chunk=32,
        queue_depth=8, prefix_cache=True))
    r1 = sched.submit(shared + [40, 41], 4)
    for _ in range(4):
        sched.tick()
    r2 = sched.submit(shared + [50, 51], 4)   # prefix-matches r1's blocks
    sched.tick()
    assert not sched.done(r1) or not sched.done(r2)
    drained = sched.drain()
    sched.server.allocator.assert_drained()   # refcounts all zero
    assert {d["rid"] for d in drained} <= {r1, r2}
    sched.close()
