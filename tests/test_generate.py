"""Autoregressive decoding (models.generate): the incremental KV-cache
decode must agree exactly with the parallel training-time forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
    generate,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng


@pytest.fixture(scope="module")
def lm():
    cfg = TransformerConfig(vocab_size=32, max_seq_len=32, n_layers=2,
                            d_model=32, n_heads=4, d_ff=64)
    model = Transformer(cfg)
    params = model.init(prng.init_key(0))
    return model, params


@pytest.mark.slow  # lane budget (round 5): heaviest in module; core coverage kept by the sibling tests
def test_greedy_matches_parallel_forward(lm):
    """Each greedy token equals the argmax of the full (non-cached) forward
    at that position — the KV-cache path reproduces training math."""
    model, params = lm
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, 32, (2, 4)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
    # replay: feed out[:, :k] through the parallel forward; its last-position
    # argmax must be out[:, k]
    for k in range(4, 10):
        logits = model.apply(params, out[:, :k])
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        np.testing.assert_array_equal(np.asarray(nxt), np.asarray(out[:, k]))


def test_temperature_sampling_is_seeded(lm):
    model, params = lm
    prompt = jnp.zeros((1, 2), jnp.int32)
    a = generate(model, params, prompt, 8, temperature=1.0,
                 key=jax.random.PRNGKey(7))
    b = generate(model, params, prompt, 8, temperature=1.0,
                 key=jax.random.PRNGKey(7))
    c = generate(model, params, prompt, 8, temperature=1.0,
                 key=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_ragged_prompts_respect_lengths(lm):
    model, params = lm
    rng = np.random.default_rng(1)
    full = jnp.asarray(rng.integers(1, 32, (2, 6)), jnp.int32)
    lens = jnp.asarray([6, 3], jnp.int32)
    out = generate(model, params, full, 4, prompt_lens=lens)
    # row 0: all 6 prompt tokens preserved
    np.testing.assert_array_equal(np.asarray(out[0, :6]),
                                  np.asarray(full[0]))
    # row 1: first 3 preserved, positions 3.. generated (not forced pads)
    np.testing.assert_array_equal(np.asarray(out[1, :3]),
                                  np.asarray(full[1, :3]))


def test_generate_rejects_overflow(lm):
    model, params = lm
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, jnp.zeros((1, 30), jnp.int32), 10)


def test_generate_jits(lm):
    import functools

    model, params = lm
    jitted = jax.jit(functools.partial(generate, model, max_new_tokens=4))
    out = jitted(params, jnp.zeros((1, 3), jnp.int32))
    assert out.shape == (1, 7)


def test_zero_new_tokens_returns_prompt_unchanged():
    # regression: the prefill path used to sample one token and clamp its
    # write onto the last prompt column when max_new_tokens == 0
    import numpy as np

    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )

    model = Transformer(TransformerConfig(vocab_size=17, max_seq_len=16,
                                          n_layers=1, d_model=8, n_heads=2,
                                          d_ff=16))
    params = model.init(jax.random.PRNGKey(0))
    prompt = jnp.arange(6, dtype=jnp.int32).reshape(1, 6) % 17
    out = generate(model, params, prompt, max_new_tokens=0)
    assert out.shape == prompt.shape
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_top_k_one_equals_greedy(lm):
    """top_k=1 sampling must reproduce greedy argmax regardless of
    temperature (only one candidate survives the filter)."""
    model, params = lm
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    greedy = generate(model, params, prompt, 6)
    topk1 = generate(model, params, prompt, 6, temperature=1.5, top_k=1,
                     key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(topk1), np.asarray(greedy))


def test_top_p_keeps_most_probable_token(lm):
    """A tiny top_p must always keep the argmax candidate (the shifted
    nucleus mask guarantees a non-empty set) -> equals greedy."""
    model, params = lm
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    greedy = generate(model, params, prompt, 5)
    nucleus = generate(model, params, prompt, 5, temperature=1.0,
                       top_p=1e-6, key=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(nucleus), np.asarray(greedy))


def test_top_k_p_sampling_stays_in_vocab(lm):
    model, params = lm
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(model, params, prompt, 8, temperature=1.0, top_k=8,
                   top_p=0.9, key=jax.random.PRNGKey(0))
    toks = np.asarray(out)
    assert toks.shape == (1, 12)
    assert (toks >= 0).all() and (toks < model.cfg.vocab_size).all()


def test_generate_sharded_matches_single_device(lm):
    """DP-sharded batch decode == the plain single-placement decode,
    greedy and sampled (same key => same tokens)."""
    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate_sharded,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )

    model, params = lm
    mesh = make_mesh(MeshConfig(data=8), devices=jax.devices("cpu")[:8])
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(1, 32, (8, 4)), jnp.int32)

    want = generate(model, params, prompt, 6)
    got = generate_sharded(model, params, prompt, mesh, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    key = prng.init_key(7)
    want_s = generate(model, params, prompt, 6, temperature=0.8, top_k=8,
                      key=key)
    got_s = generate_sharded(model, params, prompt, mesh, 6,
                             temperature=0.8, top_k=8, key=key)
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))

    import pytest as _pytest

    with _pytest.raises(ValueError, match="not divisible"):
        generate_sharded(model, params, prompt[:3], mesh, 2)


@pytest.mark.slow
def test_chunked_prefill_token_exact():
    """prefill_chunk bounds prefill attention memory (O(chunk * T)
    scores instead of O(P * T)); tokens must be identical to the
    one-pass prefill for even and uneven chunk boundaries, and compose
    with kv_quant and GQA."""
    import numpy as np

    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    for kw in ({}, {"n_kv_heads": 2}):
        model = Transformer(TransformerConfig(
            vocab_size=64, max_seq_len=64, n_layers=2, d_model=32,
            n_heads=4, d_ff=64, **kw))
        params = model.init(prng.init_key(0))
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, 64, (2, 13)), jnp.int32)
        want = generate(model, params, prompt, 10)
        for chunk in (4, 5, 13, 64):   # uneven, even-ish, ==P, >P
            got = generate(model, params, prompt, 10,
                           prefill_chunk=chunk)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want), err_msg=str(
                                              (kw, chunk)))
        kv8_want = generate(model, params, prompt, 10, kv_quant=True)
        kv8_got = generate(model, params, prompt, 10, kv_quant=True,
                           prefill_chunk=4)
        np.testing.assert_array_equal(np.asarray(kv8_got),
                                      np.asarray(kv8_want))
