"""Durable control plane (serve/wal.py + router recovery, DESIGN.md
§11).

Pins, by acceptance criterion:

* **WAL durability grammar**: append/replay roundtrip across segment
  rotation (sealed segments manifest-verified), a torn tail truncated
  at the last valid record (never fatal), a mid-file checksum-corrupt
  record quarantined WITH provenance while later records still replay,
  and a corrupt sealed segment quarantined with its intact lines
  salvaged.
* **Replay exactly-once per phase**: a router relaunched on the same
  WAL dir re-admits unfinished requests in their recorded phase —
  completed ones answer from the journal (never re-executed), queued
  ones re-run, committed handoffs re-inject without repaying prefill
  or convert to a unified reprefill when the decode pool never came
  back — and every token matches the undisturbed reference.
* **Idempotency dedupe**: a resubmit carrying the same client key maps
  to the SAME rid (no second execution), in one life and across lives.
* **Allocator drain**: ``Scheduler.quiesce`` — the one call shared by
  every worker shutdown path, including the orphaned worker whose
  control plane died — evicts everything and proves the allocator
  empty.

All in-process (the core-lane shape); the subprocess versions — a
SIGKILL'd driver process, orphan drain via stdin EOF, whole-process-
group kill — live in the chaos campaign's ``stub_router_kill`` /
``fleet_ctrlplane`` scenarios and ``bench.py --ctrlplane``.
"""

import json
import os

import pytest

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    FleetRouter, InprocReplica, Scheduler, ServeConfig, make_requests,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import wal
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    ckpt_manifest, prng,
)
from neural_networks_parallel_training_with_mpi_tpu.utils.faults import (
    DRIVER_KINDS, KINDS, FaultPlan,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import goodput

pytestmark = pytest.mark.fleet

V = 64


@pytest.fixture(scope="module")
def lm():
    model = Transformer(TransformerConfig(
        vocab_size=V, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64))
    return model, model.init(prng.init_key(0))


def _sched(model, params, *, role="unified", slots=4, queue_depth=16,
           replica=None, num_blocks=None, **kw):
    return Scheduler(model, params, ServeConfig(
        slots=slots, num_blocks=num_blocks or (1 + slots * 4),
        block_size=16, prefill_chunk=16, queue_depth=queue_depth,
        replica=replica, role=role, **kw))


def _reference(model, params, jobs):
    sched = _sched(model, params, queue_depth=64, num_blocks=64)
    try:
        rids = [sched.submit(p, m) for p, m in jobs]
        assert all(r is not None for r in rids)
        sched.run_until_drained()
        return [sched.result(r) for r in rids]
    finally:
        sched.close()


def _drive(router, rids, *, max_iter=20000):
    done = set()
    for _ in range(max_iter):
        done.update(router.pump())
        if all(r in done for r in rids):
            return
    raise AssertionError(
        f"requests never drained: {sorted(set(rids) - done)} missing; "
        f"phases={[(r, router.reqs[r].phase) for r in rids]}")


def _drive_until(router, cond, *, max_iter=20000):
    for _ in range(max_iter):
        router.pump()
        if cond():
            return
    raise AssertionError("condition never met while pumping")


# ---------------------------------------------------------------------------
# WAL grammar: roundtrip, rotation, torn tail, quarantine
# ---------------------------------------------------------------------------

def test_wal_roundtrip_and_rotation(tmp_path):
    root = str(tmp_path / "wal")
    w = wal.WriteAheadLog(root, segment_records=4)
    assert w.open() == []
    for i in range(10):
        w.append("accept", rid=i, idem=f"k{i}")
    w.close()
    # 10 appends at 4/segment: two sealed segments + two active lines
    segs = [p for _, p in wal._segments(root)]
    assert len(segs) == 2
    for seg in segs:
        assert ckpt_manifest.verify(seg) == []  # committed, verifiable
    recs, report = wal.replay(root)
    assert [r["rid"] for r in recs] == list(range(10))
    assert [r["seq"] for r in recs] == list(range(10))
    assert report["records"] == 10
    assert report["quarantined_records"] == 0
    # reopen continues the seq chain past everything replayed
    w2 = wal.WriteAheadLog(root, segment_records=4)
    w2.open()
    assert w2.append("complete", rid=0)["seq"] == 10
    w2.close()


def test_wal_torn_tail_truncated_not_fatal(tmp_path):
    root = str(tmp_path / "wal")
    w = wal.WriteAheadLog(root)
    w.open()
    for i in range(3):
        w.append("accept", rid=i)
    w.close()
    active = os.path.join(root, wal.ACTIVE)
    good_size = os.path.getsize(active)
    with open(active, "a") as f:
        f.write(wal.encode_record({"seq": 3, "kind": "accept",
                                   "rid": 3})[:11])  # no newline
    # read-only replay reports but does NOT repair (live-wal safe)
    recs, report = wal.replay(root, repair=False)
    assert len(recs) == 3 and report["torn_tail_bytes"] > 0
    assert not report["torn_tail_truncated"]
    assert os.path.getsize(active) > good_size
    # open() truncates at the last valid record
    w2 = wal.WriteAheadLog(root)
    recs2 = w2.open()
    assert [r["rid"] for r in recs2] == [0, 1, 2]
    assert w2.report["torn_tail_truncated"]
    assert os.path.getsize(active) == good_size
    # and the log appends on as if the torn write never happened
    w2.append("accept", rid=3)
    w2.close()
    recs3, _ = wal.replay(root)
    assert [r["rid"] for r in recs3] == [0, 1, 2, 3]


def test_wal_midfile_corruption_quarantined(tmp_path):
    root = str(tmp_path / "wal")
    w = wal.WriteAheadLog(root)
    w.open()
    for i in range(4):
        w.append("accept", rid=i)
    w.close()
    active = os.path.join(root, wal.ACTIVE)
    with open(active) as f:
        lines = f.readlines()
    lines[1] = "0" * 16 + lines[1][16:]  # checksum no longer matches
    with open(active, "w") as f:
        f.writelines(lines)
    w2 = wal.WriteAheadLog(root)
    recs = w2.open()
    # the corrupt record is gone; the ones AFTER it still replay (a
    # mid-file bad line is bit rot, not a torn tail)
    assert [r["rid"] for r in recs] == [0, 2, 3]
    assert w2.report["quarantined_records"] == 1
    assert not w2.report["torn_tail_truncated"]
    w2.close()
    qpath = os.path.join(root, wal.QUARANTINE_FILE)
    with open(qpath) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    assert len(rows) == 1 and rows[0]["origin"] == wal.ACTIVE


def test_wal_corrupt_segment_quarantined_and_salvaged(tmp_path):
    root = str(tmp_path / "wal")
    w = wal.WriteAheadLog(root, segment_records=4)
    w.open()
    for i in range(8):
        w.append("accept", rid=i)
    w.close()
    seg0 = os.path.join(root, f"{wal.SEG_PREFIX}0")
    rec_path = os.path.join(seg0, "records.jsonl")
    with open(rec_path) as f:
        lines = f.readlines()
    lines[2] = "f" * 16 + lines[2][16:]
    with open(rec_path, "w") as f:
        f.writelines(lines)
    assert ckpt_manifest.verify(seg0) != []  # sha mismatch detected
    w2 = wal.WriteAheadLog(root, segment_records=4)
    recs = w2.open()
    assert w2.report["quarantined_segments"] == 1
    assert w2.report["quarantined_records"] == 1
    # the failed segment moved aside; its intact lines were salvaged
    assert not os.path.isdir(seg0)
    assert os.path.isdir(os.path.join(root, f"corrupt-{wal.SEG_PREFIX}0"))
    assert [r["rid"] for r in recs] == [0, 1, 3, 4, 5, 6, 7]
    w2.close()


# ---------------------------------------------------------------------------
# router replay: exactly-once per journaled phase
# ---------------------------------------------------------------------------

def _jobs(n=4):
    plan = make_requests(n, 1, vocab_size=V, prompt_lens=(4, 20),
                         max_new=(4, 10), seed=7)
    return [(r["prompt"], r["max_new"]) for reqs in plan for r in reqs]


def _disagg_pair(model, params, *, tag=""):
    pre = InprocReplica(_sched(model, params, role="prefill",
                               replica=0), name=f"pre{tag}")
    dec = InprocReplica(_sched(model, params, role="decode",
                               replica=1), name=f"dec{tag}")
    return pre, dec


def test_replay_exactly_once_across_restart(lm, tmp_path):
    model, params = lm
    jobs = _jobs(4)
    ref = _reference(model, params, jobs)
    walroot = str(tmp_path / "wal")

    # life 1: crash (stop pumping) after at least one completion, with
    # the rest accepted — a mixed-phase journal
    pre, dec = _disagg_pair(model, params, tag="-l1")
    r1 = FleetRouter([pre, dec], queue_depth=64, wal_dir=walroot)
    rids1 = [r1.submit(p, m, idem=f"k{i}")
             for i, (p, m) in enumerate(jobs)]
    assert all(r is not None for r in rids1)
    _drive_until(r1, lambda: r1.completed >= 1)
    done_life1 = r1.completed
    assert 1 <= done_life1 < len(jobs)
    r1._wal.close()  # the crash: no graceful close, records are fsynced
    pre.sched.close()
    dec.sched.close()

    # life 2: fresh replicas, same journal
    pre2, dec2 = _disagg_pair(model, params, tag="-l2")
    r2 = FleetRouter([pre2, dec2], queue_depth=64, wal_dir=walroot)
    try:
        assert r2.recovery["recovered"]
        assert r2.completed == done_life1       # restored, not re-run
        assert r2.recovery["replayed"] == len(jobs) - done_life1
        assert r2.recovery["lost"] == 0
        # clients resubmit EVERYTHING with the same idempotency keys:
        # every submit maps onto the journal-owned rid, none re-executes
        rids2 = [r2.submit(p, m, idem=f"k{i}")
                 for i, (p, m) in enumerate(jobs)]
        assert rids2 == rids1
        assert r2.recovery["deduped"] == len(jobs)
        _drive(r2, rids1)
        for rid, want in zip(rids1, ref):
            assert r2.result(rid) == want       # byte-identical tokens
        assert r2.completed == len(jobs)        # exactly once, fleetwide
        # allocator drain after recovery: nothing leaked across lives
        pre2.sched.server.allocator.assert_drained()
        dec2.sched.server.allocator.assert_drained()
        assert r2.load_report()["now"]["post_recovery"]
    finally:
        r2.close()
        pre2.sched.close()
        dec2.sched.close()


def test_replay_committed_handoff_converts_without_decode_pool(
        lm, tmp_path):
    model, params = lm
    jobs = _jobs(3)
    ref = _reference(model, params, jobs)
    walroot = str(tmp_path / "wal")

    # life 1: crash right after the first handoff commits
    pre, dec = _disagg_pair(model, params, tag="-c1")
    r1 = FleetRouter([pre, dec], queue_depth=64, wal_dir=walroot)
    rids = [r1.submit(p, m, idem=f"k{i}")
            for i, (p, m) in enumerate(jobs)]
    _drive_until(r1, lambda: r1.handoffs >= 1)
    r1._wal.close()
    pre.sched.close()
    dec.sched.close()

    # life 2: the decode pool never comes back — a prefill-only fleet.
    # The journaled handoff record cannot re-inject; the recovery
    # table's last row converts it to a unified reprefill.
    pre2 = InprocReplica(_sched(model, params, role="prefill",
                                replica=0), name="pre-c2")
    r2 = FleetRouter([pre2], queue_depth=64, wal_dir=walroot)
    try:
        assert r2.recovery["recovered"]
        rids2 = [r2.submit(p, m, idem=f"k{i}")
                 for i, (p, m) in enumerate(jobs)]
        assert rids2 == rids
        _drive(r2, rids)
        assert r2.recovery["converted"] >= 1
        assert r2.handoff_stats()["recovery"]["converted"] >= 1
        for rid, want in zip(rids, ref):
            assert r2.result(rid) == want
        pre2.sched.server.allocator.assert_drained()
    finally:
        r2.close()
        pre2.sched.close()


def test_idempotency_dedupe_same_life(lm, tmp_path):
    model, params = lm
    (prompt, max_new), = _jobs(1)
    rep = InprocReplica(_sched(model, params), name="u0")
    router = FleetRouter([rep], queue_depth=8,
                         wal_dir=str(tmp_path / "wal"))
    try:
        rid = router.submit(prompt, max_new, idem="dup-key")
        _drive(router, [rid])
        assert router.submit(prompt, max_new, idem="dup-key") == rid
        assert router.recovery["deduped"] == 1
        assert router.completed == 1            # no second execution
        # the dedupe re-announces completion so a re-attached client
        # hears about its request again
        assert rid in router.pump()
    finally:
        router.close()
        rep.sched.close()


# ---------------------------------------------------------------------------
# quiesce: the shared worker-shutdown drain
# ---------------------------------------------------------------------------

def test_scheduler_quiesce_drains_allocator(lm):
    model, params = lm
    sched = _sched(model, params)
    try:
        rid = sched.submit([1, 2, 3, 4], 6)
        assert rid is not None
        for _ in range(3):
            sched.tick()                        # mid-flight state
        descs = sched.quiesce()
        assert any(d.get("rid") == rid for d in descs)
        sched.server.allocator.assert_drained()  # quiesce proved it
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# fault kinds + goodput category
# ---------------------------------------------------------------------------

def test_driver_fault_kinds_parse_and_noop_in_apply():
    assert "router_kill" in KINDS and "fleet_kill" in KINDS
    assert DRIVER_KINDS == ("router_kill", "fleet_kill")
    plan = FaultPlan.parse("router_kill@3?max=1,fleet_kill@5?max=1")
    # apply() never fires driver kinds: the victim cannot kill itself
    batch = {"x": [1, 2]}
    assert plan.apply(3, batch) is batch
    assert plan.apply(5, batch) is batch
    # the parent's due-check is the firing path, and max=1 bounds it
    assert plan.fire_if_due("router_kill", 3)
    assert not plan.fire_if_due("router_kill", 3)
    assert not plan.fire_if_due("fleet_kill", 4)
    assert plan.fire_if_due("fleet_kill", 5)


def test_goodput_recovery_category():
    assert "recovery" in goodput.CATEGORIES
    assert goodput.categorize("recovery") == "recovery"
    # recovery outranks the steady-state categories in overlap
    # resolution: a recovery window is never mispriced as step/idle
    assert (goodput.PRIORITY.index("recovery")
            < goodput.PRIORITY.index("step"))
    spans = [{"name": "recovery", "t": 0.0, "dur": 1.0},
             {"name": "dispatch", "t": 1.0, "dur": 1.0, "step": 0}]
    cats, _ = goodput._resolve_retrain(spans)
    secs = goodput._sweep(spans, cats, 0.0, 2.0)
    assert secs["recovery"] == pytest.approx(1.0)
    assert secs["step"] == pytest.approx(1.0)
