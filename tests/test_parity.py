"""The reference's core mathematical property (SURVEY.md §4): N-way
synchronous DP with even shards is step-for-step equivalent to single-device
full-batch training — same averaged gradient => same weights.

Also covers the uneven case: ``global_mean`` reduction keeps DP ==
single-device even when the batch doesn't divide the device count (the
reference's unweighted shard-average biases there, :188-197 — our deliberate
deviation, SURVEY.md §7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.config import (
    DataConfig, MeshConfig, ModelConfig, TrainConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.data.datasets import (
    regression_dataset,
)
from neural_networks_parallel_training_with_mpi_tpu.models.mlp import reference_mlp
from neural_networks_parallel_training_with_mpi_tpu.ops import optim
from neural_networks_parallel_training_with_mpi_tpu.parallel import (
    data_parallel as dp,
)
from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
from neural_networks_parallel_training_with_mpi_tpu.utils import prng


def _train(mesh, data, nsteps, grad_reduction="global_mean", seed=0):
    model = reference_mlp()
    opt = optim.sgd(lr=1e-3, momentum=0.9)
    state = TrainState.create(model, opt, prng.init_key(seed))
    state = dp.replicate_state(state, mesh)
    step = dp.make_train_step(model, opt, mesh, "mse", grad_reduction,
                              donate=False)
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        sharding as shd,
    )

    dp_size = mesh.shape["data"] * mesh.shape["fsdp"]
    batch = {}
    for k, v in data.items():
        pv, mask = shd.pad_to_multiple(v, dp_size)
        batch[k] = pv
    batch["mask"] = mask
    batch = shd.shard_batch(mesh, batch)
    losses = []
    for _ in range(nsteps):
        state, loss = step(state, batch)
        losses.append(float(jax.device_get(loss)))
    return jax.device_get(state), losses


@pytest.mark.parametrize("grad_reduction", ["global_mean", "per_shard_mean"])
def test_dp8_equals_single_device_even_shards(mesh8, mesh1, grad_reduction):
    """16 samples / 8 devices = even shards: both reductions must match the
    single-device run (the reference's even Scatter path, :101-108)."""
    data = regression_dataset()  # the reference workload, 16x2 (:72)
    s8, l8 = _train(mesh8, data, 5, grad_reduction)
    s1, l1 = _train(mesh1, data, 5, grad_reduction)
    np.testing.assert_allclose(l8, l1, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s8.params),
                    jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_dp8_equals_single_device_uneven_global_mean(mesh8, mesh1):
    """13 samples / 8 devices: padded+masked global_mean stays exactly equal
    to single-device full-batch training (the Scatterv regime done right)."""
    data = regression_dataset(n_samples=13)
    s8, l8 = _train(mesh8, data, 5, "global_mean")
    s1, l1 = _train(mesh1, data, 5, "global_mean")
    np.testing.assert_allclose(l8, l1, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s8.params),
                    jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_loss_decreases_on_reference_workload(mesh8):
    data = regression_dataset()
    _, losses = _train(mesh8, data, 50)
    assert losses[-1] < losses[0]


def test_momentum_replicas_stay_identical(mesh8):
    """The reference's implicit correctness argument (SURVEY.md §7): momentum
    buffers evolve identically across replicas.  In SPMD the state is one
    logical pytree; verify it stays fully replicated after steps."""
    data = regression_dataset()
    state, _ = _train(mesh8, data, 3)
    # device_get of a replicated array returns the single logical value;
    # check all leaves are finite and momentum buffer is non-zero after 3 steps
    leaves = jax.tree_util.tree_leaves(state.opt_state)
    assert all(np.isfinite(l).all() for l in leaves)
    assert any(np.abs(l).sum() > 0 for l in leaves)
