"""Prefix caching + copy-on-write block sharing (serve/paged_kv.py
``prefix_cache``).

The load-bearing contracts:

* **Token identity**: greedy decode with the prefix cache ON is
  bitwise-identical to cache OFF (and to the dense single-stream
  reference) — sharing changes WHERE K/V lives, never a number.  Pinned
  across GQA / int8 KV / scan_layers / rope and on both attention
  dispatches (``gathered`` and the fused Pallas kernel).
* **Refcount hygiene**: every block reference drains to zero at quiesce
  (``assert_drained``), a double release of a shared block is a hard
  error, and a stream never writes a block it merely borrows — the
  copy-on-write fork runs before the first write past the shared
  boundary (asserted inside the server on every prefill chunk and
  decode step, so the fuzz inherits it for free).
* **No recompiles**: cache-hit admission, CoW forks, and shared-block
  (LRU) eviction are host-side block bookkeeping riding traced
  src/dst/table values — after the programs' first compiles the ledger
  stays flat (the PR 10 table-churn invariant extended).
"""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
    DecodeServer,
)
from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    BlockAllocator, PagedDecodeServer, Scheduler, ServeConfig,
    run_closed_loop,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

VOCAB = 64


def _model(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=64, n_layers=2, d_model=32,
                n_heads=4, d_ff=64)
    base.update(kw)
    return Transformer(TransformerConfig(**base))


def _dense_reference(model, params, prompt, n):
    srv = DecodeServer(model, params, slots=1)
    rid = srv.submit(list(prompt), max_new_tokens=n)
    while not srv.done(rid):
        srv.step()
    return srv.result(rid)


def _drain(srv, rid, prefill_width=16):
    while not srv.prefill_step(rid, prefill_width):
        pass
    while not srv.done(rid):
        srv.step()
    return srv.result(rid)


# ---------------------------------------------------------------------------
# allocator refcounts
# ---------------------------------------------------------------------------

def test_allocator_refcount_share_release():
    a = BlockAllocator(8)
    got = a.alloc(2)
    a.share(got[0])                      # refcount 2
    assert a.refcount(got[0]) == 2 and a.shared_extra == 1
    a.release([got[0]])                  # one reader gone, block lives
    assert a.refcount(got[0]) == 1 and a.used_blocks == 2
    a.release(got)                       # both to zero
    a.assert_drained()


def test_allocator_double_release_of_shared_block_raises():
    """The satellite hard error: once every reference is gone, another
    release (a stale caller freeing a shared block twice) must raise —
    all frees route through the one release path."""
    a = BlockAllocator(8)
    (b,) = a.alloc(1)
    a.share(b)
    a.release([b])
    a.release([b])
    with pytest.raises(ValueError):
        a.release([b])
    with pytest.raises(ValueError):
        a.free([b])                      # the legacy alias: same path
    a.assert_drained()


def test_allocator_cached_free_lru_eviction():
    """Cached-free blocks stay allocatable (counted in free_blocks) and
    are reclaimed LRU-first with the eviction callback firing."""
    evicted = []
    a = BlockAllocator(4, on_cache_evict=evicted.append)
    blocks = a.alloc(3)                  # whole pool
    for b in blocks:
        a.mark_cached(b)
    a.release([blocks[1]])               # LRU order: 2nd, 3rd, 1st
    a.release([blocks[2]])
    a.release([blocks[0]])
    assert a.free_blocks == 3 and a.cached_free_blocks == 3
    got = a.alloc(2)                     # reclaims the two oldest-parked
    assert evicted == [blocks[1], blocks[2]]
    assert got == [blocks[1], blocks[2]]
    a.reuse_cached(blocks[0])            # the survivor revives as a hit
    assert a.refcount(blocks[0]) == 1
    a.release(got + [blocks[0]])


def test_allocator_refused_alloc_evicts_nothing():
    evicted = []
    a = BlockAllocator(4, on_cache_evict=evicted.append)
    blocks = a.alloc(3)
    a.mark_cached(blocks[0])
    a.release([blocks[0]])
    assert a.alloc(4) is None            # over capacity: all-or-nothing
    assert evicted == [] and a.cached_free_blocks == 1
    a.release(blocks[1:])


# ---------------------------------------------------------------------------
# token-identity parity pins: cache on == cache off == dense reference
# ---------------------------------------------------------------------------

def _parity_roundtrip(model, params, *, attn_impl="gathered", **srv_kw):
    """Cold admit + warm (cache-hit) re-admit of a block-straddling
    prompt with the cache ON, against the same request with the cache
    OFF: all three token streams must be identical, refcounts drained,
    and the warm admission must have skipped the matched prefill."""
    prompt = list(range(1, 21))          # 20 tokens, bs 8: 2 full + 4
    n = 8
    on = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                           block_size=8, prefix_cache=True,
                           attn_impl=attn_impl, **srv_kw)
    cold = _drain(on, on.try_admit(prompt, n), prefill_width=4)
    warm_rid = on.try_admit(prompt, n)
    assert on.prefill_remaining(warm_rid) == 1      # only the last token
    assert on.prefix_hits == 1 and on.prefix_hit_tokens == 19
    warm = _drain(on, warm_rid, prefill_width=4)
    assert on.cow_forks == 1             # mid-block boundary forked
    off = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8, attn_impl=attn_impl, **srv_kw)
    base = _drain(off, off.try_admit(prompt, n), prefill_width=4)
    assert cold == warm == base
    on.allocator.assert_drained()
    off.allocator.assert_drained()
    return base


def test_prefix_cache_tokens_identical_and_skips_prefill():
    model = _model()
    params = model.init(prng.init_key(0))
    base = _parity_roundtrip(model, params)
    assert base == _dense_reference(model, params, list(range(1, 21)), 8)


def test_prefix_cache_concurrent_share_exact():
    """Two live streams sharing prefix blocks (one extending the other's
    prompt) decode concurrently; both match their single-stream
    references and the shared blocks survive the first stream's
    retirement for the second's reads."""
    model = _model()
    params = model.init(prng.init_key(0))
    prompt = list(range(1, 21))
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8, prefix_cache=True)
    a = srv.try_admit(prompt, 10)
    while not srv.prefill_step(a, 16):
        pass
    srv.step(); srv.step()
    b = srv.try_admit(prompt + [33, 34], 6)     # shares 2 full + partial
    assert srv._streams[b].prefilled == 20      # partial share included
    assert srv.allocator.shared_extra >= 1
    while not srv.prefill_step(b, 16):
        pass
    while not (srv.done(a) and srv.done(b)):
        srv.step()
    assert srv.result(a) == _dense_reference(model, params, prompt, 10)
    assert srv.result(b) == _dense_reference(model, params,
                                             prompt + [33, 34], 6)
    assert srv.cow_forks == 1
    srv.allocator.assert_drained()


def test_evict_readmit_under_sharing_exact():
    """Eviction of a stream whose blocks are shared releases only ITS
    references; re-admission re-matches the cached blocks and the
    re-run reproduces the tokens exactly."""
    model = _model()
    params = model.init(prng.init_key(0))
    prompt = [4, 5, 6, 7, 8, 9, 10, 11, 12, 13]
    srv = PagedDecodeServer(model, params, slots=4, num_blocks=40,
                            block_size=8, prefix_cache=True)
    a = srv.try_admit(prompt, 10)
    while not srv.prefill_step(a, 16):
        pass
    srv.step(); srv.step()
    b = srv.try_admit(prompt, 10)               # shares a's blocks
    p_back, n_back = srv.evict(a)               # owner evicted first
    assert (p_back, n_back) == (prompt, 10)
    tb = _drain(srv, b)                         # reader unaffected
    a2 = srv.try_admit(p_back, n_back)          # re-admit: cache hit
    assert srv.prefill_remaining(a2) == 1
    ta = _drain(srv, a2)
    assert ta == tb == _dense_reference(model, params, prompt, 10)
    srv.allocator.assert_drained()


def test_cache_pressure_evicts_lru_and_stays_exact():
    """Filling the pool with distinct prompts reclaims cached-free
    blocks LRU-first (counted), the index entries die with them, and a
    later re-admission of an evicted prefix simply re-prefills —
    tokens exact either way."""
    model = _model()
    params = model.init(prng.init_key(0))
    srv = PagedDecodeServer(model, params, slots=2, num_blocks=9,
                            block_size=8, max_len=32, prefix_cache=True)
    first = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    t0 = _drain(srv, srv.try_admit(first, 4))
    for i in range(4):                          # churn the tiny pool
        _drain(srv, srv.try_admit([20 + i] * 9, 4))
    assert srv.cache_evictions > 0
    t1 = _drain(srv, srv.try_admit(first, 4))   # prefix may be gone: cold
    assert t0 == t1
    srv.allocator.assert_drained()


# ---------------------------------------------------------------------------
# model-variant parity (full lane: each variant is a fresh compile of the
# paged programs; the fused rows run the Pallas kernel in interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("attn_impl", [
    "gathered", pytest.param("fused", marks=pytest.mark.pallas)])
@pytest.mark.parametrize("variant", ["gqa", "int8", "scan", "rope"])
def test_variant_parity_cache_on_vs_off(variant, attn_impl):
    """The satellite pin: greedy decode with prefix cache on vs off is
    bitwise-identical across GQA / int8-KV / scan_layers / rope on BOTH
    attention dispatches — cold admit, cache-hit re-admit (CoW fork
    included) and the cache-off run all emit the same tokens."""
    kw = {"gqa": dict(n_kv_heads=2), "scan": dict(scan_layers=True),
          "rope": dict(pos_encoding="rope"), "int8": {}}[variant]
    srv_kw = {"kv_quant": True} if variant == "int8" else {}
    model = _model(**kw)
    params = model.init(prng.init_key(0))
    _parity_roundtrip(model, params, attn_impl=attn_impl, **srv_kw)


# ---------------------------------------------------------------------------
# scheduler-level: burst sharing, counters, fuzzed mixes
# ---------------------------------------------------------------------------

def test_scheduler_burst_shares_and_counts(tmp_path):
    """A burst of shared-system-prompt requests admitted in ONE tick
    still hits (the first-prefill rematch), tokens stay exact, the
    drain is faster than cache-off, and the kind="serve" telemetry
    carries the prefix counters."""
    import json
    import os

    model = _model()
    params = model.init(prng.init_key(0))
    sys_prompt = list(range(1, 25))
    reqs = [(sys_prompt + [30, 31], 8), (sys_prompt + [40], 6),
            (sys_prompt + [50, 51, 52], 10)]
    tdir = str(tmp_path / "t")
    on = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=40, block_size=8, prefill_chunk=8,
        prefix_cache=True, telemetry_dir=tdir, metrics_every=1))
    want = {on.submit(p, n): (p, n) for p, n in reqs}
    on.run_until_drained()
    for rid, (p, n) in want.items():
        assert on.result(rid) == _dense_reference(model, params, p, n)
    on.close()
    snap = on._snapshot()
    assert snap["prefix_hits"] == 2             # followers of the burst
    assert snap["prefix_hit_tokens"] == 48      # 3 aligned blocks each
    assert snap["prefix_hit_rate"] > 0.5
    assert snap["blocks_saved"] == 6
    on.server.allocator.assert_drained()
    off = Scheduler(model, params, ServeConfig(
        slots=4, num_blocks=40, block_size=8, prefill_chunk=8))
    for p, n in reqs:
        off.submit(p, n)
    off.run_until_drained()
    assert on.tick_no < off.tick_no             # skipped prefill ticks
    records = [json.loads(line) for line in
               open(os.path.join(tdir, "metrics.jsonl"))]
    finals = [r for r in records if r.get("kind") == "serve"
              and r.get("final")]
    assert finals[-1]["prefix_hits"] == 2
    assert finals[-1]["cow_forks"] == 0         # aligned prefix: no fork
    assert finals[-1]["prefix_hit_rate"] == snap["prefix_hit_rate"]


def test_loadgen_shared_mix_identity_and_residency():
    """The loadgen A/B the bench rides: identical pre-generated
    shared-prefix traffic through cache-off and cache-on schedulers —
    same tokens (sha256), fewer mean blocks in use, per-class TTFT
    fields present."""
    model = _model()
    params = model.init(prng.init_key(0))
    rows = {}
    for on in (False, True):
        sched = Scheduler(model, params, ServeConfig(
            slots=4, num_blocks=40, block_size=8, prefill_chunk=8,
            prefix_cache=on))
        rows[on] = run_closed_loop(
            sched, clients=3, requests_per_client=2, vocab_size=VOCAB,
            prompt_lens=(0, 6), max_new=(4, 8), seed=0,
            shared_prefix_len=20, shared_fraction=0.7)
        sched.server.allocator.assert_drained()
    assert rows[False]["tokens_sha256"] == rows[True]["tokens_sha256"]
    assert (rows[True]["blocks_in_use_mean"]
            < rows[False]["blocks_in_use_mean"])
    assert rows[True]["prefix_cache"]["prefix_hits"] > 0
    for row in rows.values():
        assert row["shared_requests"] > 0
        assert row["ttft_ms_p50_shared"] is not None


def _fuzz_prefix_round(seed, model, params, attn_impl="gathered"):
    """Admit/decode/CoW/evict/readmit fuzz with a shared-prefix mix:
    random arrivals draw from two shared system prompts (plus unique
    prompts and exact regenerations), the pool is tight enough to force
    stream eviction AND cached-block LRU reclaim, and after the drain
    every request must match its single-stream reference with all
    refcounts at zero.  The server's internal write-safety assertions
    (no write into a borrowed block) run on every chunk and step."""
    rng = np.random.default_rng(seed)
    block_size, max_len = 8, 64
    slots = int(rng.integers(2, 5))
    mbs = -(-max_len // block_size)
    num_blocks = int(rng.integers(mbs + 1, mbs + 2 * mbs))
    from tests.test_serve_sched import VClock

    clock = VClock()
    sched = Scheduler(model, params, ServeConfig(
        slots=slots, num_blocks=num_blocks, block_size=block_size,
        max_len=max_len, prefill_chunk=int(rng.choice([4, 8])),
        queue_depth=64, prefix_cache=True, attn_impl=attn_impl),
        now_fn=clock)
    prefixes = [rng.integers(0, VOCAB, (int(ln),)).tolist()
                for ln in (11, 20)]
    want = {}
    n_reqs = 12
    arrivals = sorted(int(t) for t in rng.integers(0, 30, n_reqs))
    submitted = 0
    tick = 0
    while submitted < n_reqs or sched.pending() or sched.in_flight():
        while submitted < n_reqs and arrivals[submitted] <= tick:
            kind = rng.random()
            if kind < 0.5:               # shared prefix + random suffix
                base = prefixes[int(rng.integers(0, len(prefixes)))]
                sfx = rng.integers(
                    0, VOCAB, (int(rng.integers(0, 6)),)).tolist()
                prompt = base + sfx
            elif kind < 0.7 and want:    # exact regeneration (full hit)
                prompt = list(next(iter(want.values()))[0])
            else:                        # unique
                prompt = rng.integers(
                    0, VOCAB, (int(rng.integers(1, 16)),)).tolist()
            n = int(rng.integers(1, min(max_len - len(prompt), 12) + 1))
            slo = (None if rng.random() < 0.3
                   else float(rng.integers(1, 1000)))
            rid = sched.submit(prompt, n, slo_ms=slo)
            assert rid is not None
            want[rid] = (prompt, n)
            submitted += 1
        clock.advance()
        sched.tick()
        tick += 1
        assert tick < 5000, "starvation: not drained"
    sched.server.allocator.assert_drained()     # refcounts all zero
    for rid, (prompt, n) in want.items():
        toks = sched.result(rid)
        assert len(toks) == len(prompt) + n
        assert toks == _dense_reference(model, params, prompt, n), (
            seed, rid, prompt, n)
    return sched


def test_prefix_cache_fuzz_property():
    """One seeded shared-prefix fuzz round in the core lane (tier-1):
    refcounts drain, no stream reads another's post-fork writes (token
    exactness + the in-server write-safety asserts), evict/readmit
    under sharing keeps tokens exact."""
    model = _model()
    params = model.init(prng.init_key(0))
    sched = _fuzz_prefix_round(0, model, params)
    assert sched.server.prefix_hits > 0         # the mix actually shared


@pytest.mark.serve
@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_prefix_cache_fuzz_more_seeds(seed):
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_prefix_round(seed, model, params)


@pytest.mark.serve
@pytest.mark.slow
@pytest.mark.pallas
def test_prefix_cache_fuzz_fused():
    """The same sharing/CoW/evict fuzz with the Pallas paged-attention
    kernel active: shared tables and fork repointing flow through the
    kernel's scalar-prefetch plumbing unchanged."""
    model = _model()
    params = model.init(prng.init_key(0))
    _fuzz_prefix_round(4, model, params, attn_impl="fused")


# ---------------------------------------------------------------------------
# compile ledger: sharing/CoW/eviction churn never recompiles
# ---------------------------------------------------------------------------

def test_cache_hit_cow_and_eviction_add_no_compiles(tmp_path):
    """Extends the PR 10 table-churn invariant: once the prefill
    buckets, the decode step, and the CoW copy program have compiled,
    cache-hit admissions, further CoW forks, and shared/cached-block
    evictions add ZERO ledger events — sharing is host bookkeeping over
    traced values."""
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        trace as trace_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        compile_ledger as ledger_lib,
    )

    model = _model()
    params = model.init(prng.init_key(0))
    sched = Scheduler(model, params, ServeConfig(
        slots=2, num_blocks=10, block_size=8, max_len=32,
        prefill_chunk=8, prefix_cache=True,
        trace_dir=str(tmp_path / "trace")))
    try:
        prompt = list(range(1, 12))             # 11 tokens: partial tail
        first = sched.submit(prompt, 4)
        sched.run_until_drained()
        sched.result(first)
        # warm pass: one cache-hit admission draws the CoW program's
        # single legitimate compile
        warm = sched.submit(prompt, 4)
        sched.run_until_drained()
        sched.result(warm)
        assert sched.server.cow_forks == 1
        ledger = ledger_lib.active()
        assert len(ledger.events_for("serve_cow")) == 1
        n_events = len(ledger.events)
        # churn: more hits + forks, block growth, and enough distinct
        # prompts (each parking 2 more cached-free blocks on release)
        # to exhaust the 9-usable-block pool's plain free list and force
        # LRU reclaim of cached blocks
        for i in range(6):
            sched.submit(prompt, 3)
            sched.submit([30 + i] * 9, 3)
            sched.tick()
        sched.run_until_drained()
        assert sched.server.cow_forks >= 2      # forks kept happening
        assert sched.server.cache_evictions > 0  # LRU reclaim happened
        assert len(ledger.events) == n_events, (
            "sharing/CoW/eviction churn recompiled: "
            f"{ledger.events[n_events:]}")
        sched.server.allocator.assert_drained()
    finally:
        sched.close()
    assert trace_lib.active() is None
