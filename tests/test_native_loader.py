"""Native (C++) batch loader: build, determinism, shared permutation
across fields, remainder handling, prefetch correctness under threading,
and integration through ShardedLoader."""

import numpy as np
import pytest

from neural_networks_parallel_training_with_mpi_tpu.data import native_loader

pytestmark = pytest.mark.skipif(
    not native_loader.available(),
    reason="native loader failed to build (no g++/make?)")


def make_data(n=37, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.standard_normal((n, 3)).astype(np.float32),
        "y": rng.standard_normal((n, 1)).astype(np.float64),
        "label": np.arange(n, dtype=np.int32),
    }


def collect(batcher, epoch):
    return list(batcher.epoch(epoch))


def test_covers_all_rows_once():
    data = make_data()
    b = native_loader.NativeBatcher(data, 8, seed=1)
    batches = collect(b, 0)
    assert sum(x["x"].shape[0] for x in batches) == 37
    labels = np.concatenate([x["label"] for x in batches])
    assert sorted(labels.tolist()) == list(range(37))
    b.close()


def test_shared_permutation_across_fields():
    data = make_data()
    b = native_loader.NativeBatcher(data, 8, seed=2)
    for batch in collect(b, 0):
        for i, lbl in enumerate(batch["label"]):
            np.testing.assert_array_equal(batch["x"][i], data["x"][lbl])
            np.testing.assert_array_equal(batch["y"][i], data["y"][lbl])
    b.close()


def test_deterministic_per_seed_epoch():
    data = make_data()
    b1 = native_loader.NativeBatcher(data, 8, seed=3)
    b2 = native_loader.NativeBatcher(data, 8, seed=3)
    for a, b in zip(collect(b1, 5), collect(b2, 5)):
        np.testing.assert_array_equal(a["label"], b["label"])
    # different epoch -> different order
    e0 = np.concatenate([x["label"] for x in collect(b1, 0)])
    e1 = np.concatenate([x["label"] for x in collect(b1, 1)])
    assert not np.array_equal(e0, e1)
    b1.close()
    b2.close()


def test_drop_remainder():
    b = native_loader.NativeBatcher(make_data(), 8, seed=0,
                                    drop_remainder=True)
    batches = collect(b, 0)
    assert len(batches) == 4
    assert all(x["x"].shape[0] == 8 for x in batches)
    b.close()


def test_no_shuffle_identity_order():
    b = native_loader.NativeBatcher(make_data(), 10, seed=0, shuffle=False)
    labels = np.concatenate([x["label"] for x in collect(b, 0)])
    np.testing.assert_array_equal(labels, np.arange(37))
    b.close()


def test_start_batch_resume():
    b = native_loader.NativeBatcher(make_data(), 8, seed=4)
    full = [x["label"] for x in collect(b, 2)]
    tail = [x["label"] for x in b.epoch(2, start_batch=3)]
    assert len(tail) == len(full) - 3
    for a, c in zip(full[3:], tail):
        np.testing.assert_array_equal(a, c)
    b.close()


def test_many_epochs_stress():
    """Worker pool restart across epochs must not deadlock or leak order."""
    b = native_loader.NativeBatcher(make_data(n=64), 4, seed=5,
                                    n_threads=4, prefetch_depth=2)
    for epoch in range(10):
        labels = np.concatenate([x["label"] for x in collect(b, epoch)])
        assert sorted(labels.tolist()) == list(range(64))
    b.close()


def test_sharded_loader_native_backend():
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.data.loader import (
        ShardedLoader,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.mesh import (
        make_mesh,
    )

    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices("cpu")[:4])
    data = make_data(n=24)
    loader = ShardedLoader(mesh, data, 8, seed=0, backend="native")
    batches = list(loader.epoch(0))
    assert len(batches) == 3
    for b in batches:
        assert b["x"].shape[0] == 8  # padded/sharded jax arrays
        assert "mask" in b
        assert float(jax.device_get(b["mask"]).sum()) == 8.0
