"""The examples must actually run — the reference's one command works out
of the box (reference README.md:12) and so must ours.

Example 01 is the parity demo (the reference's exact job: 16-sample sklearn
regression, full-batch-ish SGD, 3 epochs, dataParallelTraining_NN_MPI.py:242-255);
it runs here end-to-end on the virtual 8-device CPU mesh via the CLI's
``--platform cpu --num_devices 8`` launch path.
"""

import os
import pathlib
import subprocess
import sys
import pytest

# integration-heavy: full lane only (core lane: -m 'not slow')
pytestmark = pytest.mark.slow

REPO = pathlib.Path(__file__).resolve().parent.parent


def _clean_env():
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        platform as plat,
    )

    env = dict(os.environ)
    # the scripts' own --platform cpu pin must be sufficient; give them the
    # raw (axon-registered) environment, not the conftest's pre-pinned one
    env.pop("JAX_PLATFORMS", None)
    plat.force_host_device_count(None, env=env)
    return env


def test_example_01_reference_parity_completes():
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "01_reference_parity.sh")],
        capture_output=True, text=True, timeout=120, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stderr + out.stdout


def test_cli_platform_tpu_fails_fast_when_unavailable():
    """--platform tpu must error out quickly (exit 2), never hang."""
    env = _clean_env()
    # make the probe see no accelerator even on a healthy TPU host: point
    # the subprocess at an empty platform list is not possible, so instead
    # rely on the short timeout — on a host WITH a fast accelerator the
    # probe succeeds and the run proceeds; either way, no hang.
    out = subprocess.run(
        [sys.executable, "-m", "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "tpu", "--probe_timeout", "5", "--nepochs", "1"],
        capture_output=True, text=True, timeout=180, env=env, cwd=str(REPO),
    )
    assert out.returncode in (0, 2), out.stderr[-2000:]
    if out.returncode == 2:
        assert "no accelerator" in out.stdout + out.stderr


def test_example_08_sp_tp_completes():
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "08_sp_tp_3d.sh")],
        capture_output=True, text=True, timeout=240, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stderr + out.stdout


def test_cli_generate_from_checkpoint(tmp_path):
    """Train 1 epoch -> decode from the checkpoint via --generate: the
    inference entrypoint (the reference has none; its closest artifact is
    the dead test block at dataParallelTraining_NN_MPI.py:227-236)."""
    ck = str(tmp_path / "ck")
    common = ["--dataset", "lm", "--optimizer", "adam",
              "--platform", "cpu", "--num_devices", "8",
              "--checkpoint_dir", ck]
    train = subprocess.run(
        [sys.executable, "-m", "neural_networks_parallel_training_with_mpi_tpu",
         *common, "--no-full-batch", "--batch_size", "32", "--nepochs", "1"],
        capture_output=True, text=True, timeout=240, env=_clean_env(),
        cwd=str(REPO))
    assert train.returncode == 0, train.stderr[-2000:]
    # decode WITHOUT repeating the training-time --optimizer: restore
    # goes through the stored treedef, no template needed
    gen = subprocess.run(
        [sys.executable, "-m", "neural_networks_parallel_training_with_mpi_tpu",
         "--dataset", "lm", "--platform", "cpu", "--num_devices", "8",
         "--checkpoint_dir", ck,
         "--generate", "10,20,30", "--max_new_tokens", "8",
         "--temperature", "0.8", "--top_k", "20"],
        capture_output=True, text=True, timeout=240, env=_clean_env(),
        cwd=str(REPO))
    assert gen.returncode == 0, gen.stderr[-2000:]
    assert "restored step" in gen.stdout + gen.stderr
    toks = [int(t) for t in gen.stdout.strip().splitlines()[-1].split(",")]
    assert toks[:3] == [10, 20, 30] and len(toks) == 11
    assert all(0 <= t < 256 for t in toks)


def test_example_10_expert_tensor_completes():
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "10_expert_tensor.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stderr + out.stdout


def test_example_11_real_text_lm_completes():
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "11_real_text_lm.sh")],
        capture_output=True, text=True, timeout=360, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stderr + out.stdout


def test_example_12_interleaved_pipeline_completes():
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "12_interleaved_pipeline.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stderr + out.stdout


def test_example_13_tensor_parallel_serving_completes():
    """Trains on DP x SP x TP, decodes the checkpoint natively with
    generate_tp AND through the CLI's layout-reconciling dense path."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "13_tensor_parallel_serving.sh")],
        capture_output=True, text=True, timeout=600, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "native TP decode:" in out.stdout
    # last line: the CLI decode's comma-separated continuation ids
    last = out.stdout.strip().splitlines()[-1]
    ids = [int(t) for t in last.split(",")]
    assert len(ids) == 3 + 8 and ids[:3] == [10, 20, 30]


def test_cli_generate_reconciles_sp_tp_checkpoint(tmp_path):
    """A checkpoint written by the seq x tensor layout carries the
    head-aligned qkv permutation (meta qkv_tp=2); the CLI decode must
    unpermute it — its tokens must exactly match the native generate_tp
    decode of the same checkpoint (which consumes the permuted layout
    directly)."""
    ck = str(tmp_path / "ck")
    env = _clean_env()
    train = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "8",
         "--dataset", "lm", "--seq_len", "32", "--no-full-batch",
         "--batch_size", "32", "--nepochs", "1", "--optimizer", "adam",
         "--lr", "1e-3", "--dp", "2", "--sp", "2", "--tp", "2",
         "--checkpoint_dir", ck],
        capture_output=True, text=True, timeout=420, env=env, cwd=str(REPO),
    )
    assert train.returncode == 0, train.stderr[-2000:]
    dec = subprocess.run(
        [sys.executable, "-m",
         "neural_networks_parallel_training_with_mpi_tpu",
         "--platform", "cpu", "--num_devices", "8",
         "--dataset", "lm", "--seq_len", "32",
         "--checkpoint_dir", ck, "--generate", "7,8,9",
         "--max_new_tokens", "6"],
        capture_output=True, text=True, timeout=240, env=env, cwd=str(REPO),
    )
    assert dec.returncode == 0, dec.stderr[-2000:]
    cli_ids = [int(t) for t in dec.stdout.strip().splitlines()[-1].split(",")]

    # oracle: native TP decode of the same checkpoint, in this process
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models import (
        Transformer, TransformerConfig, generate_tp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        mesh as mesh_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        checkpoint as ckpt,
    )

    restored = ckpt.restore(ck, template=None)
    model = Transformer(TransformerConfig(
        vocab_size=256, max_seq_len=512, n_layers=2, d_model=128,
        n_heads=4, d_ff=512))
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=2),
                              devices=np.asarray(jax.devices()[:4]))
    # rows must divide the data axis (2): duplicate the prompt row — each
    # batch row decodes independently, so row 0 equals the 1-row decode
    native = generate_tp(model, restored.params,
                         jnp.asarray([[7, 8, 9], [7, 8, 9]], jnp.int32),
                         mesh, max_new_tokens=6)
    assert cli_ids == [int(t) for t in np.asarray(native)[0]]


def test_example_14_four_axis_mesh_completes():
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "14_four_axis_mesh.sh")],
        capture_output=True, text=True, timeout=600, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done: final loss" in out.stderr + out.stdout


def test_example_15_int8_quantized_serving_completes():
    """Trains, checkpoints, and decodes the same checkpoint full-precision,
    with --quantize int8 (weights-only PTQ, ops.quant) AND with the true
    int8-compute dot (--matmul_dtype int8, ops.qmm) — the script prints
    the PTQ-vs-int8-compute greedy-token agreement and asserts it at the
    DESIGN §14 tolerance (exactness on a trained model is a near-tie
    lottery; the random-init exact pin lives in tests/test_qmm.py)."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "15_int8_quantized_serving.sh")],
        capture_output=True, text=True, timeout=600, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    text = out.stderr + out.stdout
    assert "int8 weights-only PTQ: param bytes" in text
    assert "int8-compute vs PTQ greedy-token agreement" in text
    # all three decodes print prompt + 8 continuation ids (the PTQ and
    # int8-compute lines are echoed from captured variables)
    id_lines = [l for l in out.stdout.splitlines()
                if l.count(",") == 10 and l.replace(",", "").isdigit()]
    assert len(id_lines) >= 3, out.stdout


def test_example_16_continuous_batching_completes():
    """Staggered requests through the slot server; each must match its
    single-stream decode exactly (asserted inside the script)."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "16_continuous_batching.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "continuous-batched tokens == single-stream generate()" \
        in out.stdout


def test_example_17_modern_lm_stack_completes():
    """RoPE x SwiGLU x GQA trained via the CLI, then decoded from the
    checkpoint with int8 weights + int8 KV cache stacked."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "17_modern_lm_stack.sh")],
        capture_output=True, text=True, timeout=600, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    text = out.stderr + out.stdout
    assert "done: final loss" in text
    assert "int8 weights-only PTQ" in text
    last = out.stdout.strip().splitlines()[-1]
    ids = [int(t) for t in last.split(",")]
    assert ids[:3] == [10, 20, 30] and len(ids) == 11


def test_example_18_speculative_decoding_completes():
    """Trains a byte-LM, then self-draft speculative decode: tokens must
    equal plain greedy (asserted inside) with fewer target passes."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "18_speculative_decoding.sh")],
        capture_output=True, text=True, timeout=600, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "tokens identical" in out.stdout
    assert "accept rate" in out.stdout


def test_example_19_multi_step_dispatch_completes():
    """Same job at --steps_per_dispatch 1 and 8: the script itself diffs
    the final loss lines and fails on any trajectory divergence."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "19_multi_step_dispatch.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trajectory identical" in out.stdout


def test_example_21_anakin_rl_completes():
    """Gridworld PPO through the CLI end to end (rl/): the script itself
    asserts the trained return EMA beats the measured random-policy
    (lr=0) baseline AND that a checkpoint-resumed run lands on the
    bitwise-identical params of the uninterrupted trajectory."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "21_anakin_rl.sh")],
        capture_output=True, text=True, timeout=560, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "return improved over the random-policy baseline" in out.stdout
    assert "resume trajectory-exact" in out.stdout


def test_example_20_paged_serving_completes():
    """The serve/ subsystem end to end on CPU: ragged prompts with SLOs
    through the continuous-batching scheduler over the paged KV pool;
    the script itself asserts token parity with generate() and a fully
    drained block allocator (for BOTH attention impls — the fused
    Pallas kernel must be client-invisible), and prints per-request
    TTFT/ITL plus the attended-keys ratio the kernel skips."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "20_paged_serving.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "block pool fully drained" in out.stdout
    assert "TTFT" in out.stdout
    assert ("attn_impl=fused == attn_impl=gathered: token-identical "
            "end to end") in out.stdout
    assert "the skipped FLOPs" in out.stdout


def test_example_22_prefix_cached_serving_completes():
    """The prefix cache end to end on CPU: a shared-system-prompt mix
    with a regenerated turn (full hit + CoW fork) through cache-on and
    cache-off schedulers; the script itself asserts token identity
    against both the cache-off arm and generate(), refcount drain, a
    faster cached drain, and prints the per-request cold-vs-cached
    TTFTs plus the hit/fork counters."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "22_prefix_cached_serving.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert ("tokens: cache on == cache off == generate() for all "
            "5 requests") in out.stdout
    assert "CoW fork(s)" in out.stdout
    assert "near-zero-TTFT admission verified" in out.stdout
    assert "block pool fully drained" in out.stdout


def test_example_23_serving_fleet_completes():
    """The serving fleet end to end on CPU: 2 supervised subprocess
    replicas behind the SLO-aware router, a SIGKILL mid-load, requeue
    with byte-identical tokens (asserted in-script against the
    undisturbed single-scheduler reference), supervisor relaunch with
    the sibling undisturbed, and the merged per-replica obs_agg view."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "23_serving_fleet.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "tokens byte-identical across the kill" in out.stdout
    assert ("supervisor: replica-0 relaunched; replica-1 undisturbed"
            in out.stdout)
    assert "per-writer" in out.stdout        # obs_agg breakdown rows


def test_example_24_fleet_autopilot_completes():
    """The fleet autopilot end to end on CPU, both arms: a mid-load
    weight push that promotes through canary -> judge -> grow -> drain
    (zero downtime, per-generation token attribution asserted
    in-script), and a TOCTOU-corrupted canary checkpoint that fails in
    the worker (exit 44) and rolls back with generation 0
    undisturbed."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "24_fleet_autopilot.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "rollout: promoted at t=" in out.stdout
    assert "zero downtime: all" in out.stdout
    assert "corrupt canary: rolled back at t=" in out.stdout
    assert "generation 0 undisturbed" in out.stdout


def test_example_25_preemption_drain_completes():
    """Notice-drain vs SIGKILL A/B on a 2-replica fleet: the same
    failure with and without the advance notice, over bitwise-identical
    traffic — the notice arm must requeue NOTHING (victim drains to
    exit 47, the autopilot backfills before it dies) while the SIGKILL
    arm requeues every in-flight request and redecodes their tokens."""
    out = subprocess.run(
        ["bash", str(REPO / "examples" / "25_preemption_drain.sh")],
        capture_output=True, text=True, timeout=420, env=_clean_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "notice arm: zero requeued requests" in out.stdout
    assert "requests requeued" in out.stdout          # the kill arm paid
    assert "identical traffic both arms" in out.stdout
