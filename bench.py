"""Benchmark harness — prints ONE JSON line on stdout:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: BASELINE.json config #2 (wide regression MLP, 4x512 hidden), the
config that stresses the gradient allreduce — trained with this framework's
jitted SPMD train step on the available accelerator.

``vs_baseline``: ratio against the reference's own stack measured inline —
a single-process torch CPU implementation of the reference's training loop
(the only configuration the reference was ever run in: its README says the
cluster path was untested, README.md:10, and it publishes no numbers,
BASELINE.md).  Identical model, batch size, optimizer, and loss.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


BATCH = 8192
WIDTH = 512
DEPTH = 4
IN_FEATURES = 32
WARMUP_STEPS = 3
MEASURE_STEPS = 20
BASELINE_STEPS = 5


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_framework() -> float:
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import wide_mlp
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    devices = jax.devices()
    log(f"framework devices: {devices}")
    mesh = mesh_lib.make_mesh(MeshConfig(data=len(devices)), devices=devices)
    # TPU: bfloat16 matmuls feed the MXU at 2x the f32 rate (params and the
    # loss stay f32 — ops.losses accumulates in f32).  CPU smoke runs keep
    # f32: host bf16 is emulated and would only slow the hermetic test.
    on_tpu = devices[0].platform not in ("cpu",)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    log(f"compute dtype: {compute_dtype.__name__}")
    model = wide_mlp(in_features=IN_FEATURES, width=WIDTH, depth=DEPTH,
                     compute_dtype=compute_dtype)
    opt = optim.sgd(lr=1e-4, momentum=0.9)
    state = TrainState.create(model, opt, prng.init_key(0))
    state = dp.replicate_state(state, mesh)
    step = dp.make_train_step(model, opt, mesh, "mse", "global_mean")

    rng = np.random.default_rng(0)
    batch = {
        "x": rng.standard_normal((BATCH, IN_FEATURES)).astype(np.float32),
        "y": rng.standard_normal((BATCH, 1)).astype(np.float32),
        "mask": np.ones((BATCH,), np.float32),
    }
    batch = shd.shard_batch(mesh, batch)

    t0 = time.perf_counter()
    for _ in range(WARMUP_STEPS):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    log(f"compile+warmup: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    sps = BATCH * MEASURE_STEPS / dt
    log(f"framework: {MEASURE_STEPS} steps in {dt:.3f}s -> {sps:,.0f} samples/sec")
    return sps


def bench_reference_baseline() -> float:
    """The reference's training loop (torch MLP + SGD + MSE, full-batch
    steps; dataParallelTraining_NN_MPI.py:149-211) on CPU, single process,
    same workload — re-expressed, not copied."""
    import torch

    torch.manual_seed(0)
    layers = []
    prev = IN_FEATURES
    for _ in range(DEPTH):
        layers += [torch.nn.Linear(prev, WIDTH), torch.nn.ReLU()]
        prev = WIDTH
    layers.append(torch.nn.Linear(prev, 1))
    model = torch.nn.Sequential(*layers)
    optimizer = torch.optim.SGD(model.parameters(), lr=1e-4, momentum=0.9)
    loss_fn = torch.nn.MSELoss()
    x = torch.randn(BATCH, IN_FEATURES)
    y = torch.randn(BATCH, 1)

    def one_step():
        optimizer.zero_grad()
        loss = loss_fn(model(x), y)
        loss.backward()
        optimizer.step()

    one_step()  # warmup
    t0 = time.perf_counter()
    for _ in range(BASELINE_STEPS):
        one_step()
    dt = time.perf_counter() - t0
    sps = BATCH * BASELINE_STEPS / dt
    log(f"reference baseline (torch cpu): {BASELINE_STEPS} steps in {dt:.3f}s "
        f"-> {sps:,.0f} samples/sec")
    return sps


def main() -> None:
    framework_sps = bench_framework()
    baseline_sps = bench_reference_baseline()
    print(json.dumps({
        "metric": "wide_mlp_train_samples_per_sec",
        "value": round(framework_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(framework_sps / baseline_sps, 3),
    }))


if __name__ == "__main__":
    main()
