"""Benchmark harness — prints ONE JSON line on stdout:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "platform": ..., "device_kind": ..., "mfu": ...}

Default workload: BASELINE.json config #2 (wide regression MLP, 4x512
hidden), the config that stresses the gradient allreduce — trained with this
framework's jitted SPMD train step on the available accelerator.

Platform resolution is hang-proof: accelerator availability is probed from a
subprocess with a timeout (a wedged exclusive-TPU tunnel blocks forever
inside backend init rather than erroring), with retries, and on failure the
bench falls back to CPU and says so in the JSON ``platform`` field instead
of dying — the reference's workload runs anywhere with one command
(reference README.md:12) and so must this.

``vs_baseline``: ratio against the reference's own stack measured inline —
a single-process torch CPU implementation of the reference's training loop
(the only configuration the reference was ever run in: its README says the
cluster path was untested, README.md:10, and it publishes no numbers,
BASELINE.md).  Identical model, batch size, optimizer, and loss.

``mfu``: model matmul/conv FLOPs per optimizer step (fwd + 2x bwd) divided
by measured step time and the chip's peak bf16 FLOPs (TPU only; null on the
CPU fallback where "peak FLOPs" is not meaningful).

Extras (not used by the driver, which runs ``python bench.py``):

    python bench.py --config {toy,wide,mnist,cifar,lm}   # pick workload
    python bench.py --all                                # all five -> BENCH_FULL.json
    python bench.py --scaling                            # 1..8-device virtual-mesh
                                                         # sweep -> BENCH_SCALING.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

WARMUP_STEPS = 3
# Probe budget: the tunnel to the exclusive chip is flaky (observed wedged
# for whole sessions), so the default is several MINUTES of spaced attempts
# (VERDICT r2 item 1), each individually hang-proof.  Worst case with the
# defaults: 5 x 75s probes + 30/60/90/120s backoffs ~= 11 min, once, at
# capture time (kept under the round-end harness's patience; a quick
# fallback beats a killed capture).  All three knobs are env-tunable.
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "5"))
PROBE_BACKOFF_S = float(os.environ.get("BENCH_PROBE_BACKOFF", "30"))
TPU_LATEST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_TPU_LATEST.json")
# CPU timing repetitions (min-of-k, both frameworks): the fallback host is a
# single shared core, so transient load skews any single window by +-10%
_CPU_TIMING_REPS = 3

def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _git_rev() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None
    except Exception:
        return None


#: bumped when the _meta block itself changes shape
ARTIFACT_SCHEMA = 1


def _emit_artifact(path: str, doc, honesty: dict | None = None) -> str:
    """Write one BENCH_*.json artifact with the shared ``_meta`` stamp
    and an atomic replace (a reader never sees a torn artifact).

    Every artifact carries the same provenance block — schema version,
    generation time, host, python, git revision — plus per-bench
    *honesty flags* (cpu_fallback, interleaved methodology, bitwise
    pins): ``tools/bench_diff.py`` refuses to compare artifacts whose
    provenance says the numbers were measured under different rules.
    ``cpu_fallback`` is derived from the record's own ``platform``
    field when present; callers add bench-specific flags via
    ``honesty``."""
    import socket

    meta: dict = {
        "schema": ARTIFACT_SCHEMA,
        "generated_unix": round(time.time(), 1),
        "generated_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
        "host": socket.gethostname(),
        "python": sys.version.split()[0],
        "git_rev": _git_rev(),
    }
    flags = dict(honesty or {})
    if isinstance(doc, dict):
        platform_field = doc.get("platform")
        if isinstance(platform_field, str) and "cpu_fallback" not in flags:
            flags["cpu_fallback"] = platform_field == "cpu"
        if "note" in doc and "interleaved" not in flags:
            flags["interleaved"] = "interleaved" in str(doc["note"])
    if flags:
        meta["honesty"] = flags
    if isinstance(doc, dict):
        doc = {**doc, "_meta": meta}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return path


def peak_flops(device_kind: str) -> float | None:
    """Chip peak dense bf16 FLOPs/s (None off-TPU) — kept as bench's public
    name; the table and the per-step FLOPs formula live in
    train.telemetry (the telemetry subsystem's MFU accounting), so bench,
    the trainer's metrics stream and tools/big_lm_sweep.py all divide by
    the same numbers.  (Lazy import: bench must stay import-light until
    the platform is pinned.)"""
    from neural_networks_parallel_training_with_mpi_tpu.train.telemetry import (
        peak_flops_per_chip,
    )

    return peak_flops_per_chip(device_kind)


# ---------------------------------------------------------------------------
# Workload configs (BASELINE.json's five).  Each entry:
#   batch, measure_steps, baseline_steps, loss, make_model(compute_dtype),
#   make_batch(rng, B) -> dict of numpy arrays.  FLOPs accounting lives on
#   the models themselves (Module.fwd_flops) — no per-config formulas here.
# ---------------------------------------------------------------------------

_LM = dict(vocab=2048, seq=256, d_model=256, n_layers=4, n_heads=8, d_ff=1024)
# The flagship high-MFU config (VERDICT r2 item 2): sized so the FFN/qkv
# matmuls dominate (d_ff = 4d, T=1024 keeps attention ~14% of FLOPs), bf16
# on the MXU, flash attention, scan_layers for compile time.  ~218M params
# -> fits v5e HBM with SGD momentum state; ~10.3 TFLOP/step at B=8, so
# 0.4 MFU needs <= ~131 ms/step on a 197-TFLOP/s chip.
_BIG = dict(vocab=32768, seq=1024, d_model=1024, n_layers=12, n_heads=16,
            d_ff=4096)
_WIDE = dict(in_features=32, width=512, depth=4)


def _regression_batch(rng, batch, in_features):
    return {
        "x": rng.standard_normal((batch, in_features)).astype(np.float32),
        "y": rng.standard_normal((batch, 1)).astype(np.float32),
        "mask": np.ones((batch,), np.float32),
    }


def _class_batch(rng, batch, in_features, n_classes):
    return {
        "x": rng.standard_normal((batch, in_features)).astype(np.float32),
        "y": rng.integers(0, n_classes, (batch,)).astype(np.int32),
        "mask": np.ones((batch,), np.float32),
    }


def _make_config(name):
    from neural_networks_parallel_training_with_mpi_tpu.models.convnet import ConvNet
    from neural_networks_parallel_training_with_mpi_tpu.models.mlp import (
        MLP, mnist_mlp, wide_mlp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )

    if name == "toy":
        # The reference's exact workload: 16x2 make_regression, MLP 2->3->1
        # (dataParallelTraining_NN_MPI.py:41-45,:72).  Throughput here is
        # dispatch-bound, not FLOPs-bound — it measures step overhead.
        return dict(
            batch=16, measure_steps=200, baseline_steps=200, loss="mse",
            make_model=lambda cd: MLP(2, (3,), 1, compute_dtype=cd),
            make_batch=lambda rng, B: _regression_batch(rng, B, 2),
        )
    if name == "wide":
        d = _WIDE
        return dict(
            batch=8192, measure_steps=20, baseline_steps=5, loss="mse",
            make_model=lambda cd: wide_mlp(in_features=d["in_features"],
                                           width=d["width"], depth=d["depth"],
                                           compute_dtype=cd),
            make_batch=lambda rng, B: _regression_batch(rng, B, d["in_features"]),
        )
    if name == "mnist":
        return dict(
            batch=4096, measure_steps=50, baseline_steps=10,
            loss="cross_entropy",
            make_model=lambda cd: mnist_mlp(compute_dtype=cd),
            make_batch=lambda rng, B: _class_batch(rng, B, 784, 10),
        )
    if name == "cifar":
        def make_batch(rng, B):
            return {
                "x": rng.standard_normal((B, 32, 32, 3)).astype(np.float32),
                "y": rng.integers(0, 10, (B,)).astype(np.int32),
                "mask": np.ones((B,), np.float32),
            }

        return dict(
            batch=512, measure_steps=20, baseline_steps=3,
            loss="cross_entropy",
            make_model=lambda cd: ConvNet(compute_dtype=cd),
            make_batch=make_batch,
        )
    if name == "big_lm":
        c = _BIG

        def make_batch(rng, B):
            return {
                "x": rng.integers(0, c["vocab"], (B, c["seq"])).astype(np.int32),
                "y": rng.integers(0, c["vocab"], (B, c["seq"])).astype(np.int32),
                "mask": np.ones((B,), np.float32),
            }

        def make_model(cd):
            # remat=False is the round-4 chip-validated choice: the CPU
            # buffer-assignment proxy reads ~17 GB of temps at B=8 (over
            # v5e's 16 GB HBM) but the REAL chip executed it repeatedly at
            # 163-178 ms/step — the proxy is pessimistic for no-remat
            # programs (BASELINE.md).  The preflight records the proxy
            # number and accepts the config via its chip_validated
            # override; remat_policy stays "dots" so derived remat=True
            # variants keep the measured policy.
            # scan_layers=False + ce_chunk=256 are the round-4 sweep
            # winners (BIGLM_SWEEP.json b8_none_unroll_ce256: 138.5 ms =
            # MFU 0.378 vs 163.8 ms / 0.320 scanned): lax.scan over the
            # 12 blocks serialized XLA's scheduler at every layer
            # boundary, and with the layers unrolled the fused chunked CE
            # is a further win (166.4 -> 138.5) instead of neutral.
            # Compile time rises (one traced block -> 12): 35-36 s
            # measured on the chip (BIGLM_SWEEP b8_none_unroll* rows) vs
            # 5-9 s scanned — size watchdog timeouts accordingly.
            # scan_layers=True keeps its coverage in
            # tests/test_scan_layers.py and the SP path.
            return Transformer(TransformerConfig(
                vocab_size=c["vocab"], max_seq_len=c["seq"],
                n_layers=c["n_layers"], d_model=c["d_model"],
                n_heads=c["n_heads"], d_ff=c["d_ff"], compute_dtype=cd,
                attention="flash", scan_layers=False,
                remat=False, remat_policy="dots", ce_chunk=256))

        # no torch baseline: a ~218M-param CPU step takes minutes — the
        # config exists to measure MFU on the chip, not to race torch
        return dict(
            batch=8, measure_steps=10, baseline_steps=0,
            loss="cross_entropy", make_model=make_model,
            make_batch=make_batch,
        )
    if name in ("lm", "moe"):
        c = _LM

        def make_batch(rng, B):
            return {
                "x": rng.integers(0, c["vocab"], (B, c["seq"])).astype(np.int32),
                "y": rng.integers(0, c["vocab"], (B, c["seq"])).astype(np.int32),
                "mask": np.ones((B,), np.float32),
            }

        def make_model(cd, moe=(name == "moe")):
            return Transformer(TransformerConfig(
                vocab_size=c["vocab"], max_seq_len=c["seq"],
                n_layers=c["n_layers"], d_model=c["d_model"],
                n_heads=c["n_heads"], d_ff=c["d_ff"], compute_dtype=cd,
                moe_experts=_MOE_EXPERTS if moe else 0))

        return dict(
            batch=32, measure_steps=20, baseline_steps=3,
            loss="cross_entropy",
            make_model=make_model, make_batch=make_batch,
        )
    raise ValueError(f"unknown config {name!r}")


METRIC_NAMES = {
    "toy": "toy_mlp_train_samples_per_sec",
    "wide": "wide_mlp_train_samples_per_sec",
    "mnist": "mnist_mlp_train_samples_per_sec",
    "cifar": "cifar_convnet_train_samples_per_sec",
    "lm": "tiny_lm_train_samples_per_sec",
    # extra (not in BASELINE.json's five): Switch top-1 MoE LM — 8 experts,
    # same active per-token FLOPs as "lm"; its torch baseline is that
    # iso-active-FLOPs dense LM (the standard MoE-vs-dense comparison)
    "moe": "moe_lm_train_samples_per_sec",
    # extra: the flagship MFU config (_BIG) — TPU-only, no torch baseline
    "big_lm": "big_lm_train_samples_per_sec",
}
_MOE_EXPERTS = 8


def timed_chain(step, state, batch, n: int, sync_every: int = 0):
    """Dispatch n chained steps and time to the final loss VALUE.
    device_get is the sync: on the tunneled-TPU backend block_until_ready
    can resolve before the chain has executed (observed: apparent MFU >
    100%), but the loss value cannot exist until every prior step ran.
    A single timed chain measures n*step + a constant (host round-trip to
    the device, ~65 ms through the tunnel, plus the final transfer);
    callers time two chain lengths and difference to cancel the constant.

    ``sync_every`` bounds the async dispatch queue (block_until_ready every
    K steps).  Required on the virtual-CPU mesh: a deep queue of tiny
    8-device programs can starve XLA:CPU's collective rendezvous past its
    fatal 40 s termination timeout.  Leave 0 on TPU — the local sync is
    ~free on CPU but would re-introduce the tunnel round trip into the
    differenced timing on TPU.  Returns (seconds, new_state, loss_value)."""
    import jax

    t0 = time.perf_counter()
    loss = None
    for i in range(n):
        state, loss = step(state, batch)
        if sync_every and (i + 1) % sync_every == 0:
            jax.block_until_ready(loss)
    val = float(jax.device_get(loss))
    return time.perf_counter() - t0, state, val


def _chain_sync_every() -> int:
    import jax

    return 0 if jax.default_backend() == "tpu" else 25


def bench_framework(config_name: str, batch_override: int | None = None,
                    grad_reduction: str = "global_mean") -> dict:
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    cfg = _make_config(config_name)
    if batch_override:
        cfg["batch"] = batch_override
    devices = jax.devices()
    log(f"[{config_name}] devices: {devices}")
    mesh = mesh_lib.make_mesh(MeshConfig(data=len(devices)), devices=devices)
    # TPU: bfloat16 matmuls feed the MXU at 2x the f32 rate (params and the
    # loss stay f32 — ops.losses accumulates in f32).  CPU smoke runs keep
    # f32: host bf16 is emulated and would only slow the hermetic test.
    on_tpu = devices[0].platform not in ("cpu",)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    model = cfg["make_model"](compute_dtype)
    opt = optim.sgd(lr=1e-4, momentum=0.9)
    state = TrainState.create(model, opt, prng.init_key(0))
    state = dp.replicate_state(state, mesh)
    step = dp.make_train_step(model, opt, mesh, cfg["loss"], grad_reduction)

    batch_size = cfg["batch"]
    rng = np.random.default_rng(0)
    raw_batch = cfg["make_batch"](rng, batch_size)
    batch = shd.shard_batch(mesh, raw_batch)

    sync = _chain_sync_every()
    t0 = time.perf_counter()
    _, state, _ = timed_chain(step, state, batch, WARMUP_STEPS, sync)
    log(f"[{config_name}] compile+warmup: {time.perf_counter() - t0:.1f}s")

    # two chain lengths, differenced (see timed_chain).  measure_steps is
    # sized for the TPU; the CPU fallback runs the same workload 1000x
    # slower, so scale the chains down there (it is a smoke/mechanism
    # number, not the driver's headline).  The pair is repeated and the
    # fastest per-step time kept — min-of-k cancels transient host load
    # (single shared core); the torch baseline gets the same treatment.
    n1 = cfg["measure_steps"]
    if not on_tpu:
        n1 = max(3, n1 // 4)
    n2 = 3 * n1
    best_dt, best_steps, loss_val = None, None, None
    for _rep in range(1 if on_tpu else _CPU_TIMING_REPS):
        t1, state, _ = timed_chain(step, state, batch, n1, sync)
        t2, state, loss_val = timed_chain(step, state, batch, n2, sync)
        dt = max(t2 - t1, 1e-9)
        steps = n2 - n1
        if t2 <= t1:  # noise floor (sub-ms configs on a local backend)
            dt, steps = t2, n2
        if best_dt is None or dt / steps < best_dt / best_steps:
            best_dt, best_steps = dt, steps
    dt, steps = best_dt, best_steps
    sps = batch_size * steps / dt
    step_ms = dt / steps * 1e3
    log(f"[{config_name}] final loss {loss_val:.5f}")

    # MFU: matmul/conv FLOPs for one optimizer step = fwd + ~2x fwd for the
    # backward, over every chip's peak.  Single source:
    # train.telemetry.train_step_flops (which consults Module.fwd_flops).
    from neural_networks_parallel_training_with_mpi_tpu.train.telemetry import (
        train_step_flops,
    )

    train_flops = train_step_flops(model, raw_batch["x"].shape)
    param_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                      for l in jax.tree_util.tree_leaves(state.params))
    kind = devices[0].device_kind
    peak = peak_flops(kind) if on_tpu else None
    mfu = (train_flops / (dt / steps) / (peak * len(devices))
           if peak and train_flops is not None else None)
    log(f"[{config_name}] {steps} steps in {dt:.3f}s -> {sps:,.0f} samples/sec"
        f" ({step_ms:.2f} ms/step"
        + (f", MFU {mfu:.1%}" if mfu is not None else "") + ")")
    rec = dict(
        config=config_name, samples_per_sec=sps, step_ms=step_ms,
        mfu=None if mfu is None else round(mfu, 4),
        platform=devices[0].platform, device_kind=kind,
        n_devices=len(devices), batch=batch_size,
        train_flops_per_step=train_flops, param_bytes=param_bytes,
    )
    # multi-step dispatch (--steps_per_dispatch, VERDICT r4 item 6): the
    # dispatch-bound configs (MNIST 0.011 / CIFAR 0.038 MFU) spend their
    # step in the host->device round trip this per-step loop above pays by
    # construction.  Measure the lever: k distinct batches staged in ONE
    # transfer (shard_batch_stack), k steps in ONE lax.scan dispatch —
    # including the transfer in the timed region, because that is the real
    # per-dispatch cost the trainer's epoch_groups path pays.
    if (config_name in ("toy", "wide", "mnist", "cifar")
            and not os.environ.get("BENCH_SKIP_DISPATCH8")):
        from jax import lax

        k_disp = 8

        def multi(state, stacked):
            return lax.scan(lambda s, b: step(s, b), state, stacked)

        multi = jax.jit(multi)
        host_batches = [cfg["make_batch"](rng, batch_size)
                        for _ in range(k_disp)]
        stacked = shd.shard_batch_stack(mesh, host_batches)
        state, losses = multi(state, stacked)     # compile
        float(jax.device_get(losses[-1]))
        n_disp = max(2, (n2 // k_disp))
        best = None
        for _rep in range(1 if on_tpu else _CPU_TIMING_REPS):
            t0 = time.perf_counter()
            for _ in range(n_disp):
                stacked = shd.shard_batch_stack(mesh, host_batches)
                state, losses = multi(state, stacked)
            float(jax.device_get(losses[-1]))
            d = time.perf_counter() - t0
            best = d if best is None else min(best, d)
        ms_k = best / (n_disp * k_disp) * 1e3
        rec["step_ms_dispatch8"] = round(ms_k, 3)
        rec["dispatch8_speedup"] = round(step_ms / ms_k, 3)
        if mfu is not None:
            rec["mfu_dispatch8"] = round(
                train_flops / (ms_k / 1e3) / (peak * len(devices)), 4)
        log(f"[{config_name}] steps_per_dispatch=8: {ms_k:.3f} ms/step "
            f"({rec['dispatch8_speedup']}x vs per-step dispatch)")
    return rec


# ---------------------------------------------------------------------------
# Reference baseline: the reference's training loop (torch model + SGD +
# loss, full-batch steps; dataParallelTraining_NN_MPI.py:149-211) on CPU,
# single process, same nominal workload — re-expressed, not copied.
# ---------------------------------------------------------------------------

def bench_reference_baseline(config_name: str,
                             batch_override: int | None = None) -> float:
    import torch

    cfg = _make_config(config_name)
    B = batch_override or cfg["batch"]
    torch.manual_seed(0)

    def mlp(dims):
        layers = []
        for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
            layers.append(torch.nn.Linear(a, b))
            if i < len(dims) - 2:
                layers.append(torch.nn.ReLU())
        return torch.nn.Sequential(*layers)

    if config_name == "toy":
        model = mlp((2, 3, 1))
        x = torch.randn(B, 2); y = torch.randn(B, 1)
        loss_fn = torch.nn.MSELoss()
    elif config_name == "wide":
        d = _WIDE
        model = mlp((d["in_features"],) + (d["width"],) * d["depth"] + (1,))
        x = torch.randn(B, d["in_features"]); y = torch.randn(B, 1)
        loss_fn = torch.nn.MSELoss()
    elif config_name == "mnist":
        model = mlp((784, 256, 128, 10))
        x = torch.randn(B, 784)
        y = torch.randint(0, 10, (B,))
        loss_fn = torch.nn.CrossEntropyLoss()
    elif config_name == "cifar":
        model = torch.nn.Sequential(
            torch.nn.Conv2d(3, 32, 3, padding=1), torch.nn.ReLU(),
            torch.nn.AvgPool2d(2),
            torch.nn.Conv2d(32, 64, 3, padding=1), torch.nn.ReLU(),
            torch.nn.AvgPool2d(2),
            torch.nn.Flatten(),
            torch.nn.Linear(64 * 8 * 8, 128), torch.nn.ReLU(),
            torch.nn.Linear(128, 10),
        )
        x = torch.randn(B, 3, 32, 32)
        y = torch.randint(0, 10, (B,))
        loss_fn = torch.nn.CrossEntropyLoss()
    elif config_name in ("lm", "moe"):
        # "moe": the routed Switch-MoE model's torch baseline is the dense
        # LM with the SAME active per-token FLOPs (top-1 of E experts
        # runs exactly one d_ff FFN per token) — the standard iso-FLOPs
        # MoE-vs-dense comparison
        c = _LM

        class TorchLM(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.embed = torch.nn.Embedding(c["vocab"], c["d_model"])
                self.pos = torch.nn.Embedding(c["seq"], c["d_model"])
                layer = torch.nn.TransformerEncoderLayer(
                    c["d_model"], c["n_heads"], c["d_ff"],
                    activation="gelu", batch_first=True, dropout=0.0)
                self.blocks = torch.nn.TransformerEncoder(layer, c["n_layers"])
                self.head = torch.nn.Linear(c["d_model"], c["vocab"], bias=False)
                mask = torch.triu(torch.ones(c["seq"], c["seq"]), 1).bool()
                self.register_buffer("mask", mask)

            def forward(self, tokens):
                h = self.embed(tokens) + self.pos.weight[None, : tokens.shape[1]]
                h = self.blocks(h, mask=self.mask)
                return self.head(h)

        model = TorchLM()
        x = torch.randint(0, c["vocab"], (B, c["seq"]))
        y = torch.randint(0, c["vocab"], (B, c["seq"]))
        ce = torch.nn.CrossEntropyLoss()
        loss_fn = lambda logits, yy: ce(logits.reshape(-1, c["vocab"]), yy.reshape(-1))
    else:
        raise ValueError(config_name)

    optimizer = torch.optim.SGD(model.parameters(), lr=1e-4, momentum=0.9)

    def one_step():
        optimizer.zero_grad()
        loss = loss_fn(model(x), y)
        loss.backward()
        optimizer.step()

    one_step()  # warmup
    steps = cfg["baseline_steps"]
    dt = None
    for _rep in range(_CPU_TIMING_REPS):  # min-of-k, same as the framework
        t0 = time.perf_counter()
        for _ in range(steps):
            one_step()
        dt = (time.perf_counter() - t0 if dt is None
              else min(dt, time.perf_counter() - t0))
    sps = B * steps / dt
    log(f"[{config_name}] reference baseline (torch cpu): best of "
        f"{_CPU_TIMING_REPS}x{steps} steps: {dt:.3f}s -> "
        f"{sps:,.0f} samples/sec")
    return sps


# ---------------------------------------------------------------------------
# Scaling sweep: re-run the wide config in subprocesses with 1..8 virtual CPU
# devices (the role mpiexec -n N plays for the reference on one machine,
# reference README.md:10-12).  Virtual devices share one host's cores, so
# this validates the *mechanism* (per-device batch shrinks, allreduce grows);
# chip-count scaling numbers require real chips.
# ---------------------------------------------------------------------------

def _run_child_cpu(config: str, n_devices: int = 1,
                   baseline: bool = False, timeout: float = 900,
                   batch: int | None = None,
                   grad_reduction: str | None = None) -> dict | None:
    """Run one bench config in a CPU-pinned subprocess; return its JSON
    record (or None on failure).  A subprocess is required both for the
    mesh-size sweep (XLA device count is fixed at backend init) and for the
    accelerator-failure fallback (a process whose backend already
    initialized cannot switch platforms)."""
    env = _cpu_child_env(n_devices)
    # scaling-sweep children only ever read step_ms; the dispatch8
    # side-measurement would add a k=8 scan compile + timing reps to each
    # of the ~30 median-of-k attribution children for discarded output
    env["BENCH_SKIP_DISPATCH8"] = "1"
    cmd = [sys.executable, __file__, "--config", config, "--platform", "cpu"]
    if batch:
        cmd += ["--batch", str(batch)]
    if grad_reduction:
        cmd += ["--grad-reduction", grad_reduction]
    if not baseline:
        cmd.append("--no-baseline")
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        log(f"[child {config} n={n_devices}] timed out after {timeout:.0f}s")
        return None
    if out.returncode != 0:
        log(f"[child {config} n={n_devices}] FAILED:\n{out.stderr[-2000:]}")
        return None
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def run_scaling_sweep(out_path: str = "BENCH_SCALING.json",
                      per_device_batch: int = 1024) -> None:
    """WEAK scaling on the virtual-CPU mesh: fixed per-device batch, 1->8
    devices, so total work grows with n and the interesting number is the
    work-normalized step-time inflation t_n / (n * t_1).  On this host all
    virtual devices share ONE core, so ideal weak scaling is t_n = n * t_1
    exactly; anything beyond 1.0 isolates the cost the framework ADDS when
    the mesh grows — batch partitioning, the per-device gradient psum
    (ring-allreduce bytes reported analytically per device), and XLA:CPU's
    collective rendezvous.  This replaces the earlier strong-scaling sweep,
    whose 8-devices-on-1-core efficiency number measured core contention,
    not the framework (VERDICT r2 item 6)."""
    results = []
    for n in (1, 2, 4, 8):
        rec = _run_child_cpu("wide", n_devices=n, batch=per_device_batch * n)
        if rec is None:
            continue
        rec["n_devices"] = n
        rec["per_device_batch"] = per_device_batch
        pb = rec.get("param_bytes")
        # ring all-reduce moves 2(n-1)/n * bytes per device per step
        rec["allreduce_bytes_per_device"] = (
            None if pb is None else int(2 * (n - 1) / n * pb))
        # collective-cost attribution (VERDICT r3 item 7 / r4 item 7):
        # the identical per-shard compute with every gradient psum
        # removed ('local' ablation, parallel.data_parallel).  A single
        # full/ablate pair drowned at n=8 (the diff was smaller than this
        # single-core host's run-to-run noise), so the diff is now a
        # MEDIAN-OF-K INTERLEAVED DIFFERENCE: k alternating (full,
        # ablate) child runs cancel slow load drift, the medians
        # difference, and the repeat spread (max-min of each column) is
        # the stated noise floor — when the diff still loses to it, the
        # row carries the statistical BOUND instead of null.
        if n > 1:
            k_reps = 5
            fulls, ablates = [rec["step_ms"]], []
            for _rep in range(k_reps):
                ab = _run_child_cpu("wide", n_devices=n,
                                    batch=per_device_batch * n,
                                    grad_reduction="local")
                if ab is not None:
                    ablates.append(ab["step_ms"])
                if len(fulls) < k_reps:
                    fl = _run_child_cpu("wide", n_devices=n,
                                        batch=per_device_batch * n)
                    if fl is not None:
                        fulls.append(fl["step_ms"])
            if ablates:
                med_full = float(np.median(fulls))
                med_ab = float(np.median(ablates))
                spread = round(max(np.ptp(fulls), np.ptp(ablates)), 3)
                rec["compute_ms"] = round(med_ab, 3)
                rec["step_ms_median_of_k"] = round(med_full, 3)
                rec["repeat_spread_ms"] = spread
                rec["attribution_reps"] = {"full": len(fulls),
                                           "ablate": len(ablates)}
                diff = round(med_full - med_ab, 3)
                if diff > 0 and diff > spread / 2:
                    rec["collective_ms"] = diff
                    rec["collective_pct_of_step"] = round(
                        100.0 * diff / med_full, 1)
                    rec["collective_attribution"] = "measured_median_of_k"
                else:
                    # the true cost is indistinguishable from noise even
                    # after k interleaved repeats: publish the bound the
                    # data supports, not null
                    bound = round(max(diff, 0.0) + spread / 2, 3)
                    rec["collective_ms"] = None
                    rec["collective_ms_upper_bound"] = bound
                    rec["collective_pct_of_step"] = None
                    rec["collective_pct_upper_bound"] = round(
                        100.0 * bound / med_full, 1)
                    rec["collective_attribution"] = \
                        "bounded_by_noise_median_of_k"
        else:
            rec["compute_ms"] = rec["step_ms"]
            rec["collective_ms"] = 0.0
            rec["collective_pct_of_step"] = 0.0
            rec["collective_attribution"] = "no_collectives_at_n1"
        results.append(rec)
        log(f"[weak-scaling n={n}] {rec['step_ms']:.1f} ms/step "
            f"(global batch {per_device_batch * n}, collective "
            f"{rec.get('collective_ms', '?')} ms)")
    base = next((r["step_ms"] for r in results if r["n_devices"] == 1), None)
    if base:
        for rec in results:
            infl = rec["step_ms"] / (base * rec["n_devices"])
            rec["work_normalized_inflation"] = round(infl, 3)
            rec["framework_overhead_pct"] = round((infl - 1.0) * 100, 1)
            comp = rec.get("compute_ms")
            if comp is not None:
                # how much of the overhead is collectives vs everything
                # else (partitioning, scheduling, rendezvous-free compute
                # inflation)
                comp_infl = comp / (base * rec["n_devices"])
                rec["compute_only_overhead_pct"] = round(
                    (comp_infl - 1.0) * 100, 1)
    ncpu = os.cpu_count() or 1
    note = ("fixed per-device batch on 1..8 virtual CPU devices sharing "
            f"{ncpu} host core(s): with one core, ideal is step_ms = n * "
            "t_1 and work_normalized_inflation - 1 isolates partitioning + "
            "collective overhead added by the framework; compute_ms is the "
            "same step with every gradient psum removed "
            "(--grad-reduction local), so collective_ms = step - compute "
            "(median of k interleaved full/ablate repeats; rows the noise "
            "floor still beats carry collective_ms_upper_bound instead) "
            "attributes the allreduce/rendezvous share and "
            "compute_only_overhead_pct the rest (XLA:CPU per-program "
            "dispatch, which multiplies with n on one shared core and "
            "vanishes on real chips — BASELINE.md)")
    if ncpu > 1:
        note += ("; CAUTION: with multiple cores virtual devices run "
                 "partly in parallel, deflating the inflation metric below "
                 "its single-core meaning")
    note += " (chip-count scaling needs real chips)"
    if results:
        _emit_artifact(out_path, {
            "config": "wide", "mode": "weak_scaling",
            "host_cpu_count": ncpu, "note": note,
            "results": results})
        log(f"weak-scaling sweep -> {out_path}")


def preflight_config(config_name: str = "big_lm",
                     out_path: str | None = None,
                     smoke_layers: int = 2, smoke_batch: int = 2,
                     smoke_steps: int = 2,
                     hbm_bytes: float = 16 * 1024**3) -> dict:
    """No-chip de-risking of a TPU-oriented config (VERDICT r3 item 2).

    ``big_lm`` exists to measure MFU on the real chip, and the tunnel to
    that chip has been reachable for minutes per round — so every failure
    mode that does NOT need the chip must be burned down in advance, on
    CPU, leaving only Mosaic lowering chip-gated.  Four checks:

    1. **State byte budget** (`jax.eval_shape`, allocates nothing): params
       + optimizer state + one gradient pytree, in the TPU dtypes (bf16
       compute / f32 params, exactly what ``bench_framework`` builds).
    2. **Trace check**: ``jax.eval_shape`` of the full jitted train step at
       the real batch shapes — shape errors surface here, not on the chip.
    3. **XLA buffer assignment**: lower + compile the step for CPU and read
       ``compiled.memory_analysis()`` — XLA's own peak temp (activation)
       estimate for this program.  The CPU buffer assignment is not the TPU
       one (different fusion/layout), but it is the same order and catches
       a config that cannot fit 16 GB v5e HBM by construction.
    4. **Same-shape-class smoke**: a scaled-down model (``smoke_layers``
       layers, SAME d_model/d_ff/vocab/seq — the matmul shape classes the
       MXU will see) trains ``smoke_steps`` real steps on CPU; the loss
       must be finite and near ln(vocab) at init.

    Runs CPU-pinned (never touches the tunnel); writes ``out_path`` and
    returns the record.  The v5e HBM default (16 GiB) and the ~9/16 GiB
    measured budget are documented in BASELINE.md.
    """
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    if out_path is None:
        # only big_lm owns the canonical artifact ARTIFACTS.md documents;
        # a cheap preflight of another config must not clobber it
        out_path = ("BENCH_PREFLIGHT.json" if config_name == "big_lm"
                    else f"BENCH_PREFLIGHT_{config_name}.json")
    cfg = _make_config(config_name)
    rec = {"metric": f"{config_name}_preflight", "config": config_name,
           "hbm_capacity_bytes": int(hbm_bytes)}

    def tree_bytes(shapes) -> int:
        return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree_util.tree_leaves(shapes))

    # -- 1. state bytes in the TPU dtype configuration (nothing allocated)
    model = cfg["make_model"](jnp.bfloat16)
    opt = optim.sgd(lr=1e-4, momentum=0.9)
    state_shapes = jax.eval_shape(
        lambda: TrainState.create(model, opt, prng.init_key(0)))
    param_b = tree_bytes(state_shapes.params)
    opt_b = tree_bytes(state_shapes.opt_state)
    rec.update(param_bytes=param_b, opt_state_bytes=opt_b,
               grad_bytes=param_b)

    # -- 2 + 3. trace the REAL train step, compile the buffer proxy
    # (1-device CPU mesh — bench_framework on the single-chip bench
    # builds exactly this).  All-abstract: the trace and the buffer
    # assignment only need shapes, so no ~1.7 GB of real f32 state is
    # materialized on the test host.
    mesh = mesh_lib.make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    step = dp.make_train_step(model, opt, mesh, cfg["loss"], "global_mean")
    rng = np.random.default_rng(0)
    raw = cfg["make_batch"](rng, cfg["batch"])
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in raw.items()}
    jax.eval_shape(step, state_shapes, batch)
    rec["eval_shape_ok"] = True
    # Compile proxy: the committed flagship UNROLLS its layers for the
    # chip (XLA schedules across block boundaries — BIGLM_SWEEP.json
    # b8_none_unroll*), but a 12-layer-unrolled backward is minutes of
    # pure XLA:CPU compile on the 1-core test host for the same
    # order-of-magnitude temp estimate.  The proxy therefore compiles the
    # scanned twin (identical math; the scan body's buffers are reused
    # across layers, so its temp estimate is if anything OPTIMISTIC for
    # the unrolled program — recorded as such, and the chip_validated
    # override below is what actually admits the config to the chip).
    proxy_model = model
    if (config_name == "big_lm"
            and not getattr(model.cfg, "scan_layers", True)):
        import dataclasses as _dcp

        from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
            Transformer as _TP,
        )

        proxy_model = _TP(_dcp.replace(model.cfg, scan_layers=True))
        rec["compile_proxy_scan_layers"] = True
    proxy_step = (step if proxy_model is model
                  else dp.make_train_step(proxy_model, opt, mesh,
                                          cfg["loss"], "global_mean"))
    proxy_state = (state_shapes if proxy_model is model
                   else jax.eval_shape(
                       lambda: TrainState.create(proxy_model, opt,
                                                 prng.init_key(0))))
    t0 = time.perf_counter()
    compiled = jax.jit(proxy_step).lower(proxy_state, batch).compile()
    rec["cpu_compile_s"] = round(time.perf_counter() - t0, 1)
    temp_b = None
    try:
        ma = compiled.memory_analysis()
        temp_b = int(getattr(ma, "temp_size_in_bytes", 0)) or None
        rec["xla_cpu_memory_analysis"] = {
            "temp_bytes": temp_b,
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
            "generated_code_bytes": int(
                getattr(ma, "generated_code_size_in_bytes", 0)),
        }
    except Exception as e:  # noqa: BLE001 — analysis is best-effort
        rec["xla_cpu_memory_analysis"] = {"error": f"{type(e).__name__}: {e}"}
    rec["lower_compile_ok"] = True
    # steady-state residency: params + opt state + grads + XLA temp.  The
    # CPU temp number stands in for the TPU one (same order; the real
    # budget lands in BASELINE.md once the chip answers).
    known = param_b + opt_b + param_b + (temp_b or 0)
    rec["projected_hbm_bytes"] = known
    rec["fits_hbm"] = bool(temp_b is not None and known < hbm_bytes * 0.9)

    # -- 3b. sweep-candidate variants (tools/big_lm_sweep.py's MFU bets):
    # same compile + memory_analysis at the sweep's (batch, ce_chunk,
    # remat) points, DERIVED from the committed config (no hand-copied
    # shape literals — the committed model is the single source), so the
    # on-chip window never opens with an un-derisked candidate.  The CPU
    # proxy is known-pessimistic for no-remat rows (round-4 chip runs
    # executed b8 no-remat fine where the proxy read 17 GB), so fits_hbm
    # here informs, and the sweep's own OOM-tolerance decides.
    if config_name == "big_lm":
        import dataclasses as _dc

        from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
            Transformer as _T,
        )

        variants = []
        for vb, vchunk, vremat in ((8, 0, True), (8, 256, True),
                                   (16, 256, True),
                                   (8, 0, False), (8, 256, False)):
            vrow = {"batch": vb, "ce_chunk": vchunk, "remat": vremat}
            if (vb == cfg["batch"] and vchunk == model.cfg.ce_chunk
                    and vremat == model.cfg.remat):
                # byte-identical to the committed config compiled in
                # step 3 — reuse its measurement instead of paying the
                # most expensive CPU compile a second time
                vrow.update(temp_bytes=temp_b,
                            projected_hbm_bytes=known,
                            fits_hbm=rec["fits_hbm"])
                variants.append(vrow)
                continue
            # variants derive from the PROXY twin (scanned when the
            # committed config is unrolled — see step 3): same shape
            # classes, bounded CPU compile on the 1-core test host
            vmodel = _T(_dc.replace(proxy_model.cfg, ce_chunk=vchunk,
                                    remat=vremat))
            # abstract lowering: memory_analysis only needs shapes, so
            # skip materializing ~1.7 GB of real f32 state per variant
            vstate = jax.eval_shape(
                lambda m=vmodel: TrainState.create(m, opt, prng.init_key(0)))
            vstep = dp.make_train_step(vmodel, opt, mesh, cfg["loss"],
                                       "global_mean")
            vraw = cfg["make_batch"](rng, vb)
            vbatch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for k, v in vraw.items()}
            try:
                vcomp = jax.jit(vstep).lower(vstate, vbatch).compile()
                vtemp = int(getattr(vcomp.memory_analysis(),
                                    "temp_size_in_bytes", 0)) or None
                vknown = param_b + opt_b + param_b + (vtemp or 0)
                vrow.update(temp_bytes=vtemp, projected_hbm_bytes=vknown,
                            fits_hbm=bool(vtemp is not None
                                          and vknown < hbm_bytes * 0.9))
            except Exception as e:  # noqa: BLE001 — best-effort like 3.
                vrow["error"] = f"{type(e).__name__}: {e}"[:300]
            variants.append(vrow)
        rec["ce_chunk_variants"] = variants

    # -- 4. same-shape-class smoke (CPU f32, like bench_framework's CPU
    # path): every matmul shape class the chip will see, fewer layers
    smoke = dict(rec=None)
    if config_name == "big_lm":
        from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
            Transformer, TransformerConfig,
        )

        c = _BIG
        small = Transformer(TransformerConfig(
            vocab_size=c["vocab"], max_seq_len=c["seq"],
            n_layers=smoke_layers, d_model=c["d_model"],
            n_heads=c["n_heads"], d_ff=c["d_ff"],
            compute_dtype=jnp.float32, attention="flash", scan_layers=True))
        sstate = TrainState.create(small, opt, prng.init_key(0))
        sstate = dp.replicate_state(sstate, mesh)
        sstep = dp.make_train_step(small, opt, mesh, cfg["loss"],
                                   "global_mean")
        sraw = cfg["make_batch"](rng, smoke_batch)
        sbatch = shd.shard_batch(mesh, sraw)
        losses = []
        t0 = time.perf_counter()
        for _ in range(smoke_steps):
            sstate, loss = sstep(sstate, sbatch)
            losses.append(float(jax.device_get(loss)))
        smoke = {
            "layers": smoke_layers, "batch": smoke_batch,
            "steps": smoke_steps, "losses": [round(l, 4) for l in losses],
            "elapsed_s": round(time.perf_counter() - t0, 1),
            "ln_vocab": round(float(np.log(c["vocab"])), 4),
            "ok": bool(np.all(np.isfinite(losses))
                       and abs(losses[0] - np.log(c["vocab"])) < 1.0),
        }
    rec["smoke"] = smoke
    # fits_hbm gates the verdict: an over-budget config passing its
    # preflight would burn the scarce tunnel window on an on-chip OOM —
    # the exact failure this gate exists to prevent.  EXCEPTION: an actual
    # successful execution on the real chip is strictly stronger evidence
    # than the CPU buffer-assignment proxy (which round 4 measured to be
    # pessimistic for no-remat programs: 17 GB proxy vs a clean 163 ms
    # chip step).  If BIGLM_SWEEP.json carries a successful TPU row
    # matching the committed config, the proxy verdict is overridden and
    # recorded as chip_validated.
    rec["chip_validated"] = False
    if config_name == "big_lm":
        # a row only waives the HBM gate if every knob it was measured
        # at is STILL the committed configuration (shapes, batch, remat,
        # attention, ce_chunk, scan_layers, kernel tiles —
        # committed_big_lm_sweep_row; unstamped rows fall back to
        # LEGACY_SWEEP_SHAPES and cannot match a changed config)
        row = committed_big_lm_sweep_row(model.cfg, cfg["batch"])
        if row is not None:
            rec["chip_validated"] = True
            rec["chip_row"] = {k: row.get(k) for k in
                               ("label", "step_ms", "mfu")}
    rec["ok"] = bool(rec["eval_shape_ok"] and rec["lower_compile_ok"]
                     and (rec["fits_hbm"] or rec["chip_validated"])
                     and (smoke.get("ok", True)))
    _emit_artifact(out_path, rec)
    log(f"preflight[{config_name}] -> {out_path}")
    return rec


def bench_attention(out_path: str = "BENCH_ATTENTION.json") -> None:
    """Attention implementation comparison, two parts (VERDICT r2 item 3):

    1. **dense vs flash** (Pallas fwd + Mosaic bwd kernels) — full
       train-step time at growing sequence lengths.  On TPU the kernels are
       compiled and this is the real number; on the CPU fallback flash runs
       in Pallas *interpret mode* at one short length — timings there
       measure the emulation (marked ``interpret_mode: true``), but both
       columns are filled so the comparison machinery itself is proven.
    2. **ring vs ring_flash** — the same comparison with the sequence
       sharded over a 'seq' mesh axis (ring attention, with the local block
       compute dense or the Pallas kernel).  Needs >= 2 devices, so on a
       single-chip TPU these rows record a skip reason; the CPU fallback
       runs them on the virtual multi-device mesh.
    """
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
        spmd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel.sequence import (
        resolve_attention_impl,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    devices = jax.devices()
    n_dev = len(devices)
    on_tpu = devices[0].platform not in ("cpu",)
    cd = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.default_rng(0)
    sync = _chain_sync_every()

    def lm_cfg(seq, att, n_layers=2):
        return TransformerConfig(
            vocab_size=2048, max_seq_len=seq, n_layers=n_layers,
            d_model=256 if on_tpu else 128, n_heads=8, d_ff=1024 if on_tpu
            else 256, attention=att, compute_dtype=cd)

    def time_step(step, state, batch, n1, n2):
        _, state, _ = timed_chain(step, state, batch, 2, sync)  # compile
        best = None
        # min-of-k on the CPU fallback, same rationale as bench_framework
        # (single shared core, +-10% transient-load noise per window)
        for _rep in range(1 if on_tpu else _CPU_TIMING_REPS):
            t1, state, _ = timed_chain(step, state, batch, n1, sync)
            t2, state, _ = timed_chain(step, state, batch, n2, sync)
            ms = max(t2 - t1, 1e-9) / (n2 - n1) * 1e3
            best = ms if best is None else min(best, ms)
        return round(best, 3)

    results = []
    # ---- part 1: dense vs flash (DP mesh, full local sequence) ----
    mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev), devices=devices)
    n1, n2 = (10, 30) if on_tpu else (2, 6)
    # T >= 4k is where the flash kernel's O(T) memory beats dense's
    # materialized (B, H, T, T) scores (VERDICT r3 item 3: measure the
    # claim, don't state it); 8k is flash-only — dense's quadratic HBM
    # traffic makes it a strawman there, so the row records flash alone
    for seq in ((512, 1024, 2048, 4096, 8192) if on_tpu else (128,)):
        b = max(1, (8192 if on_tpu else 256) // seq)
        b = ((b + n_dev - 1) // n_dev) * n_dev  # rows divide the data axes
        row = {"seq": seq, "batch": b, "mode": "dense_vs_flash"}
        if not on_tpu:
            row["interpret_mode"] = True  # flash = Pallas emulation on CPU
        # "auto" is the framework default (VERDICT r4 item 3): the row
        # proves the dispatch table picks the winner at every swept T —
        # auto_ms should track min(dense_ms, flash_ms) within noise
        impls = (("dense", "flash", "auto") if seq <= 4096
                 else ("flash", "auto"))
        if seq > 4096:
            row["dense_skipped"] = "quadratic scores tensor at 8k"
        for att in impls:
            model = Transformer(lm_cfg(seq, att))
            opt = optim.sgd(lr=1e-4, momentum=0.9)
            state = dp.replicate_state(
                TrainState.create(model, opt, prng.init_key(0)), mesh)
            step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                                      "global_mean")
            batch = shd.shard_batch(mesh, {
                "x": rng.integers(0, 2048, (b, seq)).astype(np.int32),
                "y": rng.integers(0, 2048, (b, seq)).astype(np.int32),
                "mask": np.ones((b,), np.float32)})
            row[f"{att}_ms"] = time_step(step, state, batch, n1, n2)
        if row.get("dense_ms") and row.get("flash_ms"):
            row["flash_speedup"] = round(row["dense_ms"] / row["flash_ms"], 3)
        if row.get("auto_ms"):
            row["auto_resolved"] = resolve_attention_impl(
                "auto", seq, "tpu" if on_tpu else "cpu")
            best = min(v for k_, v in row.items()
                       if k_ in ("dense_ms", "flash_ms"))
            row["auto_vs_best"] = round(row["auto_ms"] / best, 3)
        log(f"[attention] {row}")
        results.append(row)

    # ---- part 1b: KERNEL-ONLY dense vs flash (fwd + bwd of the bare
    # attention op).  The full-step rows above dilute the kernel's win
    # with embed/FFN/head/optimizer time; this isolates the op the Pallas
    # kernel actually replaces, which is where the O(T) vs O(T^2) memory
    # story lives.  -------------------------------------------------------
    from neural_networks_parallel_training_with_mpi_tpu.parallel.sequence import (
        sequence_sharded_attention,
    )

    h_k, dh_k = 8, 64
    for seq in ((1024, 2048, 4096, 8192) if on_tpu else (256,)):
        b = max(1, (8192 if on_tpu else 512) // seq)
        row = {"seq": seq, "batch": b, "heads": h_k, "head_dim": dh_k,
               "mode": "attn_kernel_only"}
        if not on_tpu:
            row["interpret_mode"] = True
        qkv = [jnp.asarray(rng.standard_normal((b, seq, h_k, dh_k)),
                           cd) for _ in range(3)]
        for att in ("dense", "flash"):
            def loss_fn(q, k, v, _att=att):
                out = sequence_sharded_attention(_att, q, k, v,
                                                 causal=True)
                return jnp.sum(out.astype(jnp.float32))

            g = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))
            g(*qkv)[0].block_until_ready()  # compile
            n = 20 if on_tpu else 3
            t0 = time.perf_counter()
            for _ in range(n):
                outs = g(*qkv)
            jax.block_until_ready(outs)
            row[f"{att}_ms"] = round((time.perf_counter() - t0) / n * 1e3,
                                     3)
        row["flash_speedup"] = round(row["dense_ms"] / row["flash_ms"], 3)
        log(f"[attention] {row}")
        results.append(row)

    # ---- part 2: ring vs ring_flash (sequence sharded over 'seq') ----
    sp = min(4, n_dev)
    if sp < 2:
        results.append({"mode": "ring_vs_ring_flash", "skipped":
                        f"needs >= 2 devices for the 'seq' axis, have "
                        f"{n_dev} (single tunneled chip)"})
    else:
        seq = 1024 if on_tpu else 256
        b = 4 if on_tpu else 2
        smesh = mesh_lib.make_mesh(MeshConfig(data=1, seq=sp),
                                   devices=devices[:sp])
        row = {"seq": seq, "batch": b, "seq_shards": sp,
               "mode": "ring_vs_ring_flash"}
        if not on_tpu:
            row["interpret_mode"] = True
        # striped_flash: balanced causal blocks (every device does half
        # work every tick) — the wall-clock fix for lockstep causal rings;
        # expected ~2x over ring_flash at scale on real chips
        for att in ("ring", "ring_flash", "striped_flash"):
            model = Transformer(lm_cfg(seq, att))
            opt = optim.sgd(lr=1e-4, momentum=0.9)
            state = jax.device_put(
                TrainState.create(model, opt, prng.init_key(0)),
                jax.sharding.NamedSharding(
                    smesh, jax.sharding.PartitionSpec()))
            placed = spmd.place_batch(smesh, {
                "x": rng.integers(0, 2048, (b, seq)).astype(np.int32),
                "y": rng.integers(0, 2048, (b, seq)).astype(np.int32),
                "mask": np.ones((b,), np.float32)}, "seq")
            step = spmd.make_spmd_train_step(
                model, opt, smesh, "cross_entropy", seq_axis="seq",
                donate=False, example_batch=placed)
            row[f"{att}_ms"] = time_step(step, state, placed, n1, n2)
        if row.get("ring_ms") and row.get("ring_flash_ms"):
            row["ring_flash_speedup"] = round(
                row["ring_ms"] / row["ring_flash_ms"], 3)
        if row.get("ring_flash_ms") and row.get("striped_flash_ms"):
            row["striped_vs_ring_flash"] = round(
                row["ring_flash_ms"] / row["striped_flash_ms"], 3)
        log(f"[attention] {row}")
        results.append(row)

    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, {
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "note": ("compiled kernels" if on_tpu else
                 "CPU fallback: flash/ring_flash run in Pallas "
                 "interpret mode — fills the comparison columns "
                 "but measures the emulation, not kernel perf"),
        "results": results})
    log(f"attention comparison -> {out_path}")
    return out_path


def _divert_cpu_overwrite(out_path: str, on_tpu: bool) -> str:
    """Never clobber a real-chip artifact with a CPU-fallback run: when the
    current run is cpu and ``out_path`` holds platform != cpu, divert to
    ``<stem>_CPU.json`` (same rule BENCH_FULL.json applies inline)."""
    if on_tpu:
        return out_path
    try:
        with open(out_path) as f:
            prior = json.load(f)
        if isinstance(prior, dict) and prior.get("platform") not in (None,
                                                                     "cpu"):
            diverted = out_path.replace(".json", "_CPU.json")
            log(f"{out_path} holds a real-chip run; cpu fallback writes "
                f"{diverted}")
            return diverted
    except (OSError, ValueError):
        pass
    return out_path


def _cpu_child_env(n_devices: int) -> dict:
    """The one place the CPU-child launch env is assembled (plugin env
    stripping + platform pin + virtual device count) — every bench child
    (scaling sweep, fallback retry, attention) goes through it."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    plat.force_host_device_count(n_devices, env=env)
    return env


def _run_flag_cpu_child(flag: str, n_devices: int,
                        timeout: float = 1800, extra=None):
    """Run a comparison sub-benchmark (--attention-inproc /
    --decode-inproc) in a CPU child with a virtual multi-device mesh: the
    fallback parent has a single device, but ring/tensor axes need >= 2.
    Returns the artifact path the child reports (possibly a ``*_CPU.json``
    diversion — the parent must relay the TRUE path, or a watcher reading
    the pointer would mark a cpu run as a chip capture), or None."""
    env = _cpu_child_env(n_devices)
    cmd = [sys.executable, __file__, flag, "--platform", "cpu"]
    cmd += list(extra or [])
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        log(f"[{flag} child] timed out after {timeout:.0f}s")
        return None
    if out.returncode != 0:
        log(f"[{flag} child] FAILED:\n{out.stderr[-2000:]}")
        return None
    for line in out.stderr.strip().splitlines():
        if "->" in line or "[attention]" in line:
            log(line)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return (doc.get("attention_artifact")
                    or doc.get("decode_artifact")
                    or doc.get("serve_artifact")
                    or doc.get("serve_fleet_artifact")
                    or doc.get("serve_disagg_artifact")
                    or doc.get("ctrlplane_artifact")
                    or doc.get("paged_attn_artifact")
                    or doc.get("rl_artifact")
                    or doc.get("update_sharding_artifact")
                    or doc.get("trace_artifact")
                    or doc.get("obs_artifact")
                    or doc.get("prefix_cache_artifact")
                    or doc.get("quant_artifact"))
    return None


def bench_decode(out_path: str = "BENCH_DECODE.json") -> None:
    """Serving throughput: KV-cache decode tokens/sec for the three decode
    paths — single-stream dense (`models.generate`), batch-parallel
    sharded (`generate_sharded`, params replicated / rows sharded), and
    tensor-parallel native (`generate_tp`, Megatron blocks + head-sharded
    caches + vocab-parallel sampling).  On the CPU fallback this is a
    mechanism check at tiny shapes; on TPU the numbers are real."""
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models import (
        Transformer, TransformerConfig, generate, generate_sharded,
        generate_tp,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        megatron,
        mesh as mesh_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    devices = jax.devices()
    n_dev = len(devices)
    on_tpu = devices[0].platform not in ("cpu",)
    cd = jnp.bfloat16 if on_tpu else jnp.float32
    c = (_LM if on_tpu else
         dict(vocab=256, seq=128, d_model=128, n_layers=2, n_heads=8,
              d_ff=256))
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=c["seq"], n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=cd))
    params = model.init(prng.init_key(0))
    rng = np.random.default_rng(0)
    new_tokens = 64 if on_tpu else 16
    p_len = 16 if on_tpu else 8

    def time_decode(fn, batch, vocab=None):
        prompt = jnp.asarray(rng.integers(0, vocab or c["vocab"],
                                          (batch, p_len)), jnp.int32)
        # sync the warmup: async dispatch would bleed the compile/first-run
        # into the (single, on TPU) timed rep
        jax.block_until_ready(fn(prompt))
        best = None
        for _ in range(1 if on_tpu else _CPU_TIMING_REPS):
            t0 = time.perf_counter()
            out = fn(prompt)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return round(batch * new_tokens / best, 1)

    results = {"new_tokens": new_tokens, "prompt_len": p_len,
               "n_devices": n_dev}
    jitted = jax.jit(lambda pr: generate(model, params, pr, new_tokens))
    results["dense_tokens_per_sec"] = time_decode(jitted, 4)
    # weights-only int8 PTQ (ops.quant): same decode program, kernels
    # stored int8 + per-out-channel scales — the decode loop is HBM-bound
    # streaming the weights once per token, so on-chip this row should
    # approach 2x dense-bf16; on the CPU fallback it is a mechanism check
    # (numerics parity is pinned by tests/test_quant.py)
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params, quantized_bytes,
    )

    qparams = quantize_params(params)
    jitted_q = jax.jit(lambda pr: generate(model, qparams, pr, new_tokens))
    results["dense_int8_tokens_per_sec"] = time_decode(jitted_q, 4)
    results["int8_param_bytes"] = quantized_bytes(qparams)
    results["full_param_bytes"] = quantized_bytes(params)
    # grouped-query attention (n_kv_heads = heads/4): the KV cache — what
    # decode re-streams EVERY step, growing with context — shrinks 4x.
    # A different model (smaller kv projection), so this is a config
    # comparison at equal d_model/layers, not a same-weights ablation;
    # the int8 row stacks both serving levers.
    gq = max(1, c["n_heads"] // 4)
    model_gqa = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=c["seq"], n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], n_kv_heads=gq,
        d_ff=c["d_ff"], compute_dtype=cd))
    params_gqa = model_gqa.init(prng.init_key(0))
    results["gqa_kv_heads"] = gq
    results["gqa_tokens_per_sec"] = time_decode(
        jax.jit(lambda pr: generate(model_gqa, params_gqa, pr,
                                    new_tokens)), 4)
    qparams_gqa = quantize_params(params_gqa)
    results["gqa_int8_tokens_per_sec"] = time_decode(
        jax.jit(lambda pr: generate(model_gqa, qparams_gqa, pr,
                                    new_tokens)), 4)
    # int8 KV cache (generate(kv_quant=True)): the third serving lever —
    # the cache is what decode RE-streams every step, growing with
    # context; all three stack in the last row
    results["dense_kv8_tokens_per_sec"] = time_decode(
        jax.jit(lambda pr: generate(model, params, pr, new_tokens,
                                    kv_quant=True)), 4)
    results["gqa_int8_kv8_tokens_per_sec"] = time_decode(
        jax.jit(lambda pr: generate(model_gqa, qparams_gqa, pr,
                                    new_tokens, kv_quant=True)), 4)
    # continuous batching (models.serve): ragged requests sharing one
    # batched step.  Run the same workload twice — the first pass pays
    # every compile (log2-bucketed prefills + the step), the second is
    # the steady-state number a serving loop sees.
    from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
        DecodeServer,
    )

    def serve_pass():
        srv = DecodeServer(model, params, slots=4, max_len=c["seq"])
        lens = [3, 7, 12, 5, 9, 4, 14, 6]
        pending = [(list(rng.integers(0, c["vocab"], (p,))), new_tokens)
                   for p in lens]
        done_tok = 0
        t0 = time.perf_counter()
        rids = []
        while pending or rids:
            while pending:
                rid = srv.submit(*pending[0])
                if rid is None:
                    break
                rids.append((rid, pending.pop(0)[1]))
            srv.step()
            for rid, n in list(rids):
                if srv.done(rid):
                    srv.result(rid)
                    done_tok += n
                    rids.remove((rid, n))
        return round(done_tok / (time.perf_counter() - t0), 1)

    serve_pass()  # compile pass (prefill buckets + batched step)
    results["serve_requests"] = 8
    results["serve_slots"] = 4
    results["serve_tokens_per_sec"] = serve_pass()
    # greedy speculative decoding: a 1-layer draft of the same family
    # proposes k=4, the full model verifies in one chunk — tokens are
    # EXACT (tests/test_speculative.py), so the only question is the
    # accept rate and the wall-clock vs plain decode
    from neural_networks_parallel_training_with_mpi_tpu.models.speculative import (
        speculative_generate,
    )

    draft = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=c["seq"], n_layers=1,
        d_model=c["d_model"] // 2, n_heads=c["n_heads"],
        d_ff=c["d_ff"] // 2, compute_dtype=cd))
    draft_params = draft.init(prng.init_key(1))
    spec_prompt = jnp.asarray(rng.integers(0, c["vocab"], (4, p_len)),
                              jnp.int32)
    speculative_generate(model, params, draft, draft_params, spec_prompt,
                         new_tokens, k=4)     # compile pass
    t0 = time.perf_counter()
    _, spec_stats = speculative_generate(model, params, draft,
                                         draft_params, spec_prompt,
                                         new_tokens, k=4)
    dt = time.perf_counter() - t0
    results["speculative_tokens_per_sec"] = round(
        4 * new_tokens / dt, 1)
    results["speculative_accept_rate"] = round(
        spec_stats["accept_rate"], 3)
    results["speculative_target_passes"] = spec_stats["target_passes"]
    # the bench models are UNTRAINED, so the real-draft accept rate is
    # meaningless (unrelated random argmaxes -> ~0, the worst case);
    # the self-draft row shows the mechanism's ceiling: accept rate 1,
    # 1 + ceil((N-1)/(k+1)) target passes instead of N
    speculative_generate(model, params, model, params, spec_prompt,
                         new_tokens, k=4)     # compile pass
    t0 = time.perf_counter()
    _, self_stats = speculative_generate(model, params, model, params,
                                         spec_prompt, new_tokens, k=4)
    results["speculative_selfdraft_tokens_per_sec"] = round(
        4 * new_tokens / (time.perf_counter() - t0), 1)
    results["speculative_selfdraft_target_passes"] = (
        self_stats["target_passes"])
    # the single-program DEVICE path (round 5): same acceptance, zero
    # host traffic — on the tunneled chip (~65 ms host round trip per
    # dispatch) this is where the lever lives.  Self-draft shows the
    # orchestration ceiling at accept 1; the trained-pair eval
    # (BENCH_DECODE_SPEC*.json) owns the realistic-accept rows.
    from neural_networks_parallel_training_with_mpi_tpu.models.speculative import (
        speculative_generate_device,
    )

    speculative_generate_device(model, params, draft, draft_params,
                                spec_prompt, new_tokens, k=4)  # compile
    t0 = time.perf_counter()
    _, dev_stats = speculative_generate_device(model, params, draft,
                                               draft_params, spec_prompt,
                                               new_tokens, k=4)
    results["speculative_device_tokens_per_sec"] = round(
        4 * new_tokens / (time.perf_counter() - t0), 1)
    results["speculative_device_target_passes"] = (
        dev_stats["target_passes"])
    speculative_generate_device(model, params, model, params, spec_prompt,
                                new_tokens, k=4)  # compile
    t0 = time.perf_counter()
    _, sd_stats = speculative_generate_device(model, params, model,
                                              params, spec_prompt,
                                              new_tokens, k=4)
    results["speculative_device_selfdraft_tokens_per_sec"] = round(
        4 * new_tokens / (time.perf_counter() - t0), 1)
    results["speculative_device_selfdraft_target_passes"] = (
        sd_stats["target_passes"])
    if n_dev >= 2:
        from neural_networks_parallel_training_with_mpi_tpu.parallel.sharding import (
            replicated_sharding,
        )

        dmesh = mesh_lib.make_mesh(MeshConfig(data=n_dev), devices=devices)
        # place params ONCE outside the timed loop (generate_sharded's own
        # device_put is then a no-op) — the dense path bakes params into
        # its jitted closure, so the comparison must not charge the
        # sharded paths a per-call weight broadcast
        params_repl = jax.device_put(params, replicated_sharding(dmesh))
        results["sharded_batch"] = 4 * n_dev
        results["sharded_tokens_per_sec"] = time_decode(
            lambda pr: generate_sharded(model, params_repl, pr, dmesh,
                                        new_tokens), 4 * n_dev)
    if n_dev >= 4 and c["n_heads"] % 2 == 0:
        from jax.sharding import NamedSharding

        from neural_networks_parallel_training_with_mpi_tpu.parallel.spmd import (
            sp_tp_param_specs,
        )

        tmesh = mesh_lib.make_mesh(MeshConfig(data=n_dev // 2, tensor=2),
                                   devices=devices)
        tpp = dict(params)
        tpp["blocks"] = megatron.permute_qkv(params["blocks"], c["d_model"],
                                             c["n_heads"], 2)
        tspecs = sp_tp_param_specs(tpp, vocab_parallel=True)
        tpp = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(tmesh, s)), tpp,
            tspecs)
        results["tp_batch"] = 2 * (n_dev // 2)
        results["tp_tokens_per_sec"] = time_decode(
            lambda pr: generate_tp(model, tpp, pr, tmesh, new_tokens,
                                   vocab_parallel=True), 2 * (n_dev // 2))
    # --- the TP-wins regime (VERDICT r3 item 8): EQUAL global batch,
    # latency-bound, wide model slice.  The throughput rows above give
    # every path its own best batch (dense-replicated rows scale with n,
    # so TP "loses" 4x by construction at tiny shapes).  Serving's
    # latency-bound question is different: a FIXED small request batch on
    # the same n devices — replicate the model and give each device
    # M = B/n rows of full-width matmuls, or TP-cooperate with
    # M = B/(n/tp) rows of 1/tp-width matmuls + psums?  At d_model 1024
    # the wide slice wins even on the single-core CPU mesh (the M=1
    # full-width GEMV is a worse program than the M=2 half-width GEMM by
    # more than two psums/layer cost); on chips the same regime is where
    # TP serving lives, with the additional 1/tp weight-streaming
    # advantage per device that a bandwidth-bound decode enjoys.
    if n_dev >= 4:
        cw = dict(vocab=c["vocab"], seq=p_len + new_tokens, d_model=1024,
                  n_heads=16, d_ff=2048, n_layers=2)
        model_w = Transformer(TransformerConfig(
            vocab_size=cw["vocab"], max_seq_len=cw["seq"],
            n_layers=cw["n_layers"], d_model=cw["d_model"],
            n_heads=cw["n_heads"], d_ff=cw["d_ff"], compute_dtype=cd))
        params_w = model_w.init(prng.init_key(1))
        B_eq = n_dev
        eq = {"global_batch": B_eq, "d_model": cw["d_model"],
              "n_layers": cw["n_layers"]}
        dmesh = mesh_lib.make_mesh(MeshConfig(data=n_dev), devices=devices)
        from neural_networks_parallel_training_with_mpi_tpu.parallel.sharding import (
            replicated_sharding,
        )

        pw_repl = jax.device_put(params_w, replicated_sharding(dmesh))
        eq["dense_replicated_tokens_per_sec"] = time_decode(
            lambda pr: generate_sharded(model_w, pw_repl, pr, dmesh,
                                        new_tokens), B_eq, vocab=cw["vocab"])
        from jax.sharding import NamedSharding

        from neural_networks_parallel_training_with_mpi_tpu.parallel.spmd import (
            sp_tp_param_specs,
        )

        tmesh = mesh_lib.make_mesh(MeshConfig(data=n_dev // 2, tensor=2),
                                   devices=devices)
        tpw = dict(params_w)
        tpw["blocks"] = megatron.permute_qkv(params_w["blocks"],
                                             cw["d_model"], cw["n_heads"], 2)
        tspecs = sp_tp_param_specs(tpw, vocab_parallel=True)
        tpw = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(tmesh, s)), tpw,
            tspecs)
        eq["tp_tokens_per_sec"] = time_decode(
            lambda pr: generate_tp(model_w, tpw, pr, tmesh, new_tokens,
                                   vocab_parallel=True), B_eq,
            vocab=cw["vocab"])
        eq["tp_speedup"] = round(eq["tp_tokens_per_sec"]
                                 / eq["dense_replicated_tokens_per_sec"], 3)
        eq["tp_wins"] = bool(eq["tp_speedup"] > 1.0)
        results["equal_batch_latency_regime"] = eq

    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    if not on_tpu:
        results["note"] = ("CPU fallback mechanism check; the throughput "
                           "rows use tiny shapes, the equal-batch regime "
                           "the wide (d=1024) slice where TP wins")
    # read the prior artifact BEFORE any cpu-diversion rewrites out_path —
    # the carry-forward must see the real-chip file, not the diverted name
    try:
        with open(out_path) as f:
            prior_doc = json.load(f)
    except (OSError, ValueError):
        prior_doc = None
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    if n_dev < 4:
        # the sharded/TP rows and the equal-batch TP-wins regime (VERDICT
        # r3 item 8) need a multi-device mesh; a single tunneled chip
        # cannot re-measure them.  Carry the prior artifact's regime
        # forward with provenance instead of silently dropping the
        # documented evidence (same pattern as BENCH_TPU_LATEST reuse).
        results["multi_device_rows_skipped"] = (
            f"sharded/TP decode and the equal-batch regime need >= 4 "
            f"devices, have {n_dev}")
        try:
            if prior_doc is None:
                raise OSError("no prior artifact")
            prior = prior_doc
            eq = prior.get("equal_batch_latency_regime")
            if eq is None:
                eq = (prior.get("prior_equal_batch_latency_regime") or
                      {}).get("regime")
                prior = (prior.get("prior_equal_batch_latency_regime")
                         or {})
            if eq is not None:
                results["prior_equal_batch_latency_regime"] = {
                    "regime": eq,
                    "platform": prior.get("platform"),
                    "n_devices": prior.get("n_devices"),
                    "note": "carried forward from the last multi-device "
                            "run; not re-measured on this single-chip "
                            "capture",
                }
        except (OSError, ValueError):
            pass
    _emit_artifact(out_path, results)
    log(f"decode comparison -> {out_path}: {results}")
    return out_path


def bench_update_sharding(out_path: str = "BENCH_UPDATE_SHARDING.json",
                          reps: int = 3, chain: int = 2) -> str:
    """Interleaved A/B of the replicated vs automatic-sharded weight
    update (ROADMAP item 2; parallel.update_sharding) at the CPU-bench
    transformer scale (DESIGN §7's 4L/d256/T128 — the _LM config at
    seq 128), on the full virtual-device DP mesh.  Three arms:

      replicated            the baseline full-psum update
      sharded               per-leaf reduce-scatter -> 1/N update ->
                            all-gather (update_sharding='sharded')
      sharded_bf16_master   the same plus bf16 param storage with f32
                            master weights in the sharded opt state
                            (--param_dtype bfloat16 --master_weights)

    Methodology: interleaved pairs (DESIGN §7 — grouping all A reps
    before all B reps on the single shared core lets one load spike
    masquerade as a delta); per-arm best-of-k and median step_ms.  The
    SPEED claim on this host is only "no worse" — XLA:CPU serializes
    every virtual device on one core, so the reduce-scatter's bandwidth
    win cannot show as wall time; the win is claimed in (a) the
    analytic per-device optimizer-state bytes (~1/N, exact) and (b) the
    compiled-HLO overlap evidence (per-leaf reduce-scatters interleaved
    with backward matmuls — ``collective_report``), plus the donation
    audit (every state leaf aliased in/out).
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
        update_sharding as us,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.train.telemetry import (
        telemetry_peak_flops, train_step_flops,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng
    from neural_networks_parallel_training_with_mpi_tpu.utils.profiling import (
        donation_report,
    )

    c = _LM
    seq, batch_size = 128, 32
    devices = jax.devices()
    n = len(devices)
    mesh = mesh_lib.make_mesh(MeshConfig(data=n), devices=devices)
    on_tpu = devices[0].platform not in ("cpu",)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    base_cfg = TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=seq, n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=compute_dtype)
    rng = np.random.default_rng(0)
    raw = {
        "x": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "y": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "mask": np.ones((batch_size,), np.float32),
    }
    batch = shd.shard_batch(mesh, raw)
    sync = _chain_sync_every()

    def tree_bytes(tree, per_device=False):
        total = 0
        for l in jax.tree_util.tree_leaves(tree):
            shape = (l.addressable_shards[0].data.shape if per_device
                     else l.shape)
            total += int(np.prod(shape) or 1) * l.dtype.itemsize
        return total

    def build(mode):
        m_cfg = base_cfg
        opt = optim.sgd(lr=1e-4, momentum=0.9)
        if mode == "sharded_bf16_master":
            m_cfg = _dc.replace(base_cfg, param_dtype=jnp.bfloat16)
            opt = optim.with_master_weights(opt)
        model = Transformer(m_cfg)
        if mode == "replicated":
            state = dp.replicate_state(
                TrainState.create(model, opt, prng.init_key(0)), mesh)
            step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                                      "global_mean")
        else:
            params = model.init(prng.init_key(0))
            plan = us.plan_updates(params, n)
            host = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=us.init_opt_state(opt, params, plan))
            state = us.place_state(host, mesh, opt, plan)
            step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                                      "global_mean",
                                      update_sharding="sharded",
                                      update_plan=plan)
        compiled = step.lower(state, batch).compile()
        hlo_text = compiled.as_text()  # rendered once, tens of MB
        arm = {
            "model": model,
            "comp": compiled,
            "state": state,
            "param_bytes": tree_bytes(state.params),
            "opt_bytes_total": tree_bytes(state.opt_state),
            "opt_bytes_per_device": tree_bytes(state.opt_state,
                                               per_device=True),
            "hlo": us.collective_report(hlo_text),
            "donation": {
                k: v for k, v in donation_report(
                    compiled, hlo_text=hlo_text).items()
                if k != "aliased"},
            "n_state_leaves": len(jax.tree_util.tree_leaves(state)),
        }
        try:
            ma = compiled.memory_analysis()
            arm["xla_temp_bytes"] = int(
                getattr(ma, "temp_size_in_bytes", 0)) or None
        except Exception:  # noqa: BLE001 — analysis is best-effort
            arm["xla_temp_bytes"] = None
        return arm

    arms = {name: build(name)
            for name in ("replicated", "sharded", "sharded_bf16_master")}
    # warmup every arm once, then INTERLEAVED pairs (DESIGN §7)
    for a in arms.values():
        _, a["state"], _ = timed_chain(a["comp"], a["state"], batch, 1, sync)
    times = {name: [] for name in arms}
    loss_vals = {}
    for _rep in range(reps):
        for name, a in arms.items():
            dt, a["state"], loss_vals[name] = timed_chain(
                a["comp"], a["state"], batch, chain, sync)
            times[name].append(dt / chain)
    flops = train_step_flops(arms["replicated"]["model"], raw["x"].shape)
    peak = telemetry_peak_flops(devices[0].device_kind,
                                devices[0].platform) * n
    rec = {
        "metric": "update_sharding_ab",
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n,
        "batch": batch_size,
        "model": {"n_layers": c["n_layers"], "d_model": c["d_model"],
                  "d_ff": c["d_ff"], "seq": seq, "vocab": c["vocab"]},
        "reps": reps, "chain_steps": chain,
        "mfu_denominator": ("chip_peak" if on_tpu
                            else "nominal_cpu_peak (NNPT_PEAK_FLOPS)"),
        "arms": {},
    }
    base_opt = arms["replicated"]["opt_bytes_per_device"]
    base_best = min(times["replicated"])
    for name, a in arms.items():
        best = min(times[name])
        med = float(np.median(times[name]))
        # per-PAIR ratios (each rep's arms ran adjacent in time, so the
        # ratio within a rep cancels slow host-load drift the way the
        # best-of-k comparison cannot)
        pair_ratios = [t / b for t, b in zip(times[name],
                                             times["replicated"])]
        assert np.isfinite(loss_vals[name]), (name, loss_vals[name])
        rec["arms"][name] = {
            "step_ms_best": round(best * 1e3, 2),
            "step_ms_median": round(med * 1e3, 2),
            "step_vs_replicated_best": round(best / base_best, 4),
            "pair_ratio_median": round(float(np.median(pair_ratios)), 4),
            "final_loss": round(float(loss_vals[name]), 5),
            "param_bytes": a["param_bytes"],
            "opt_bytes_total": a["opt_bytes_total"],
            "opt_bytes_per_device": a["opt_bytes_per_device"],
            "opt_per_device_vs_replicated": round(
                a["opt_bytes_per_device"] / base_opt, 4),
            "xla_temp_bytes": a["xla_temp_bytes"],
            "hlo": a["hlo"],
            "donation": a["donation"],
            "n_state_leaves": a["n_state_leaves"],
            "mfu": round(flops / best / peak, 4),
        }
        log(f"[update-sharding {name}] best {best * 1e3:.1f} ms/step "
            f"(median {med * 1e3:.1f}), opt state "
            f"{a['opt_bytes_per_device'] / 2**20:.1f} MiB/device "
            f"({a['opt_bytes_per_device'] / base_opt:.2f}x replicated), "
            f"HLO {a['hlo']['counts']}")
    rec["note"] = (
        "interleaved A/B pairs on the shared-core CPU host: wall-time "
        "parity is the claim here (XLA:CPU serializes the virtual "
        "devices, so the reduce-scatter bandwidth win cannot show); the "
        "win is opt_bytes_per_device ~1/n_devices (analytic, exact) + "
        "the HLO overlap evidence (per-leaf reduce-scatters interleaved "
        "with backward dots) + bf16 param storage halving param bytes "
        "with f32 masters costing 1/n_devices")
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, rec)
    log(f"update-sharding A/B -> {out_path}")
    return out_path


def bench_quant_ab(out_path: str = "BENCH_QUANT.json",
                   reps: int = 3, chain: int = 2,
                   curve_steps: int = 12) -> str:
    """Interleaved A/B of the quantized-matmul seam (ops.qmm, ROADMAP
    item 5, DESIGN §14) at the CPU-bench transformer scale — the
    BENCH_UPDATE_SHARDING discipline (DESIGN §7: per-rep adjacent pairs
    so shared-core load drift cancels in the ratio).  Two experiments:

    * **train**: bf16 vs fp8 (e4m3/e5m2 qdot + delayed scaling) vs int8
      (dynamic symmetric qdot) on the full virtual-device DP mesh —
      step-time pairs AND a ``curve_steps``-step loss curve per arm with
      the PARITY BOUND embedded as a boolean (max per-step |loss_arm -
      loss_bf16| within the documented envelope).  On this host the
      SPEED claim is only "no worse": XLA:CPU has no int8/fp8 MXU — the
      quantized dots emulate through int32/f32 units, so the arithmetic-
      rate win (the whole point of the seam) is claimable only from the
      TPU's int8/fp8:bf16 throughput ratio; what the CPU numbers pin is
      the numerics envelope and that the seam's overhead (quantize +
      scale folds + amax state) does not blow up the step.
    * **serve**: greedy decode tokens/s, int8 PTQ (dequant-then-
      compute-dtype dot — the pre-seam path) vs int8 COMPUTE
      (``matmul_dtype='int8'``: true int8 activation x weight dot,
      dynamic per-token activation scales) over the same quantized
      params, with ``tokens_exact`` comparing the two arms' greedy
      tokens on the bench prompts.
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.generate import (
        generate,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.ops.quant import (
        quantize_params,
    )
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    # loss-curve parity envelope at this scale (max per-step |delta| vs
    # the bf16 arm over curve_steps fresh-init steps).  fp8's e4m3
    # mantissa and int8's per-channel rounding both land well inside
    # this on the 4L/d256 config; a regression (bad scales, saturation)
    # blows through it immediately.
    LOSS_ENVELOPE = 0.08

    c = _LM
    seq, batch_size = 128, 32
    devices = jax.devices()
    n = len(devices)
    mesh = mesh_lib.make_mesh(MeshConfig(data=n), devices=devices)
    on_tpu = devices[0].platform not in ("cpu",)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    base_cfg = TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=seq, n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=compute_dtype)
    rng = np.random.default_rng(0)
    raw = {
        "x": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "y": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "mask": np.ones((batch_size,), np.float32),
    }
    batch = shd.shard_batch(mesh, raw)
    sync = _chain_sync_every()

    def build(fmt):
        model = Transformer(_dc.replace(base_cfg, matmul_dtype=fmt))
        opt = optim.sgd(lr=1e-4, momentum=0.9)
        state = dp.replicate_state(
            TrainState.create(model, opt, prng.init_key(0)), mesh)
        step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                                  "global_mean")
        return {"model": model, "opt": opt, "step": step, "state": state}

    arms = {fmt: build(fmt) for fmt in ("bf16", "fp8", "int8")}
    # warmup (compile) once per arm, then INTERLEAVED pairs (DESIGN §7)
    for a in arms.values():
        _, a["state"], _ = timed_chain(a["step"], a["state"], batch, 1,
                                       sync)
    times = {name: [] for name in arms}
    for _rep in range(reps):
        for name, a in arms.items():
            dt, a["state"], _ = timed_chain(a["step"], a["state"], batch,
                                            chain, sync)
            times[name].append(dt / chain)

    # fresh-init loss curves for the parity bound (separate from the
    # timing states, whose step counts the interleaving staggered)
    curves = {}
    for fmt, a in arms.items():
        state = dp.replicate_state(
            TrainState.create(a["model"], a["opt"], prng.init_key(0)),
            mesh)
        ls = []
        for _ in range(curve_steps):
            state, loss = a["step"](state, batch)
            ls.append(float(loss))
        curves[fmt] = ls

    rec = {
        "metric": "quant_ab",
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n,
        "batch": batch_size,
        "model": {"n_layers": c["n_layers"], "d_model": c["d_model"],
                  "d_ff": c["d_ff"], "seq": seq, "vocab": c["vocab"]},
        "reps": reps, "chain_steps": chain,
        "curve_steps": curve_steps,
        "loss_envelope": LOSS_ENVELOPE,
        "train": {},
    }
    base_best = min(times["bf16"])
    for fmt in arms:
        best = min(times[fmt])
        pair_ratios = [t / b for t, b in zip(times[fmt], times["bf16"])]
        deltas = [abs(a - b) for a, b in zip(curves[fmt], curves["bf16"])]
        rec["train"][fmt] = {
            "step_ms_best": round(best * 1e3, 2),
            "step_ms_median": round(float(np.median(times[fmt])) * 1e3, 2),
            "step_vs_bf16_best": round(best / base_best, 4),
            "pair_ratio_median": round(float(np.median(pair_ratios)), 4),
            "loss_curve": [round(l, 5) for l in curves[fmt]],
            "loss_max_abs_delta_vs_bf16": round(max(deltas), 5),
            "loss_parity_within_envelope": bool(max(deltas)
                                                <= LOSS_ENVELOPE),
            "all_losses_finite": bool(np.all(np.isfinite(curves[fmt]))),
        }
        log(f"[quant-ab train {fmt}] best {best * 1e3:.1f} ms/step, "
            f"pair-ratio median "
            f"{rec['train'][fmt]['pair_ratio_median']}, loss delta "
            f"{rec['train'][fmt]['loss_max_abs_delta_vs_bf16']}")

    # ---- serve: int8 PTQ vs int8-compute greedy decode ---------------
    # exactness pin at the PARITY scale (the tests' config): small vocab
    # keeps random-init top-1 gaps above the activation-rounding noise,
    # so greedy tokens must match EXACTLY.  At the bench (timing) scale
    # the vocab-2048 random-init logits carry near-tie argmaxes — one
    # rounding flip cascades — so that arm reports the agreement
    # fraction instead of pretending exactness (DESIGN §14).
    p_cfg = TransformerConfig(vocab_size=64, max_seq_len=48, n_layers=2,
                              d_model=32, n_heads=4, d_ff=64,
                              compute_dtype=compute_dtype)
    p_params = Transformer(p_cfg).init(prng.init_key(0))
    p_q = quantize_params(p_params)
    p_prompt = jnp.asarray([[1, 2, 3], [7, 8, 9]], jnp.int32)
    p_tokens = {
        "ptq": np.asarray(generate(Transformer(p_cfg), p_q, p_prompt, 16)),
        "qdot": np.asarray(generate(
            Transformer(_dc.replace(p_cfg, matmul_dtype="int8")),
            p_q, p_prompt, 16)),
    }

    s_cfg = _dc.replace(base_cfg, max_seq_len=seq)
    s_params = Transformer(s_cfg).init(prng.init_key(0))
    qparams = quantize_params(s_params)
    prompts = jnp.asarray(
        rng.integers(1, c["vocab"], (4, 8)).astype(np.int32))
    new_tokens = 24
    serve_arms = {
        "int8_ptq": Transformer(s_cfg),
        "int8_compute": Transformer(_dc.replace(s_cfg,
                                                matmul_dtype="int8")),
    }
    tokens = {}
    for name, m in serve_arms.items():  # warmup/compile + token pin
        tokens[name] = np.asarray(
            generate(m, qparams, prompts, new_tokens))
    s_times = {name: [] for name in serve_arms}
    for _rep in range(reps):
        for name, m in serve_arms.items():
            t0 = time.perf_counter()
            out = generate(m, qparams, prompts, new_tokens)
            jax.block_until_ready(out)
            s_times[name].append(time.perf_counter() - t0)
    gen_total = int(prompts.shape[0]) * new_tokens
    bench_agree = float((tokens["int8_ptq"][:, 8:]
                         == tokens["int8_compute"][:, 8:]).mean())
    rec["serve"] = {
        "prompts": prompts.tolist(),
        "new_tokens": new_tokens,
        # acceptance pin: greedy argmax EXACT on the parity-scale bench
        # prompts (both rows, all 16 generated tokens)
        "tokens_exact": bool((p_tokens["ptq"] == p_tokens["qdot"]).all()),
        "tokens_exact_config": {"vocab": 64, "d_model": 32, "n_layers": 2,
                                "prompts": p_prompt.tolist(),
                                "new_tokens": 16},
        # disclosed separately: at the timing scale near-tie argmaxes can
        # flip under activation rounding (vocab-2048 random init)
        "bench_scale_token_agreement": round(bench_agree, 4),
    }
    base_s = min(s_times["int8_ptq"])
    for name in serve_arms:
        best = min(s_times[name])
        pair_ratios = [t / b for t, b in zip(s_times[name],
                                             s_times["int8_ptq"])]
        rec["serve"][name] = {
            "decode_s_best": round(best, 4),
            "tokens_per_s_best": round(gen_total / best, 1),
            "vs_ptq_best": round(best / base_s, 4),
            "pair_ratio_median": round(float(np.median(pair_ratios)), 4),
        }
        log(f"[quant-ab serve {name}] {gen_total / best:.0f} tok/s best "
            f"(ratio {rec['serve'][name]['pair_ratio_median']})")
    log(f"[quant-ab serve] greedy tokens exact (parity scale): "
        f"{rec['serve']['tokens_exact']}; bench-scale agreement "
        f"{bench_agree:.2f}")
    rec["note"] = (
        "interleaved A/B pairs on the shared-core CPU host (DESIGN §7). "
        "The SPEED claim here is honesty-bounded: XLA:CPU has no "
        "int8/fp8 matrix unit, so the quantized dots emulate through "
        "int32/f32 and the MXU arithmetic-rate win is TPU-only (v5e "
        "int8 is ~2x bf16 peak); what this artifact pins is (a) the "
        "loss-curve parity envelope for fp8/int8 training, (b) greedy-"
        "token exactness of the int8-compute decode vs the PTQ path on "
        "the bench prompts, and (c) that the seam's bookkeeping "
        "(dynamic scales, amax state) keeps step time in the same "
        "regime as bf16 even without quantized hardware")
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, rec)
    log(f"quant A/B -> {out_path}")
    return out_path


def bench_trace_overhead(out_path: str = "BENCH_TRACE.json",
                         reps: int = 5, chain: int = 2) -> str:
    """Interleaved A/B of tracing OFF vs ON (span tracer + compile
    ledger, train/trace.py + utils/compile_ledger.py) at the CPU-bench
    transformer scale — the DESIGN §7 methodology: per-rep adjacent
    pairs so shared-core load drift cancels in the ratio, because a
    non-interleaved A/B on this host fabricates +10-18% from drift
    alone.  The ON arm pays everything the instrumented trainer pays
    per dispatch: a span write (json + flush), the ledger's signature
    check, and dispatch through the AOT-compiled executable.  Both arms
    start from the same init and the final param digests are compared —
    the bitwise trace-on-vs-off pin, embedded as evidence (and pinned
    independently by tests/test_trace.py)."""
    import hashlib
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        trace as trace_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        compile_ledger as ledger_lib,
        prng,
    )

    c = _LM
    seq, batch_size = 128, 32
    devices = jax.devices()
    n = len(devices)
    mesh = mesh_lib.make_mesh(MeshConfig(data=n), devices=devices)
    on_tpu = devices[0].platform not in ("cpu",)
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=seq, n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32))
    opt = optim.sgd(lr=1e-4, momentum=0.9)
    rng = np.random.default_rng(0)
    raw = {
        "x": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "y": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "mask": np.ones((batch_size,), np.float32),
    }
    batch = shd.shard_batch(mesh, raw)
    step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                              "global_mean")
    instrumented = ledger_lib.instrument(step, "bench_step[dp]")
    sync = _chain_sync_every()

    def fresh_state():
        return dp.replicate_state(
            TrainState.create(model, opt, prng.init_key(0)), mesh)

    def digest(state):
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
            h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest()

    def run_chain(state, k, traced):
        t0 = time.perf_counter()
        loss = None
        for i in range(k):
            if traced:
                with trace_lib.span("dispatch", step=i):
                    state, loss = instrumented(state, batch)
            else:
                state, loss = step(state, batch)
            if sync and (i + 1) % sync == 0:
                jax.block_until_ready(loss)
        val = float(jax.device_get(loss))
        return time.perf_counter() - t0, state, val

    trace_tmp = tempfile.mkdtemp(prefix="bench_trace_")
    tracer = trace_lib.start_run(trace_tmp)
    try:
        states = {"off": fresh_state(), "on": fresh_state()}
        # warmup both arms (off: jit compile; on: ledger AOT compile)
        for name in states:
            _, states[name], _ = run_chain(states[name], 1, name == "on")
        times = {"off": [], "on": []}
        loss_vals = {}
        for _rep in range(reps):
            for name in ("off", "on"):
                dt, states[name], loss_vals[name] = run_chain(
                    states[name], chain, name == "on")
                times[name].append(dt / chain)
        dig = {name: digest(s) for name, s in states.items()}
        ledger = ledger_lib.active()
        n_compiles = len(ledger.events) if ledger else 0
        compile_s = ledger.compile_seconds() if ledger else 0.0
        n_spans = trace_lib.active().events if trace_lib.active() else 0
    finally:
        trace_lib.stop_run(tracer)
        shutil.rmtree(trace_tmp, ignore_errors=True)
    assert np.isfinite(loss_vals["off"]) and np.isfinite(loss_vals["on"])
    pair_ratios = [a / b for a, b in zip(times["on"], times["off"])]
    best_off, best_on = min(times["off"]), min(times["on"])
    rec = {
        "metric": "trace_overhead_ab",
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n,
        "batch": batch_size,
        "model": {"n_layers": c["n_layers"], "d_model": c["d_model"],
                  "d_ff": c["d_ff"], "seq": seq, "vocab": c["vocab"]},
        "reps": reps, "chain_steps": chain,
        "arms": {
            "trace_off": {"step_ms_best": round(best_off * 1e3, 2),
                          "step_ms_median": round(
                              float(np.median(times["off"])) * 1e3, 2)},
            "trace_on": {"step_ms_best": round(best_on * 1e3, 2),
                         "step_ms_median": round(
                             float(np.median(times["on"])) * 1e3, 2)},
        },
        "overhead_best_pct": round((best_on / best_off - 1.0) * 100, 2),
        "overhead_pair_median_pct": round(
            (float(np.median(pair_ratios)) - 1.0) * 100, 2),
        "params_bitwise_identical": dig["off"] == dig["on"],
        "params_sha256": dig["off"],
        "trace_spans_written": int(n_spans),
        "ledger_compiles": int(n_compiles),
        "ledger_compile_s": round(compile_s, 3),
        "note": ("interleaved ON/OFF pairs (DESIGN §7): the ON arm pays "
                 "one span write + one ledger signature check per "
                 "dispatch and executes through the ledger's AOT-"
                 "compiled executable; params bitwise-identical either "
                 "way (also pinned by tests/test_trace.py)"),
    }
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    log(f"[trace-overhead] off {best_off * 1e3:.1f} ms/step, on "
        f"{best_on * 1e3:.1f} ms/step (pair-median "
        f"{rec['overhead_pair_median_pct']:+.1f}%), "
        f"{n_compiles} ledger compile(s), params bitwise "
        f"{'equal' if rec['params_bitwise_identical'] else 'DIFFERENT'}")
    _emit_artifact(out_path, rec)
    log(f"trace-overhead A/B -> {out_path}")
    # raise AFTER writing: a failing run must leave an artifact that
    # records params_bitwise_identical: false, not vanish
    if dig["off"] != dig["on"]:
        raise AssertionError(
            f"trace on/off param digests differ: {dig}")
    return out_path


def bench_obs_overhead(out_path: str = "BENCH_OBS.json",
                       reps: int = 5, chain: int = 2) -> str:
    """Interleaved A/B of the FULL observability plane OFF vs ON at the
    CPU-bench transformer scale (the DESIGN §7 methodology: per-rep
    adjacent pairs so shared-core load drift cancels in the ratio).

    The ON arm pays everything a fleet-observable trainer pays per
    dispatch: the on-device metrics vector (telemetry ``with_metrics``
    step), the lag-2 fetch, the metrics.jsonl write, the quantile-
    sketch feeds + EMA z-score detectors, the kind="rollup" sketch
    serialization on its cadence, and the per-role heartbeat.  Both
    arms start from the same init and the final param digests are
    compared — the bitwise sketches-on-vs-off pin, embedded as
    evidence (the with_metrics bitwise half is pinned independently by
    tests/test_telemetry.py; everything the sketch layer adds is host-
    side arithmetic on already-fetched floats, so it CANNOT touch the
    update math — the digest proves it)."""
    import hashlib
    import shutil
    import tempfile
    import types

    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        telemetry as telemetry_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    c = _LM
    seq, batch_size = 128, 32
    devices = jax.devices()
    n = len(devices)
    mesh = mesh_lib.make_mesh(MeshConfig(data=n), devices=devices)
    on_tpu = devices[0].platform not in ("cpu",)
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=seq, n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32))
    opt = optim.sgd(lr=1e-4, momentum=0.9)
    rng = np.random.default_rng(0)
    raw = {
        "x": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "y": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "mask": np.ones((batch_size,), np.float32),
    }
    batch = shd.shard_batch(mesh, raw)
    step_off = dp.make_train_step(model, opt, mesh, "cross_entropy",
                                  "global_mean")
    step_on = dp.make_train_step(model, opt, mesh, "cross_entropy",
                                 "global_mean", with_metrics=True)
    sync = _chain_sync_every()
    telem_tmp = tempfile.mkdtemp(prefix="bench_obs_")
    # every other dispatch crosses a rollup boundary: the ON arm pays
    # sketch serialization INSIDE the measured window, not just at exit
    telem_cfg = types.SimpleNamespace(
        telemetry_dir=telem_tmp, metrics_every=1, flight_recorder=64,
        rollup_every=2, alerts=True)
    telem = telemetry_lib.Telemetry(
        telem_cfg, model, (seq,), n_devices=n,
        device_kind=devices[0].device_kind,
        platform=devices[0].platform)

    def fresh_state():
        return dp.replicate_state(
            TrainState.create(model, opt, prng.init_key(0)), mesh)

    def digest(state):
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
            h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest()

    step_counter = {"on": 0}

    def run_chain(state, k, mode):
        t0 = time.perf_counter()
        out = None
        for i in range(k):
            if mode == "off":
                state, out = step_off(state, batch)
            else:
                state, out = step_on(state, batch)
                if mode == "on":
                    before = step_counter["on"]
                    step_counter["on"] += 1
                    telem.on_dispatch(step_counter["on"], 0, before, out,
                                      1, batch_size)
            if sync and (i + 1) % sync == 0:
                jax.block_until_ready(out)
        loss = out["loss"] if isinstance(out, dict) else out
        val = float(jax.device_get(loss))
        return time.perf_counter() - t0, state, val

    try:
        # three interleaved arms: 'off' (bare step), 'metrics' (the PR 2
        # with_metrics step, NO telemetry driver — the on-device norms'
        # own cost) and 'on' (full plane) — so the artifact attributes
        # the off->on delta between the jitted-step norms and the new
        # host-side sketch/rollup/alert/heartbeat layer
        states = {"off": fresh_state(), "metrics": fresh_state(),
                  "on": fresh_state()}
        modes = {"off": "off", "metrics": "metrics", "on": "on"}
        for name in states:  # warmup: jit compile all arms
            _, states[name], _ = run_chain(states[name], 1, modes[name])
        times = {"off": [], "metrics": [], "on": []}
        loss_vals = {}
        for _rep in range(reps):
            for name in ("off", "metrics", "on"):
                dt, states[name], loss_vals[name] = run_chain(
                    states[name], chain, modes[name])
                times[name].append(dt / chain)
        telem.flush(final=True, step=step_counter["on"])
        dig = {name: digest(s) for name, s in states.items()}
        rollups = telem.rollups_written
        alerts = telem.alerts_fired
    finally:
        telem.close()
        shutil.rmtree(telem_tmp, ignore_errors=True)
    assert all(np.isfinite(v) for v in loss_vals.values())
    pair_ratios = [a / b for a, b in zip(times["on"], times["off"])]
    plane_ratios = [a / b for a, b in zip(times["on"], times["metrics"])]
    best_off, best_on = min(times["off"]), min(times["on"])
    rec = {
        "metric": "obs_overhead_ab",
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n,
        "batch": batch_size,
        "model": {"n_layers": c["n_layers"], "d_model": c["d_model"],
                  "d_ff": c["d_ff"], "seq": seq, "vocab": c["vocab"]},
        "reps": reps, "chain_steps": chain,
        "arms": {
            "obs_off": {"step_ms_best": round(best_off * 1e3, 2),
                        "step_ms_median": round(
                            float(np.median(times["off"])) * 1e3, 2)},
            "metrics_step_only": {
                "step_ms_best": round(min(times["metrics"]) * 1e3, 2),
                "step_ms_median": round(
                    float(np.median(times["metrics"])) * 1e3, 2)},
            "obs_on": {"step_ms_best": round(best_on * 1e3, 2),
                       "step_ms_median": round(
                           float(np.median(times["on"])) * 1e3, 2)},
        },
        "overhead_best_pct": round((best_on / best_off - 1.0) * 100, 2),
        "overhead_pair_median_pct": round(
            (float(np.median(pair_ratios)) - 1.0) * 100, 2),
        # the fleet plane's own increment: full plane vs the PR 2
        # with_metrics step alone (the sketch feeds, detectors, rollup
        # serialization, metrics write and heartbeat)
        "plane_increment_pair_median_pct": round(
            (float(np.median(plane_ratios)) - 1.0) * 100, 2),
        "params_bitwise_identical": (dig["off"] == dig["on"]
                                     == dig["metrics"]),
        "params_sha256": dig["off"],
        "rollups_written": int(rollups),
        "alerts_fired": int(alerts),
        "rollup_every": telem_cfg.rollup_every,
        "note": ("interleaved OFF/METRICS/ON triples (DESIGN §7): the "
                 "ON arm runs the with_metrics step and pays the lag-2 "
                 "fetch, metrics.jsonl write, sketch feeds + EMA "
                 "detectors, rollup serialization every rollup_every "
                 "dispatches and the per-role heartbeat; the METRICS "
                 "arm isolates the jitted step's own norm cost (the "
                 "PR 2 layer), so plane_increment_pair_median_pct is "
                 "what THIS plane adds; params bitwise-identical "
                 "across all arms (sketches are host arithmetic on "
                 "fetched floats)"),
    }
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    log(f"[obs-overhead] off {best_off * 1e3:.1f} ms/step, on "
        f"{best_on * 1e3:.1f} ms/step (pair-median "
        f"{rec['overhead_pair_median_pct']:+.1f}%, plane increment "
        f"{rec['plane_increment_pair_median_pct']:+.1f}% over the "
        f"with_metrics step), {rollups} rollup(s) written, params "
        f"bitwise "
        f"{'equal' if rec['params_bitwise_identical'] else 'DIFFERENT'}")
    _emit_artifact(out_path, rec)
    log(f"obs-overhead A/B -> {out_path}")
    # raise AFTER writing: a failing run must leave an artifact that
    # records params_bitwise_identical: false, not vanish
    if not rec["params_bitwise_identical"]:
        raise AssertionError(
            f"obs on/off param digests differ: {dig}")
    return out_path


_GOODPUT_CHAOS_CHILD = r'''
import importlib.util
import json
import os
import sys
import time


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace = _load("_nnpt_trace", sys.argv[1])
jz = _load("_nnpt_jsonl", sys.argv[2])
gp = _load("_nnpt_goodput", sys.argv[3])
gp._jsonl = jz
trace_dir, telem_dir, marker = sys.argv[4], sys.argv[5], sys.argv[6]
steps = int(sys.argv[7])

tracer = trace.start_run(trace_dir, ledger=False)
meter = gp.GoodputMeter()
trace.add_listener(meter.on_span)
crash = bool(marker) and not os.path.exists(marker)
for i in range(steps):
    with trace.span("fetch", step=i):
        time.sleep(0.004)
    with trace.span("dispatch", step=i):
        time.sleep(0.03)
    if crash and i == 2:
        # first incarnation of the chaos child: die mid-run with the
        # trace file mid-stream; the relaunch re-runs every step, so the
        # offline ledger must price BOTH the supervisor gap
        # (relaunch_gap) and the re-trained step window (rollback)
        open(marker, "w").close()
        os._exit(1)
ident = trace.run_identity()
rec = gp.goodput_record(meter.snapshot(), role="train", step=steps,
                        ident=ident)
trace.remove_listener(meter.on_span)
tracer.close()
os.makedirs(telem_dir, exist_ok=True)
with open(os.path.join(telem_dir, "metrics.jsonl"), "a") as f:
    f.write(json.dumps(rec) + "\n")
'''


def _load_tool(name: str):
    """File-path load of a repo-root ``tools/`` module (they are
    standalone scripts, not a package)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_bench_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _goodput_chaos_run(tmp: str) -> dict:
    """Supervised 2-process chaos run for the goodput artifact: stdlib
    ``python -S`` children emit real trace spans, one is crashed once
    mid-run (``os._exit(1)``) and relaunched by :class:`GroupSupervisor`
    with the lifecycle JSONL enabled, and the offline ledger must then
    classify 100%% of both processes' wall-clock — the crash priced as a
    ``relaunch_gap`` plus a ``rollback`` re-trained window, never
    dropped."""
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        resilience as res,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        goodput as gp_lib,
    )

    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "neural_networks_parallel_training_with_mpi_tpu")
    trace_py = os.path.join(pkg, "train", "trace.py")
    jsonl_py = os.path.join(pkg, "utils", "jsonl.py")
    goodput_py = os.path.join(pkg, "utils", "goodput.py")
    script = os.path.join(tmp, "chaos_child.py")
    with open(script, "w") as f:
        f.write(_GOODPUT_CHAOS_CHILD)
    trace_dir = os.path.join(tmp, "trace")
    os.makedirs(trace_dir, exist_ok=True)
    telem_dirs = [os.path.join(tmp, f"telem{p}") for p in range(2)]
    marker = os.path.join(tmp, "crashed.marker")

    def cmd(p, mk):
        return [sys.executable, "-S", script, trace_py, jsonl_py,
                goodput_py, trace_dir, telem_dirs[p], mk, "6"]

    specs = [
        res.ChildSpec(name="w0", cmd=cmd(0, ""), role="train",
                      env={"NNPT_PROCESS_ID": "0"}, backoff=0.2),
        res.ChildSpec(name="w1", cmd=cmd(1, marker), role="train",
                      env={"NNPT_PROCESS_ID": "1"}, backoff=0.2),
    ]
    sup = res.GroupSupervisor(
        specs, log=lambda m: None,
        events_path=os.path.join(trace_dir, "supervisor-events.jsonl"))
    sup.start()
    deadline = time.time() + 120.0
    while sup.running() and time.time() < deadline:
        sup.poll()
        time.sleep(0.02)
    if sup.running():
        sup.terminate_all()
        raise AssertionError("goodput chaos run did not drain in 120s")
    for name in ("w0", "w1"):
        if sup.done(name) != 0:
            raise AssertionError(
                f"chaos child {name} finished rc={sup.done(name)}")

    led = gp_lib.ledger_from_dir(trace_dir)
    fleet = led["fleet"]
    return {
        "trace_dir": trace_dir,
        "telem_dirs": telem_dirs,
        "n_processes": fleet["n_processes"],
        "relaunches": fleet["relaunches"],
        "covered_s": fleet["covered_s"],
        "goodput_fraction": fleet["goodput_fraction"],
        "categories": fleet["categories"],
        "relaunch_gap_s": fleet["categories"].get("relaunch_gap", 0.0),
        "retrain_rollback_s": fleet["categories"].get("rollback", 0.0),
        "sum_ok_all_processes": all(p["sum_ok"] for p in led["processes"]),
        "fleet_sum_ok": fleet["sum_ok"],
        "max_abs_residual_s": max(
            (abs(p["sum_residual_s"]) for p in led["processes"]),
            default=0.0),
        "crashed_incarnations": [
            {"p": p["p"], "incarnations": len(p["incarnations"]),
             "exit_rcs": [i["exit_rc"] for i in p["incarnations"]]}
            for p in led["processes"]],
    }


def _goodput_serve_bitwise(tmp: str) -> dict:
    """Tokens-bitwise pin for the serving half: the same prompts through
    the continuous-batching scheduler with goodput accounting ON
    (meter + kind="goodput" rollups + burn budget) vs OFF must generate
    IDENTICAL token ids — the accounting layer is a span listener over
    host timestamps and cannot reach the sampler."""
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve.scheduler import (
        Scheduler, ServeConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    model = Transformer(TransformerConfig(
        vocab_size=64, max_seq_len=64, n_layers=2, d_model=32,
        n_heads=4, d_ff=64, compute_dtype=jnp.float32))
    params = model.init(prng.init_key(0))
    prompts = [[1, 2, 3], [4, 5, 6, 7], [9, 10]]
    tokens = {}
    records = {}
    for arm in ("on", "off"):
        tdir = os.path.join(tmp, f"serve_{arm}")
        cfg = ServeConfig(slots=4, num_blocks=40, block_size=8,
                          prefill_chunk=8, telemetry_dir=tdir,
                          rollup_every=4, goodput=(arm == "on"))
        sched = Scheduler(model, params, cfg)
        rids = [sched.submit(p, 8) for p in prompts]
        sched.run_until_drained()
        tokens[arm] = [sched.result(r) for r in rids]
        sched.close()
        with open(os.path.join(tdir, "metrics.jsonl")) as f:
            records[arm] = sum(
                1 for ln in f if '"kind": "goodput"' in ln)
    return {
        "prompts": prompts,
        "new_tokens": 8,
        "tokens_bitwise_identical": tokens["on"] == tokens["off"],
        "tokens": tokens["on"],
        "goodput_records_on": records["on"],
        "goodput_records_off": records["off"],
    }


def bench_goodput(out_path: str = "BENCH_GOODPUT.json",
                  reps: int = 7, chain: int = 2) -> str:
    """The goodput-accounting bench (utils/goodput.py): prices the
    in-process :class:`GoodputMeter` the DESIGN §7 way — interleaved
    per-rep OFF/ON pairs on the traced CPU-bench transformer chain, so
    the ratio isolates exactly what the accounting layer adds on top of
    tracing (one listener call + frontier dict update per span, plus a
    snapshot per chain) — and then proves the accounting CONTRACTS on a
    supervised 2-process chaos run: an injected crash -> relaunch must
    come back as ``relaunch_gap`` + ``rollback`` with every process's
    categories summing to its covered wall-clock, the merged telemetry
    must surface a per-role goodput fraction through tools/obs_agg.py's
    Prometheus export, and serving tokens must be bitwise identical
    accounting on vs off."""
    import hashlib
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
    from neural_networks_parallel_training_with_mpi_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        data_parallel as dp,
        mesh as mesh_lib,
        sharding as shd,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train import (
        trace as trace_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.train.state import TrainState
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        goodput as gp_lib,
        prng,
    )

    c = _LM
    seq, batch_size = 128, 32
    devices = jax.devices()
    n = len(devices)
    mesh = mesh_lib.make_mesh(MeshConfig(data=n), devices=devices)
    on_tpu = devices[0].platform not in ("cpu",)
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=seq, n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32))
    opt = optim.sgd(lr=1e-4, momentum=0.9)
    rng = np.random.default_rng(0)
    raw = {
        "x": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "y": rng.integers(0, c["vocab"], (batch_size, seq)).astype(np.int32),
        "mask": np.ones((batch_size,), np.float32),
    }
    batch = shd.shard_batch(mesh, raw)
    step = dp.make_train_step(model, opt, mesh, "cross_entropy",
                              "global_mean")
    sync = _chain_sync_every()

    def fresh_state():
        return dp.replicate_state(
            TrainState.create(model, opt, prng.init_key(0)), mesh)

    def digest(state):
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
            h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest()

    meter = gp_lib.GoodputMeter()

    def run_chain(state, k, metered):
        # BOTH arms are traced: the measured delta is the goodput
        # layer alone — the span-listener fan-out, the meter's frontier
        # update per span, and one snapshot per chain (the per-rollup
        # cost the instrumented trainer pays)
        if metered:
            trace_lib.add_listener(meter.on_span)
        t0 = time.perf_counter()
        try:
            loss = None
            for i in range(k):
                with trace_lib.span("dispatch", step=i):
                    state, loss = step(state, batch)
                if sync and (i + 1) % sync == 0:
                    jax.block_until_ready(loss)
            val = float(jax.device_get(loss))
            if metered:
                meter.snapshot()
        finally:
            if metered:
                trace_lib.remove_listener(meter.on_span)
        return time.perf_counter() - t0, state, val

    trace_tmp = tempfile.mkdtemp(prefix="bench_goodput_")
    tracer = trace_lib.start_run(os.path.join(trace_tmp, "ab"),
                                 ledger=False)
    try:
        states = {"off": fresh_state(), "on": fresh_state()}
        for name in states:  # warmup: jit compile both arms
            _, states[name], _ = run_chain(states[name], 1, name == "on")
        times = {"off": [], "on": []}
        loss_vals = {}
        for _rep in range(reps):
            for name in ("off", "on"):
                dt, states[name], loss_vals[name] = run_chain(
                    states[name], chain, name == "on")
                times[name].append(dt / chain)
        dig = {name: digest(s) for name, s in states.items()}
        snap = meter.snapshot()
    finally:
        trace_lib.stop_run(tracer)
    assert np.isfinite(loss_vals["off"]) and np.isfinite(loss_vals["on"])
    # snapshot values are rounded to 1e-6 each, so the sum of 11 rounded
    # categories can miss the rounded covered total by up to half an ulp
    # per term — widen the tolerance by the term count, nothing more
    meter_sum_ok = abs(
        sum(snap["categories"].values()) - snap["covered_s"]) < max(
            gp_lib.SUM_TOL * (len(snap["categories"]) + 1),
            1e-9 * max(snap["covered_s"], 1.0))

    try:
        chaos = _goodput_chaos_run(trace_tmp)

        # fleet merge evidence: the chaos children's kind="goodput"
        # telemetry records through the same aggregation every operator
        # surface uses — per-role fraction must reach Prometheus
        oa = _load_tool("obs_agg")
        fleet_doc = oa.aggregate(chaos.pop("telem_dirs"))
        prom = oa.to_prometheus(fleet_doc)
        prom_lines = [ln for ln in prom.splitlines()
                      if ln.startswith("nnpt_goodput_fraction{")]
        chaos.pop("trace_dir", None)
        merged = {
            "fleet_goodput_fraction": fleet_doc["fleet"].get(
                "goodput_fraction"),
            "prometheus_fraction_lines": prom_lines,
            "prometheus_families_present": (
                "nnpt_goodput_seconds_total" in prom
                and bool(prom_lines)),
        }

        serve = _goodput_serve_bitwise(trace_tmp)
    finally:
        shutil.rmtree(trace_tmp, ignore_errors=True)

    pair_ratios = [a / b for a, b in zip(times["on"], times["off"])]
    best_off, best_on = min(times["off"]), min(times["on"])
    rec = {
        "metric": "goodput_accounting_ab",
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": n,
        "batch": batch_size,
        "model": {"n_layers": c["n_layers"], "d_model": c["d_model"],
                  "d_ff": c["d_ff"], "seq": seq, "vocab": c["vocab"]},
        "reps": reps, "chain_steps": chain,
        "arms": {
            "goodput_off": {"step_ms_best": round(best_off * 1e3, 2),
                            "step_ms_median": round(
                                float(np.median(times["off"])) * 1e3, 2)},
            "goodput_on": {"step_ms_best": round(best_on * 1e3, 2),
                           "step_ms_median": round(
                               float(np.median(times["on"])) * 1e3, 2)},
        },
        "overhead_best_pct": round((best_on / best_off - 1.0) * 100, 2),
        "overhead_pair_median_pct": round(
            (float(np.median(pair_ratios)) - 1.0) * 100, 2),
        "overhead_gate_pct": 1.0,
        "params_bitwise_identical": dig["off"] == dig["on"],
        "params_sha256": dig["off"],
        "meter_spans": int(snap["spans"]),
        "meter_sum_ok": bool(meter_sum_ok),
        "chaos": chaos,
        "fleet_merge": merged,
        "serve": serve,
        "note": ("interleaved ON/OFF pairs (DESIGN §7), both arms "
                 "traced so the ratio prices the goodput layer alone "
                 "(span listener + frontier update per span + one "
                 "snapshot per chain); chaos block is a supervised "
                 "2-process stdlib run with one injected crash — the "
                 "offline ledger classifies 100% of both processes' "
                 "wall-clock (sum_ok), pricing the crash as "
                 "relaunch_gap + re-trained rollback; fleet_merge pins "
                 "the per-role goodput fraction surviving to the "
                 "Prometheus export; serve pins tokens bitwise "
                 "identical accounting on vs off"),
    }
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    log(f"[goodput] off {best_off * 1e3:.1f} ms/step, on "
        f"{best_on * 1e3:.1f} ms/step (pair-median "
        f"{rec['overhead_pair_median_pct']:+.1f}%), chaos sum_ok="
        f"{chaos['sum_ok_all_processes']} relaunch_gap="
        f"{chaos['relaunch_gap_s']:.2f}s rollback="
        f"{chaos['retrain_rollback_s']:.2f}s, serve tokens bitwise "
        f"{'equal' if serve['tokens_bitwise_identical'] else 'DIFFERENT'}")
    _emit_artifact(out_path, rec)
    log(f"goodput A/B -> {out_path}")
    # raise AFTER writing: a failing run must leave an artifact that
    # records which contract broke, not vanish
    if dig["off"] != dig["on"]:
        raise AssertionError(f"goodput on/off param digests differ: {dig}")
    if not serve["tokens_bitwise_identical"]:
        raise AssertionError("serve tokens differ accounting on vs off")
    if not (chaos["sum_ok_all_processes"] and chaos["fleet_sum_ok"]
            and meter_sum_ok):
        raise AssertionError(
            f"goodput sum-to-covered invariant violated: {chaos}")
    if chaos["relaunch_gap_s"] <= 0.0:
        raise AssertionError(
            "injected crash produced no relaunch_gap attribution")
    if not merged["prometheus_families_present"]:
        raise AssertionError(
            "goodput families missing from the Prometheus export")
    return out_path


def bench_serve(out_path: str = "BENCH_SERVE.json",
                attn_impl: str = "gathered") -> str:
    """The serving-subsystem bench (serve/): a CLOSED-LOOP load sweep of
    the continuous-batching scheduler over the paged KV cache — tokens/s
    and p50/p99 TTFT/ITL vs. offered load (concurrent clients) — plus
    two targeted A/Bs: (1) concurrent-stream CAPACITY at equal device
    cache memory, dense slot server vs. paged pool (the paged win is
    measured by admitting streams until each refuses); (2) the dense
    server's per-token host-sync fix (models/serve.py), old blocking
    fetch vs. host-tracked completion, same workload.  On the CPU
    fallback the absolute numbers are mechanism checks at tiny shapes;
    the CURVES (latency vs. load, capacity ratio) are the evidence."""
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.models import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.serve import (
        DecodeServer,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        Scheduler, ServeConfig, prewarm, run_closed_loop, sweep_loads,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    cd = jnp.bfloat16 if on_tpu else jnp.float32
    c = (_LM if on_tpu else
         dict(vocab=256, seq=128, d_model=64, n_layers=2, n_heads=4,
              d_ff=128))
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=c["seq"], n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=cd))
    params = model.init(prng.init_key(0))
    results: dict = {"model": {k: c[k] for k in
                               ("vocab", "seq", "d_model", "n_layers")}}

    # --- closed-loop load sweep (>= 3 offered loads) -------------------
    block_size = 16
    slots = 8
    max_len = c["seq"]
    # a non-starved pool for the latency sweep: the question here is
    # latency vs. load, not eviction policy (capacity A/B below covers
    # the tight-pool regime)
    num_blocks = 1 + slots * (max_len // block_size)
    cfg = dict(slots=slots, num_blocks=num_blocks, block_size=block_size,
               max_len=max_len, prefill_chunk=32, attn_impl=attn_impl)
    loads = [2, 6, 12] if not on_tpu else [4, 16, 64]
    reqs_per_client = 3

    def make_sched():
        return Scheduler(model, params, ServeConfig(**cfg))

    # sweep_loads prewarms via serve.loadgen.prewarm: every prefill
    # bucket the prompt range can draw plus the batched decode program
    # (the Pallas paged-attention compile under attn_impl='fused'), so
    # no load point books a compile as a fake TTFT outlier
    results["load_sweep"] = sweep_loads(
        make_sched, loads, reqs_per_client, vocab_size=c["vocab"],
        prompt_lens=(4, 24), max_new=(8, 24), seed=1)
    results["serve_config"] = cfg

    # --- gathered vs fused through the FULL service loop ---------------
    # one mid-sweep load point per attention impl, same request stream:
    # end-to-end tokens/s with scheduling/prefill riding along, plus the
    # attended-keys accounting the fused kernel skips.  The kernel-level
    # A/B at ragged lengths (token identity, per-step wall time, the
    # long-context regime) is BENCH_PAGED_ATTN.json (bench --paged-attn).
    ab = {}
    for impl in ("gathered", "fused"):
        def mk(impl=impl):
            return Scheduler(model, params,
                             ServeConfig(**{**cfg, "attn_impl": impl}))

        # both arms measured back-to-back with the same code path (the
        # gathered arm deliberately repeats a sweep-like point rather
        # than reusing a load_sweep row measured minutes earlier —
        # host-load drift would contaminate the A/B); prewarm pays each
        # arm's compiles (the fused arm's Pallas kernel) up front
        prewarm(mk, prompt_lens=(4, 24))
        sched = mk()
        try:
            row = run_closed_loop(
                sched, loads[1], reqs_per_client, vocab_size=c["vocab"],
                prompt_lens=(4, 24), max_new=(8, 24), seed=1)
            ab[impl] = {"tokens_per_sec": row["tokens_per_sec"],
                        "itl_ms_p50": row["itl_ms_p50"],
                        "attended_keys": sched.attended_keys,
                        "padded_keys": sched.padded_keys}
        finally:
            sched.close()
    ab["see_also"] = "BENCH_PAGED_ATTN.json (kernel-level ragged A/B)"
    if not on_tpu:
        ab["note"] = (
            "short-context point (max_len 128, 8 blocks/stream): in CPU "
            "interpret mode the fused kernel's fixed per-program cost "
            "is not amortized here — BENCH_PAGED_ATTN.json measures the "
            "long-context regime (max_len 1024) where fused is at or "
            "under gathered's step time even interpreted, and the "
            "attended/padded ratio is the TPU-facing FLOPs claim")
    results["attn_impl_ab"] = ab

    # --- capacity at EQUAL device cache memory -------------------------
    # dense: 4 slots x max_len positions reserved up front.  paged: the
    # same number of cache positions split into blocks (+1 sink block of
    # overhead, disclosed).  Short streams (prompt 8 + 8 new = 16
    # positions) admit until each server refuses — measured, not derived.
    dense_slots = 4
    eq_positions = dense_slots * max_len
    paged_blocks = 1 + eq_positions // block_size      # +1: the sink
    short_prompt, short_new = 8, 8
    dense_srv = DecodeServer(model, params, slots=dense_slots,
                             max_len=max_len)
    dense_cap = 0
    while dense_srv.submit([1 + dense_cap % 250] * short_prompt,
                           short_new) is not None:
        dense_cap += 1
    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        PagedDecodeServer,
    )

    paged_srv = PagedDecodeServer(model, params,
                                  slots=eq_positions // block_size,
                                  num_blocks=paged_blocks,
                                  block_size=block_size, max_len=max_len)
    paged_cap = 0
    while paged_srv.try_admit([1 + paged_cap % 250] * short_prompt,
                              short_new) is not None:
        paged_cap += 1
    # paged admission reserves blocks for prompt+1 only; the honest
    # capacity number is streams that can run END TO END concurrently
    # (each needs blocks_for(prompt + new)); report both
    per_stream = paged_srv.blocks_for(short_prompt + short_new)
    results["capacity_equal_memory"] = {
        "cache_positions": eq_positions,
        "block_size": block_size,
        "paged_pool_blocks": paged_blocks,
        "stream_positions": short_prompt + short_new,
        "dense_streams_admitted": dense_cap,
        "paged_streams_admitted": paged_cap,
        "paged_streams_end_to_end": (paged_blocks - 1) // per_stream,
        "paged_over_dense": round(paged_cap / max(1, dense_cap), 2),
    }

    # --- the dense server's host-sync fix, measured --------------------
    def serve_pass(sync_per_step: bool) -> float:
        srv = DecodeServer(model, params, slots=4, max_len=max_len,
                           sync_per_step=sync_per_step)
        rng = np.random.default_rng(0)
        lens = [3, 7, 12, 5, 9, 4, 14, 6]
        new_tokens = 32 if not on_tpu else 64
        pending = [(list(rng.integers(0, c["vocab"], (p,))), new_tokens)
                   for p in lens]
        done_tok = 0
        t0 = time.perf_counter()
        rids = []
        while pending or rids:
            while pending:
                rid = srv.submit(*pending[0])
                if rid is None:
                    break
                rids.append((rid, pending.pop(0)[1]))
            srv.step()
            for rid, n in list(rids):
                if srv.done(rid):
                    srv.result(rid)
                    done_tok += n
                    rids.remove((rid, n))
        return round(done_tok / (time.perf_counter() - t0), 1)

    serve_pass(False)                        # compile pass
    best_async = best_sync = 0.0
    for _ in range(1 if on_tpu else _CPU_TIMING_REPS):
        best_async = max(best_async, serve_pass(False))
        best_sync = max(best_sync, serve_pass(True))
    results["dense_host_sync_fix"] = {
        "tokens_per_sec_host_tracked": best_async,
        "tokens_per_sec_per_step_fetch": best_sync,
        "speedup": round(best_async / max(1e-9, best_sync), 3),
        "note": ("the removed cost is a blocking per-token host<->device "
                 "round trip; XLA:CPU dispatch is effectively "
                 "synchronous, so the CPU delta is noise — the win is "
                 "the async-dispatch pipeline on a real accelerator "
                 "(the tunneled chip pays ~65 ms per host round trip, "
                 "DESIGN.md 6b)") if not on_tpu else None,
    }

    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    results["n_devices"] = len(devices)
    if not on_tpu:
        results["note"] = ("CPU fallback mechanism check: tiny model, "
                           "absolute tokens/s not meaningful; the load-"
                           "latency curves and the capacity ratio are "
                           "the platform-independent evidence")
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, results)
    log(f"serve bench -> {out_path}")
    return out_path


def bench_serve_fleet(out_path: str = "BENCH_FLEET.json") -> str:
    """The serving-fleet bench (serve/fleet.py): aggregate tokens/s vs
    REPLICA COUNT (1/2/4 subprocess replicas, each its own jax runtime,
    under the group supervisor and the SLO-aware router) at saturating
    offered load (closed-loop clients > total fleet slots), per-class
    TTFT percentiles (interactive-with-SLO vs bulk), and a router
    overload point where the bounded fleet queue REJECTS.

    Honesty on the CPU host: this box has ONE core, so N concurrently
    time-sliced CPU-bound replicas can never beat one (physics, not
    routing — the ``cpu_bound_control`` rows measure exactly that: a
    ratio AT OR UNDER 1.0, and in practice UNDER it, since IPC + a
    second runtime add pure overhead).  A real serving replica is
    DEVICE-bound: the host's tick work (admission, block tables,
    sampling bookkeeping) is a small slice of a decode step that runs
    on the accelerator
    while sibling replicas' steps run on THEIR accelerators.  The
    sweep therefore pads each replica's decode tick with
    ``device_emulation_ms`` of emulated device latency
    (``--step-sleep-ms`` in the worker — measured host tick cost at
    this scale is ~0.6 ms, disclosed below),
    which is the regime the fleet targets; the scaling rows then
    measure what the ROUTER + supervisor + IPC actually add — the part
    this subsystem is responsible for.  Same convention family as the
    CPU MFU divisor (DESIGN.md §7): an emulated-device number, clearly
    labeled, never passed off as chip throughput."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        launch_fleet, run_fleet_closed_loop,
    )

    devices = jax.devices()
    device_ms = 15.0
    model = dict(vocab=256, seq=128, layers=2, d_model=64, heads=4,
                 d_ff=128, init_seed=0)
    serve = dict(slots=4, block_size=16, prefill_chunk=32,
                 queue_depth=16)
    classes = [{"name": "interactive", "slo_ms": 2000.0},
               {"name": "bulk", "slo_ms": None}]
    results: dict = {
        "model": model, "serve_per_replica": serve,
        "device_emulation_ms": device_ms,
        "host_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity") else os.cpu_count(),
    }

    def run_arm(n, *, sleep_ms, clients, rpc, queue_depth=128,
                seed=1):
        fleet = launch_fleet(
            n, model=model, serve=serve, step_sleep_ms=sleep_ms,
            router_kwargs=dict(queue_depth=queue_depth),
            prewarm=True, max_restarts=1, log=lambda m: None)
        try:
            fleet.wait_ready(600)
            row = run_fleet_closed_loop(
                fleet, clients, rpc, vocab_size=model["vocab"],
                prompt_lens=(4, 24), max_new=(8, 24), seed=seed,
                classes=classes)
            row["replicas"] = n
            row["offered_clients"] = clients
            row["fleet_slots"] = n * serve["slots"]
            return row
        finally:
            fleet.close()

    # ---- the scaling sweep: 1/2/4 replicas, saturating load ----------
    sweep = []
    for n in (1, 2, 4):
        row = run_arm(n, sleep_ms=device_ms, clients=6 * n, rpc=4)
        log(f"[fleet n={n}] {row['tokens_per_sec']} tok/s "
            f"(interactive ttft p50 "
            f"{row['ttft_ms_p50_interactive']:.1f} ms, "
            f"requeued {row['requeued']})")
        sweep.append(row)
    results["fleet_sweep"] = sweep
    base = sweep[0]["tokens_per_sec"]
    speedup_2 = round(sweep[1]["tokens_per_sec"] / base, 2)
    speedup_4 = round(sweep[2]["tokens_per_sec"] / base, 2)

    # ---- CPU-bound control: no emulated device latency ----------------
    # N time-sliced CPU-bound replicas on one core CANNOT scale; this
    # row set proves the sweep above is measuring fleet overlap, not a
    # measurement artifact (if the control ALSO scaled, something would
    # be wrong with the harness)
    control = []
    for n in (1, 2):
        row = run_arm(n, sleep_ms=0.0, clients=6 * n, rpc=3, seed=2)
        control.append({"replicas": n,
                        "tokens_per_sec": row["tokens_per_sec"]})
    results["cpu_bound_control"] = {
        "rows": control,
        "ratio_2x": round(control[1]["tokens_per_sec"]
                          / control[0]["tokens_per_sec"], 2),
        "note": ("no device emulation: both replicas time-slice the "
                 "single host core, so the ratio is bounded by ~1.0 "
                 "and in practice lands UNDER it (IPC + a second "
                 "runtime are pure overhead) — the fleet's scaling "
                 "claim lives in the device-bound regime above, and "
                 "on real accelerators (one replica per host/chip)"),
    }

    # ---- router overload: the bounded fleet queue rejects -------------
    over = run_arm(2, sleep_ms=device_ms, clients=24, rpc=2,
                   queue_depth=6, seed=3)
    results["router_overload"] = {
        "router_queue_depth": 6,
        "offered_clients": 24,
        "router_rejections": over["router_rejections"],
        "submit_retries": over["submit_retries"],
        "completed": over["requests"],
        "ttft_ms_p99_interactive": over["ttft_ms_p99_interactive"],
        "note": ("overload sheds at the ROUTER's one bounded queue "
                 "(clients retry, closed-loop); replica-local queues "
                 "stay shallow so waiting work remains re-placeable"),
    }

    results["acceptance"] = {
        "tokens_per_sec_1_2_4": [r["tokens_per_sec"] for r in sweep],
        "speedup_2_replicas": speedup_2,
        "speedup_2_ge_1_6": bool(speedup_2 >= 1.6),
        "speedup_4_replicas": speedup_4,
        "speedup_4_ge_2_5": bool(speedup_4 >= 2.5),
        "router_rejections_observed":
            int(over["router_rejections"]) > 0,
        "per_class_ttft_embedded": True,
    }
    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    out_path = _divert_cpu_overwrite(
        out_path, devices[0].platform not in ("cpu",))
    _emit_artifact(out_path, results)
    log(f"serve fleet bench -> {out_path} (2x {speedup_2}, "
        f"4x {speedup_4})")
    return out_path


def bench_serve_disagg(out_path: str = "BENCH_DISAGG.json") -> str:
    """The disaggregated prefill/decode bench (serve/fleet.py role
    pools + the handoff ledger, DESIGN.md §11): price the block
    handoff and pin its safety.

    Arms (identical request plan wherever tokens are pinned — same
    seed, same ``long_prefill`` mix, so every arm's token stream is
    byte-comparable):

    * ``decode_floor`` — one unified replica, near-zero prompts: the
      decode-cadence floor (what ITL looks like when prefill work is
      negligible).  Different traffic by construction, so it is the
      cadence REFERENCE, not part of the token pin.
    * ``unified`` — two unified replicas under the long-prompt-heavy
      mix: chunked prefill interleaves with decode on the SAME
      replica, so long prompts tax running streams' ITL.
    * ``disagg`` — one prefill + one decode replica, same traffic:
      prefill runs elsewhere, blocks arrive via the handoff, and the
      decode pool's ITL p99 must stay FLAT (near the floor, at or
      under unified) — the whole point of disaggregation.
    * ``degraded`` — the prefill replica dies for good (restart budget
      zero): the router serves unified on the surviving decode pool;
      degraded dispatches/seconds are priced and tokens still match.
    * four chaos arms — one per fleet fault kind (``handoff_kill``
      pre-commit, ``handoff_kill_post``, ``decode_kill``,
      ``handoff_stall``): every recovery path exercised under load,
      each arm completing ALL requests with byte-identical tokens.

    Honesty: same device-emulation convention as BENCH_FLEET (each
    decode tick padded with ``device_emulation_ms`` of emulated device
    latency; this one-core host time-slices the replicas), and the
    byte-identity pin holds for GREEDY decode only — replicas are
    bit-identical by construction, so tokens are a pure function of
    the request plan, never of placement, handoff, or recovery."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        launch_fleet, run_fleet_closed_loop,
    )

    devices = jax.devices()
    device_ms = 15.0
    model = dict(vocab=256, seq=128, layers=2, d_model=64, heads=4,
                 d_ff=128, init_seed=0)
    serve = dict(slots=4, block_size=16, prefill_chunk=32,
                 queue_depth=16)
    clients, rpc, seed = 6, 4, 11
    results: dict = {
        "model": model, "serve_per_replica": serve,
        "device_emulation_ms": device_ms,
        "mix": "long_prefill",
        "clients": clients, "requests_per_client": rpc, "seed": seed,
        "host_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity") else os.cpu_count(),
    }

    def run_arm(label, *, roles, fault=None, max_restarts=1,
                handoff_timeout_s=60.0, mix="long_prefill",
                prompt_lens=(4, 24), max_new=(8, 24)):
        """One fleet arm: ``roles`` spawns the healthy replicas;
        ``fault`` (role, faults-spec) adds one more carrying the
        injected fault (its worker index is len(roles), matching the
        spec's ``proc=``)."""
        fleet = launch_fleet(
            len(roles), model=model, serve=serve,
            step_sleep_ms=device_ms,
            router_kwargs=dict(queue_depth=128,
                               handoff_timeout_s=handoff_timeout_s),
            prewarm=True, max_restarts=max_restarts, roles=roles,
            log=lambda m: None)
        try:
            if fault is not None:
                frole, fstr = fault
                fleet.add_replica(role=frole, faults=fstr)
            fleet.wait_ready(600)
            row = run_fleet_closed_loop(
                fleet, clients, rpc, vocab_size=model["vocab"],
                prompt_lens=prompt_lens, max_new=max_new, seed=seed,
                mix=mix)
            hs = fleet.router.handoff_stats()
            # include any STILL-OPEN degraded span (an arm that ends
            # degraded would otherwise report only closed spans)
            hs["degraded_mode_s"] = (
                fleet.router.load_report()["now"]["degraded_mode_s"])
            row["handoff"] = hs
            log(f"[disagg {label}] {row['tokens_per_sec']} tok/s "
                f"itl_p99 {row['itl_ms_p99']:.1f} ms "
                f"handoffs {hs['handoffs']} "
                f"requeued {row['requeued']} "
                f"degraded {hs['degraded_dispatches']}")
            return row
        finally:
            fleet.close()

    # ---- cadence floor: negligible prefill, same decode lengths ------
    floor = run_arm("decode_floor", roles=[None], mix=None,
                    prompt_lens=(4, 8), max_new=(16, 28))
    results["decode_floor"] = floor

    # ---- unified vs disagg at equal replica count --------------------
    unified = run_arm("unified", roles=[None, None])
    disagg = run_arm("disagg", roles=["prefill", "decode"])
    results["unified"] = unified
    results["disagg"] = disagg

    # ---- degraded mode: prefill pool dies, zero restart budget -------
    degraded = run_arm("degraded", roles=["decode"],
                       fault=("prefill", "replica_kill@2?proc=1&max=1"),
                       max_restarts=0)
    results["degraded"] = degraded

    # ---- chaos arms: one per fleet fault kind ------------------------
    # fault plans reset per process life, so a killed worker re-fires
    # on relaunch until the restart budget runs out — each kill arm
    # therefore ALSO ends in (and prices) degraded single-pool serving
    chaos_specs = [
        ("handoff_kill", ["decode"],
         ("prefill", "handoff_kill@2?proc=1&max=1"), 60.0),
        ("handoff_kill_post", ["decode"],
         ("prefill", "handoff_kill_post@2?proc=1&max=1"), 60.0),
        ("decode_kill", ["prefill"],
         ("decode", "decode_kill@2?proc=1&max=1"), 60.0),
        # stall: the 2nd inject is swallowed (no ack) — a short ledger
        # timeout so the retry path is exercised inside the arm
        ("handoff_stall", ["prefill"],
         ("decode", "handoff_stall@2?proc=1&max=1"), 2.0),
    ]
    chaos: dict = {}
    for name, roles, fault, timeout_s in chaos_specs:
        row = run_arm(name, roles=roles, fault=fault,
                      max_restarts=1, handoff_timeout_s=timeout_s)
        chaos[name] = row
    results["chaos"] = chaos

    pinned = [("unified", unified), ("disagg", disagg),
              ("degraded", degraded)] + sorted(chaos.items())
    shas = {k: r["tokens_sha256"] for k, r in pinned}
    want = clients * rpc
    results["acceptance"] = {
        "tokens_sha256": shas,
        "tokens_identical_all_arms":
            len(set(shas.values())) == 1,
        "all_arms_completed":
            all(r["requests"] == want for _, r in pinned),
        "itl_p99_floor_ms": floor["itl_ms_p99"],
        "itl_p99_unified_ms": unified["itl_ms_p99"],
        "itl_p99_disagg_ms": disagg["itl_ms_p99"],
        # flat = the disagg decode pool's cadence stays near the
        # no-prefill floor and never loses to unified under the same
        # long-prompt mix (5% noise allowance on a one-core host)
        "disagg_itl_p99_flat": bool(
            disagg["itl_ms_p99"] <= floor["itl_ms_p99"] * 1.6
            and disagg["itl_ms_p99"] <= unified["itl_ms_p99"] * 1.05),
        "handoffs_committed": disagg["handoff"]["handoffs"] > 0,
        "handoff_ms_p50": disagg["handoff"]["handoff_ms_p50"],
        "handoff_ms_p99": disagg["handoff"]["handoff_ms_p99"],
        "degraded_served_unified":
            degraded["handoff"]["degraded_dispatches"] > 0,
        "stall_retried":
            chaos["handoff_stall"]["handoff"]["handoff_retries"] > 0,
        "decode_kill_redecoded":
            chaos["decode_kill"]["handoff"]["redecodes"] > 0,
        "kill_requeued":
            chaos["handoff_kill"]["requeued"] > 0,
    }
    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    out_path = _divert_cpu_overwrite(
        out_path, devices[0].platform not in ("cpu",))
    _emit_artifact(out_path, results, honesty={
        "device_emulation": True,   # decode ticks padded with emulated
        # device latency; one-core host time-slices the replicas
        "greedy_byte_identity_only": True,  # the cross-arm token pin
        # holds for greedy decode (temperature=0) — sampled decode has
        # per-server PRNG state and is out of scope by design
    })
    acc = results["acceptance"]
    log(f"serve disagg bench -> {out_path} "
        f"(tokens_identical={acc['tokens_identical_all_arms']}, "
        f"itl_flat={acc['disagg_itl_p99_flat']})")
    return out_path


def bench_ctrlplane(out_path: str = "BENCH_CTRLPLANE.json") -> str:
    """The durable-control-plane bench (serve/wal.py + router recovery,
    DESIGN.md §12): price the write-ahead ledger and pin exactly-once
    across control-plane death.

    The router lives in the operator process, so the subject runs in a
    killable driver subprocess (serve/ctrlplane_driver.py) whose
    progress the parent observes by polling the WAL read-only.  Arms
    (identical prefill/decode fleet, identical ``long_prefill`` plan —
    every arm's token stream is byte-comparable):

    * ``wal_off`` (x2) — journal disabled; run twice so the pair's
      spread IS the run-noise yardstick the WAL overhead is judged
      against.
    * ``wal_on`` — journal enabled, no crash: steady-state fsync cost.
    * ``router_kill`` — SIGKILL the driver pid mid-load
      (``router_kill@3``: after 3 journaled completions).  Workers
      orphan, hit stdin EOF, and drain through the notice channel;
      relaunch with the same WAL dir replays the ledger.
    * ``fleet_kill`` — SIGKILL the whole process group mid-load, gated
      on a committed handoff still inflight (the hardest record class:
      journaled on the prefill side, undelivered on the decode side);
      relaunch recovers from the fsynced WAL alone.

    Exactly-once is the gate: each crash arm's second life completes
    ALL requests with ``tokens_sha256`` identical to the uncrashed
    arms — completed requests answered from the journal (deduped by
    idempotency key), unfinished ones re-executed — with zero lost and
    zero duplicated deliveries.  Recovery wall time (relaunch ->
    serving) and replay counters are priced per arm."""
    import signal
    import tempfile

    import jax

    from neural_networks_parallel_training_with_mpi_tpu.serve import wal
    from neural_networks_parallel_training_with_mpi_tpu.utils.faults import (
        FaultPlan,
    )

    devices = jax.devices()
    device_ms = 15.0
    clients, rpc, seed = 6, 4, 11
    want = clients * rpc
    kill_at, late_fire = 3, want - 6
    tmp = tempfile.mkdtemp(prefix="bench_ctrlplane_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    results: dict = {
        "mix": "long_prefill", "device_emulation_ms": device_ms,
        "clients": clients, "requests_per_client": rpc, "seed": seed,
        "roles": ["prefill", "decode"],
        "host_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity") else os.cpu_count(),
    }

    def driver_cmd(wal_dir: str, out: str) -> list:
        return [sys.executable, "-m",
                "neural_networks_parallel_training_with_mpi_tpu"
                ".serve.ctrlplane_driver",
                "--roles", "prefill,decode",
                "--clients", str(clients), "--rpc", str(rpc),
                "--seed", str(seed), "--mix", "long_prefill",
                "--step-sleep-ms", str(device_ms),
                "--wal-dir", wal_dir, "--out", out]

    def run_driver(label: str, wal_dir: str) -> dict:
        """One uncrashed driver life; returns its result doc plus the
        arm's wall time (launch + compile + load, driver-measured)."""
        out = os.path.join(tmp, f"{label}.json")
        with open(os.path.join(tmp, f"{label}.stderr"), "w") as errf:
            t0 = time.perf_counter()
            subprocess.run(driver_cmd(wal_dir, out), env=env,
                           stderr=errf, check=True, timeout=900)
            wall = time.perf_counter() - t0
        with open(out) as f:
            doc = json.load(f)
        doc["arm_wall_s"] = round(wall, 3)
        return doc

    def wal_progress(wal_dir: str) -> tuple:
        """(completed, committed-handoffs-still-inflight) — read-only
        replay against the LIVE journal."""
        recs, _ = wal.replay(wal_dir, repair=False)
        done = {r.get("rid") for r in recs if r.get("kind") == "complete"}
        inflight = sum(1 for r in recs if r.get("kind") == "handoff"
                       and r.get("rid") not in done)
        return len(done), inflight

    def crash_arm(label: str, kind: str) -> dict:
        """Life 1 under a ``kind@kill_at`` fault plan (fired by the
        parent — the victim cannot SIGKILL itself), then relaunch on
        the same WAL dir and let life 2 run to completion."""
        wal_dir = os.path.join(tmp, f"wal_{label}")
        out1 = os.path.join(tmp, f"{label}_life1.json")
        plan = FaultPlan.parse(f"{kind}@{kill_at}?max=1")
        fired, kill_done, kill_inflight = False, 0, 0
        with open(os.path.join(tmp, f"{label}_life1.stderr"),
                  "w") as errf:
            p = subprocess.Popen(driver_cmd(wal_dir, out1), env=env,
                                 stderr=errf, start_new_session=True)
            t0 = time.perf_counter()
            while p.poll() is None and time.perf_counter() - t0 < 600:
                done, inflight = wal_progress(wal_dir)
                # fleet_kill waits for a committed handoff inflight
                # (falling back to a late fire so a fast decode pool
                # cannot starve the arm); gate BEFORE fire_if_due so
                # an unmet precondition does not consume the fire
                ok = (kind != "fleet_kill" or inflight > 0
                      or done >= late_fire)
                if ok and plan.fire_if_due(kind, done):
                    if kind == "fleet_kill":
                        os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                    else:
                        os.kill(p.pid, signal.SIGKILL)
                    fired, kill_done, kill_inflight = True, done, inflight
                    break
                time.sleep(0.1)
            p.wait(timeout=120)
        if kind == "router_kill":
            time.sleep(2.0)  # orphaned workers EOF -> drain -> exit 47
        doc2 = run_driver(f"{label}_life2", wal_dir)
        arm = {
            "fired": fired, "kill_at_completed": kill_done,
            "handoffs_inflight_at_kill": kill_inflight,
            "life1_rc": p.returncode,
            "resumed": doc2["resumed"],
            "recovery": doc2["recovery"],
            "recovery_wall_s": doc2["ready_wall_s"],
            "row": doc2["row"], "completed": doc2["completed"],
        }
        log(f"[ctrlplane {label}] fired={fired} "
            f"at_completed={kill_done} inflight={kill_inflight} "
            f"recovery={doc2['recovery']} "
            f"wall={doc2['ready_wall_s']:.2f}s")
        return arm

    # ---- steady state: wal off (x2 for the noise yardstick) vs on ----
    off_a = run_driver("wal_off_a", "")
    off_b = run_driver("wal_off_b", "")
    on = run_driver("wal_on", os.path.join(tmp, "wal_steady"))
    tps_off = [off_a["row"]["tokens_per_sec"],
               off_b["row"]["tokens_per_sec"]]
    tps_on = on["row"]["tokens_per_sec"]
    mean_off = sum(tps_off) / 2
    noise_pct = abs(tps_off[0] - tps_off[1]) / mean_off * 100
    overhead_pct = (mean_off - tps_on) / mean_off * 100
    results["wal_off"] = {"rows": [off_a["row"], off_b["row"]],
                          "arm_wall_s": [off_a["arm_wall_s"],
                                         off_b["arm_wall_s"]]}
    results["wal_on"] = {"row": on["row"],
                         "arm_wall_s": on["arm_wall_s"],
                         "wal": on["wal"]}
    log(f"[ctrlplane steady] off {tps_off[0]}/{tps_off[1]} tok/s "
        f"on {tps_on} tok/s overhead {overhead_pct:.1f}% "
        f"noise {noise_pct:.1f}%")

    # ---- crash arms ---------------------------------------------------
    rk = crash_arm("router_kill", "router_kill")
    fk = crash_arm("fleet_kill", "fleet_kill")
    results["router_kill"] = rk
    results["fleet_kill"] = fk

    pinned = [("wal_off_a", off_a["row"]), ("wal_off_b", off_b["row"]),
              ("wal_on", on["row"]), ("router_kill", rk["row"]),
              ("fleet_kill", fk["row"])]
    shas = {k: r["tokens_sha256"] for k, r in pinned}
    results["acceptance"] = {
        "tokens_sha256": shas,
        "tokens_identical_all_arms": len(set(shas.values())) == 1,
        "all_arms_completed":
            all(r["requests"] == want for _, r in pinned),
        "both_kills_fired": rk["fired"] and fk["fired"],
        "fleet_kill_handoffs_inflight":
            fk["handoffs_inflight_at_kill"] > 0,
        "zero_lost": (rk["recovery"]["lost"] == 0
                      and fk["recovery"]["lost"] == 0),
        # duplicates would surface as requests > want or a sha drift;
        # both are pinned above — this key states the dedupe evidence
        "zero_duplicated": all(r["requests"] == want for _, r in pinned)
            and len(set(shas.values())) == 1,
        "replayed_or_deduped": (
            rk["recovery"]["replayed"] + rk["recovery"]["deduped"] > 0
            and fk["recovery"]["replayed"]
            + fk["recovery"]["deduped"] > 0),
        "wal_overhead_pct": round(overhead_pct, 2),
        "run_noise_pct": round(noise_pct, 2),
        # 2pp allowance: two samples of a one-core host underestimate
        # the true spread
        "wal_overhead_below_noise":
            overhead_pct <= noise_pct + 2.0,
        "recovery_wall_s": {"router_kill": rk["recovery_wall_s"],
                            "fleet_kill": fk["recovery_wall_s"]},
    }
    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    out_path = _divert_cpu_overwrite(
        out_path, devices[0].platform not in ("cpu",))
    _emit_artifact(out_path, results, honesty={
        "device_emulation": True,   # decode ticks padded with emulated
        # device latency; one-core host time-slices the replicas
        "greedy_byte_identity_only": True,  # the cross-arm token pin
        # holds for greedy decode — tokens are a pure function of the
        # request plan, never of placement, crash timing, or recovery
    })
    acc = results["acceptance"]
    log(f"ctrlplane bench -> {out_path} "
        f"(tokens_identical={acc['tokens_identical_all_arms']}, "
        f"zero_lost={acc['zero_lost']}, "
        f"overhead {acc['wal_overhead_pct']}% vs "
        f"noise {acc['run_noise_pct']}%)")
    return out_path


def bench_autopilot(out_path: str = "BENCH_AUTOPILOT.json") -> str:
    """The fleet-autopilot bench (serve/autopilot.py): price the
    control loop.  Four arms, all on the BENCH_FLEET device-emulated
    regime (15 ms/tick replicas, prewarmed so TTFTs are steady-state):

    1. steady-state overhead — the same 2-replica saturating run with
       and without the autopilot attached (idle: min=max=2 pins the
       width, no rollout).  Its tick rides the pump loop, so any cost
       shows up directly in tokens/s; a per-tick microbenchmark pins
       the mechanism cost independent of run-to-run fleet noise.
    2. scale-out reaction — 1 replica under a saturating ramp with
       headroom to 2: time from the hysteresis-guarded scale_out
       decision to the new replica taking traffic, with the
       interactive class's deadline misses counted (target: zero —
       the fleet absorbs the ramp while the spawn is in flight).
    3. scale-in drain — 2 replicas under light load: the autopilot
       retires one through decommission (worker drains, exits 47);
       ledger-verified zero dropped/duplicated requests, drain wall
       time recorded.
    4. zero-downtime rollout — a verified weight snapshot pushed
       mid-load: canary spawn -> judged traffic slice -> promote ->
       old generation drained, with every completion attributed to
       its generation and the rollout wall time recorded."""
    import tempfile

    import jax

    from neural_networks_parallel_training_with_mpi_tpu.models import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        Autopilot, AutopilotConfig, launch_fleet,
        run_fleet_closed_loop, save_weight_snapshot,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        prng,
    )

    devices = jax.devices()
    device_ms = 15.0
    model = dict(vocab=256, seq=128, layers=2, d_model=64, heads=4,
                 d_ff=128, init_seed=0)
    serve = dict(slots=4, block_size=16, prefill_chunk=32,
                 queue_depth=16)
    classes = [{"name": "interactive", "slo_ms": 8000.0},
               {"name": "bulk", "slo_ms": None}]
    results: dict = {
        "model": model, "serve_per_replica": serve,
        "device_emulation_ms": device_ms,
        "baseline_artifact": "BENCH_FLEET.json",
    }

    def run_arm(n, clients, rpc, *, ap_cfg=None, rollout_after=0.0,
                snapshot=None, seed=1, cls=classes):
        fleet = launch_fleet(
            n, model=model, serve=serve, step_sleep_ms=device_ms,
            router_kwargs=dict(queue_depth=128),
            prewarm=True, max_restarts=1, log=lambda m: None)
        ap_obj = None
        try:
            fleet.wait_ready(600)
            if ap_cfg is not None:
                ap_obj = Autopilot(fleet, ap_cfg)
                fleet.autopilot = ap_obj
                if rollout_after > 0:
                    t0 = time.monotonic()
                    fired = []
                    orig_tick = ap_obj.tick

                    def tick():
                        if (not fired and time.monotonic() - t0
                                >= rollout_after):
                            fired.append(True)
                            ap_obj.start_rollout(snapshot)
                        return orig_tick()

                    ap_obj.tick = tick
            row = run_fleet_closed_loop(
                fleet, clients, rpc, vocab_size=model["vocab"],
                prompt_lens=(4, 24), max_new=(8, 24), seed=seed,
                classes=cls)
            row["per_generation_completed"] = \
                fleet.router.per_generation_completed()
            if ap_obj is not None:
                row["decisions"] = ap_obj.decisions
            return row
        finally:
            fleet.close()

    def decision(row, action):
        return next((d for d in row.get("decisions", [])
                     if d["action"] == action), None)

    # ---- arm 1: steady-state overhead --------------------------------
    plain = run_arm(2, clients=12, rpc=6)
    pinned = AutopilotConfig(min_replicas=2, max_replicas=2,
                             interval_s=0.1)
    attached = run_arm(2, clients=12, rpc=6, ap_cfg=pinned)
    overhead_pct = round(
        100.0 * (plain["tokens_per_sec"] - attached["tokens_per_sec"])
        / plain["tokens_per_sec"], 2)
    # the mechanism cost, isolated from fleet run-to-run noise: time
    # raw control evaluations against an idle stand-in whose width
    # bounds (0..0) make every tick a pure evaluate-and-decline
    class _IdleRouter:
        replicas: list = []
        queue: list = []
        requeued = 0
        _primary_gen = 0

    class _IdleFleet:
        router = _IdleRouter()

        @staticmethod
        def replica_done(name):
            return None

    idle = Autopilot(_IdleFleet(), AutopilotConfig(
        interval_s=0.0, min_replicas=0, max_replicas=0))
    t0 = time.perf_counter()
    for _ in range(1000):
        idle.tick()
    tick_us = round((time.perf_counter() - t0) * 1e6 / 1000, 1)
    results["steady_state"] = {
        "tokens_per_sec_plain": plain["tokens_per_sec"],
        "tokens_per_sec_autopilot": attached["tokens_per_sec"],
        "overhead_pct": overhead_pct,
        "tick_cost_us": tick_us,
        "autopilot_actions": len(attached.get("decisions", [])),
        "note": ("same saturating 2-replica run; the autopilot is "
                 "attached but width-pinned, so every tick is a pure "
                 "evaluate-and-decline — the honest overhead shape; "
                 "tick_cost_us is the microbenchmarked mechanism cost "
                 "(fleet tokens/s has ~few-percent run-to-run noise)"),
    }
    log(f"[autopilot steady] {plain['tokens_per_sec']} vs "
        f"{attached['tokens_per_sec']} tok/s ({overhead_pct}% "
        f"overhead, tick {tick_us}us)")

    # ---- arm 2: scale-out reaction under a ramp ----------------------
    ramp_cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                               interval_s=0.1, scale_out_hold_s=0.5,
                               cooldown_s=2.0)
    ramp = run_arm(1, clients=12, rpc=30, ap_cfg=ramp_cfg, seed=2)
    out_d = decision(ramp, "scale_out")
    ready_d = decision(ramp, "scale_out_ready")
    results["scale_out"] = {
        "decided_at_s": out_d and out_d["t"],
        "reaction_s": ready_d and ready_d["reaction_s"],
        "deadline_missed_interactive":
            ramp.get("deadline_missed_interactive", 0),
        "tokens_per_sec": ramp["tokens_per_sec"],
        "per_replica_completed": ramp["per_replica_completed"],
        "note": ("reaction_s = hysteresis-guarded decision -> new "
                 "replica taking traffic; dominated by the spawned "
                 "worker's jax import + prewarm compiles on this "
                 "host, not by the control loop"),
    }
    log(f"[autopilot ramp] scale_out at {out_d and out_d['t']}s, "
        f"ready after {ready_d and ready_d['reaction_s']}s, "
        f"misses {ramp.get('deadline_missed_interactive', 0)}")

    # ---- arm 3: scale-in drain (no-drop decommission) ----------------
    in_cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                             interval_s=0.1, scale_in_hold_s=1.5,
                             cooldown_s=2.0)
    light = run_arm(2, clients=2, rpc=25, ap_cfg=in_cfg, seed=3)
    in_d = decision(light, "scale_in")
    drain_d = decision(light, "drained")
    submitted = 2 * 25
    completed = sum(light["per_replica_completed"].values())
    results["scale_in"] = {
        "decided_at_s": in_d and in_d["t"],
        "drain_wall_s": drain_d and drain_d["wall_s"],
        "drain_rc": drain_d and drain_d["rc"],
        "drain_requeued": drain_d and drain_d["requeued"],
        "submitted": submitted,
        "completed": completed,
        "ledger_exact": bool(completed == submitted
                             == light["requests"]),
        "note": ("worker drains its scheduler and exits 47 "
                 "(EXIT_DECOMMISSION, terminal — no restart-budget "
                 "burn); in-flight work requeues exactly once through "
                 "the router ledger"),
    }
    log(f"[autopilot scale-in] drain {drain_d and drain_d['wall_s']}s "
        f"rc={drain_d and drain_d['rc']} ledger_exact="
        f"{results['scale_in']['ledger_exact']}")

    # ---- arm 4: zero-downtime weight rollout -------------------------
    tdir = tempfile.mkdtemp(prefix="bench-autopilot-")
    m = Transformer(TransformerConfig(
        vocab_size=model["vocab"], max_seq_len=model["seq"],
        n_layers=model["layers"], d_model=model["d_model"],
        n_heads=model["heads"], d_ff=model["d_ff"]))
    snap = save_weight_snapshot(
        tdir, m.init(prng.init_key(model["init_seed"])), step=1)
    roll_cfg = AutopilotConfig(min_replicas=2, max_replicas=4,
                               interval_s=0.1, canary_window_s=4.0,
                               canary_fraction=0.25)
    roll = run_arm(2, clients=8, rpc=110, ap_cfg=roll_cfg,
                   rollout_after=2.0, snapshot=snap, seed=4)
    done_d = decision(roll, "rollout_complete")
    promote_d = decision(roll, "canary_promote")
    per_gen = roll["per_generation_completed"]
    results["rollout"] = {
        "wall_s": done_d and done_d["wall_s"],
        "promoted": promote_d is not None,
        "canary_verdict": promote_d and {
            k: promote_d[k]
            for k in ("completed", "missed", "miss_frac", "p50_ratio")},
        "per_generation_completed": per_gen,
        "all_attributed": bool(sum(per_gen.values())
                               == roll["requests"]),
        "requeued": roll["requeued"],
        "tokens_per_sec": roll["tokens_per_sec"],
        "note": ("verified snapshot pushed mid-load: canary spawn -> "
                 "judged 25% slice -> promote -> old generation "
                 "drained through the same no-drop decommission; "
                 "every completion carries its generation"),
    }
    log(f"[autopilot rollout] wall {done_d and done_d['wall_s']}s "
        f"per_gen {per_gen} promoted={promote_d is not None}")

    results["acceptance"] = {
        "steady_state_overhead_pct": overhead_pct,
        "tick_cost_us": tick_us,
        "scale_out_reaction_s": ready_d and ready_d["reaction_s"],
        "ramp_zero_deadline_misses":
            int(ramp.get("deadline_missed_interactive", 0)) == 0,
        "scale_in_ledger_exact": results["scale_in"]["ledger_exact"],
        "rollout_promoted": promote_d is not None,
        "rollout_wall_s": done_d and done_d["wall_s"],
        "rollout_all_tokens_attributed":
            results["rollout"]["all_attributed"],
    }
    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    out_path = _divert_cpu_overwrite(
        out_path, devices[0].platform not in ("cpu",))
    _emit_artifact(out_path, results)
    log(f"autopilot bench -> {out_path} (overhead {overhead_pct}%, "
        f"reaction {ready_d and ready_d['reaction_s']}s)")
    return out_path


def bench_chaos(out_path: str = "BENCH_CHAOS.json") -> str:
    """The chaos-campaign bench (utils/chaos.py): run the ``full``
    plan — stub crash-vs-notice A/B plus three real-subprocess-fleet
    failures (SIGKILL mid-load, advance-notice drain with backfill,
    degraded-replica health eviction) — twice, gate on every
    invariant, and report the recovery prices: MTTR, reaction time,
    requeued requests, tokens lost, and the crash-vs-notice goodput
    split (rollback + relaunch_gap collapsing to drain when the
    failure is announced).  The campaign's wall-clock-free canonical
    digest must match across both passes — reproducibility IS one of
    the acceptance gates, not a side note."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.utils import (
        chaos,
    )

    devices = jax.devices()
    plan = chaos.load_plan("full")
    doc = chaos.run_campaign(plan, repeat=2, log=log)

    scenarios: dict = {}
    for r in doc["scenarios"]:
        scenarios[r["name"]] = {
            "mode": r.get("mode", r.get("fault")),
            "invariants": r["invariants"],
            "metrics": r["metrics"],
            "wall_s": r["wall_s"],
        }
    by = {r["name"]: r for r in doc["scenarios"]}
    crash = by.get("stub_crash", {}).get("metrics", {})
    notice = by.get("stub_preempt", {}).get("metrics", {})
    results: dict = {
        "plan": doc["plan"],
        "seed": doc["seed"],
        "scenarios": scenarios,
        "crash_vs_notice": {
            # the tentpole A/B: same failure point, announced vs not —
            # the notice arm's rollback and relaunch_gap must be zero
            "crash": {
                "mttr_s": crash.get("mttr_s"),
                "rollback_s":
                    crash.get("categories", {}).get("rollback", 0.0),
                "relaunch_gap_s":
                    crash.get("categories", {}).get("relaunch_gap",
                                                    0.0),
            },
            "notice": {
                "mttr_s": notice.get("mttr_s"),
                "rollback_s":
                    notice.get("categories", {}).get("rollback", 0.0),
                "relaunch_gap_s":
                    notice.get("categories", {}).get("relaunch_gap",
                                                     0.0),
                "drain_s":
                    notice.get("categories", {}).get("drain", 0.0),
            },
        },
        "determinism": doc["determinism"],
        "invariants_ok": doc["invariants_ok"],
        "problems": doc["problems"],
    }
    results["acceptance"] = {
        "all_invariants_held": doc["invariants_ok"],
        "reproducible": doc["determinism"]["reproducible"],
        "notice_zero_rollback":
            notice.get("categories", {}).get("rollback", 0.0) == 0.0,
        "notice_zero_relaunch_gap":
            notice.get("categories", {}).get("relaunch_gap",
                                             0.0) == 0.0,
        "notice_fleet_zero_requeue":
            by.get("fleet_preempt_notice", {})
              .get("metrics", {}).get("requeued") == 0,
        "evict_p99_recovered":
            by.get("fleet_slow_evict", {})
              .get("invariants", {}).get("p99_itl_recovered", False),
    }
    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    out_path = _divert_cpu_overwrite(
        out_path, devices[0].platform not in ("cpu",))
    _emit_artifact(out_path, results, honesty={
        "stub_scenarios_no_jax": True,   # supervised span-emitting
        # stdlib children stand in for trainers in the stub arms; the
        # fleet arms are real subprocess replicas under load
        "digest_excludes_wall_clock": True,  # canonical digest drops
        # timing-jittered metrics and contingent escalation actions
    })
    log(f"chaos bench -> {out_path} "
        f"(invariants_ok={doc['invariants_ok']}, "
        f"reproducible={doc['determinism']['reproducible']})")
    return out_path


def bench_paged_attn(out_path: str = "BENCH_PAGED_ATTN.json") -> str:
    """The fused paged-attention bench (ops.pallas_kernels.paged_attention
    behind serve/paged_kv.py's ``attn_impl`` seam): (1) a gathered-vs-
    fused decode A/B at RAGGED stream lengths — same model, same pool
    geometry, same admitted streams, only the attention dispatch differs
    — asserting token identity and recording per-step wall time; (2) an
    attended-keys accounting sweep through the scheduler at three
    prompt-length mixes, recording attended/padded/kernel key positions
    and their ratio from the ``kind="serve"`` telemetry counters.

    The TPU-facing claim is the FLOPs/bandwidth one: the fused kernel
    walks ``sum(ceil(len/bs))`` blocks instead of reducing over
    ``streams*max_blocks*bs`` keys, and attended/padded < 1 at ragged
    lengths IS that win, measured.  The CPU arm runs the kernel in
    interpret mode at a LONG-context geometry (max_len 1024) — the
    regime the kernel exists for, and where the skipped reduction
    outweighs interpret mode's fixed per-program cost, so the step-time
    parity gate is honest on both platforms."""
    import jax
    import jax.numpy as jnp

    from neural_networks_parallel_training_with_mpi_tpu.models import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        PagedDecodeServer, Scheduler, ServeConfig, run_closed_loop,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    cd = jnp.bfloat16 if on_tpu else jnp.float32
    c = (dict(_LM, block=16) if on_tpu else
         dict(vocab=256, seq=1024, d_model=64, n_layers=2, n_heads=4,
              d_ff=128, block=128))
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=c["seq"], n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"],
        compute_dtype=cd))
    params = model.init(prng.init_key(0))
    results: dict = {"model": {k: c[k] for k in
                               ("vocab", "seq", "d_model", "n_layers")}}

    # --- gathered vs fused at ragged lengths ---------------------------
    block_size = c["block"]
    slots = 8
    max_len = c["seq"]
    num_blocks = 1 + slots * (max_len // block_size)
    timed_steps = 12
    reps = 1 if on_tpu else _CPU_TIMING_REPS
    # every stream must stay live through warmup + ALL timed windows
    # (best-of-reps times back-to-back windows in ONE session — the
    # untimed admit/prefill/drain cost is paid once, not per rep)
    new_tok = 2 + reps * timed_steps + 4
    # ragged prompt lengths spanning short to near-max (minus headroom
    # for the generated tokens): the regime where a fixed max_blocks*bs
    # reduction wastes the most
    raw = [s * max_len // 1024 for s in
           (16, 48, 96, 160, 320, 512, 768, 1024)]
    plens = [max(1, min(p, max_len - new_tok - 1)) for p in raw]
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, c["vocab"], (p,)).tolist() for p in plens]

    def ab_pass(impl: str):
        srv = PagedDecodeServer(model, params, slots=slots,
                                num_blocks=num_blocks,
                                block_size=block_size, max_len=max_len,
                                attn_impl=impl)
        rids = [srv.try_admit(p, new_tok) for p in prompts]
        assert all(r is not None for r in rids)
        for r in rids:
            while not srv.prefill_step(r, 64):
                pass
        for _ in range(2):                       # warm the decode program
            srv.step()
        jax.block_until_ready(srv.tokens)
        acct = srv.keys_accounting()
        step_ms = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(timed_steps):
                srv.step()
            jax.block_until_ready(srv.tokens)
            step_ms = min(step_ms,
                          (time.perf_counter() - t0) / timed_steps * 1e3)
        while any(not srv.done(r) for r in rids):
            srv.step()
        toks = [srv.result(r) for r in rids]
        srv.allocator.assert_drained()
        return toks, step_ms, acct

    gathered_toks, g_ms, acct = ab_pass("gathered")
    fused_toks, f_ms, _ = ab_pass("fused")
    assert fused_toks == gathered_toks, \
        "fused decode diverged from the gathered parity reference"
    results["ragged_ab"] = {
        "prompt_lens": plens,
        "new_tokens": new_tok,
        "block_size": block_size,
        "max_blocks": -(-max_len // block_size),
        "timed_steps": timed_steps,
        "timing_reps": reps,
        "step_ms_gathered": round(g_ms, 3),
        "step_ms_fused": round(f_ms, 3),
        "fused_over_gathered": round(f_ms / max(1e-9, g_ms), 3),
        "tokens_identical": True,
        # the accounting at the timed window's start: what each impl
        # reduces over per decode step
        "attended_keys": acct["attended_keys"],
        "kernel_keys": acct["kernel_keys"],
        "padded_keys": acct["padded_keys"],
        "attended_over_padded": round(
            acct["attended_keys"] / max(1, acct["padded_keys"]), 4),
    }

    # --- attended-keys accounting sweep through the scheduler ----------
    sweep = []
    mixes = ((max(1, max_len // 64), max_len // 16),
             (max(1, max_len // 32), max_len // 8),
             (max(1, max_len // 8), max_len // 2))
    for lo, hi in mixes:
        sched = Scheduler(model, params, ServeConfig(
            slots=slots, num_blocks=num_blocks, block_size=block_size,
            max_len=max_len, prefill_chunk=64, attn_impl="fused"))
        try:
            row = run_closed_loop(
                sched, clients=4, requests_per_client=2,
                vocab_size=c["vocab"], prompt_lens=(lo, hi),
                max_new=(8, 24), seed=2)
            ratio = (sched.attended_keys / sched.padded_keys
                     if sched.padded_keys else None)
            sweep.append({
                "prompt_lens": [lo, hi],
                "requests": row["requests"],
                "attended_keys": sched.attended_keys,
                "padded_keys": sched.padded_keys,
                "kernel_keys": sched.kernel_keys,
                "attended_ratio": round(ratio, 4),
                # the kernel's whole-block walk vs the exact need: block
                # quantization overhead, bounded by bs/(bs+1) per stream
                "kernel_over_attended": round(
                    sched.kernel_keys / max(1, sched.attended_keys), 4),
            })
            assert ratio is not None and ratio < 1.0, \
                "ragged lengths must leave attended/padded below 1"
        finally:
            sched.close()
    results["accounting_sweep"] = sweep

    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    results["n_devices"] = len(devices)
    if not on_tpu:
        results["note"] = (
            "CPU fallback: the Pallas kernel runs in interpret mode at "
            "a long-context geometry (max_len 1024, block 128) where "
            "the skipped reduction beats interpret mode's fixed "
            "per-program cost; the platform-independent evidence is "
            "tokens_identical plus the attended/padded accounting (the "
            "FLOPs the fused kernel skips), the chip capture overwrites "
            "the timings")
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, results)
    log(f"paged-attention bench -> {out_path}")
    return out_path


def bench_prefix_cache(out_path: str = "BENCH_PREFIX_CACHE.json") -> str:
    """The prefix-cache bench (serve/paged_kv.py ``prefix_cache``): a
    cache-OFF vs cache-ON A/B of the full continuous-batching service
    loop at varying shared-prefix traffic ratios.  Both arms serve the
    BYTE-IDENTICAL pre-generated request stream (serve.loadgen.
    make_requests), and the row-level sha256 over every request's output
    tokens pins greedy decode bitwise-equal cache on vs off — the
    parity claim — while the deltas measure the two wins: cached-prefix
    TTFT (admission skips the matched prefill chunks) and steady-state
    blocks-in-use (shared blocks are resident once).  Interleaved
    OFF/ON pairs per mix (DESIGN S7: grouping arms would let shared-host
    load drift masquerade as a delta); the shared prefix length is NOT
    block-aligned, so every shared-suffix admission also exercises the
    copy-on-write fork path under measurement."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.models import (
        Transformer, TransformerConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.serve import (
        Scheduler, ServeConfig, prewarm, run_closed_loop,
    )
    from neural_networks_parallel_training_with_mpi_tpu.utils import prng

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    c = (_LM if on_tpu else
         dict(vocab=256, seq=128, d_model=64, n_layers=2, n_heads=4,
              d_ff=128))
    model = Transformer(TransformerConfig(
        vocab_size=c["vocab"], max_seq_len=c["seq"], n_layers=c["n_layers"],
        d_model=c["d_model"], n_heads=c["n_heads"], d_ff=c["d_ff"]))
    params = model.init(prng.init_key(0))

    block_size = 16
    slots = 8
    max_len = c["seq"]
    num_blocks = 1 + slots * (max_len // block_size)
    # a small prefill chunk makes TTFT prefill-dominated (the quantity
    # the cache attacks); the pool is non-starved so eviction policy
    # stays out of the latency measurement
    base = dict(slots=slots, num_blocks=num_blocks, block_size=block_size,
                max_len=max_len, prefill_chunk=16)
    # 72 = 4.5 blocks: a long system prompt ending MID-block, so a
    # regenerated turn (0-token suffix — see loadgen.make_requests)
    # full-hits and FORKS (CoW) under measurement, while distinct-suffix
    # requests share the block-aligned 64 tokens
    shared_len = 72
    suffix_lens = (0, 12)
    new_tokens = (8, 16)
    clients, reqs_per_client, reps = 6, 3, 3
    workload = dict(shared_prefix_len=shared_len,
                    suffix_prompt_lens=list(suffix_lens),
                    max_new=list(new_tokens), clients=clients,
                    requests_per_client=reqs_per_client,
                    interleaved_pairs=reps, seed=7)

    def mk(on: bool):
        return Scheduler(model, params,
                         ServeConfig(**base, prefix_cache=on))

    # pay every compile BEFORE measuring: prefill buckets + decode for
    # both arms (same programs — prefix_cache is host-side), plus the
    # CoW fork program, which only the ON arm can draw (two prompts
    # sharing a non-aligned prefix force one fork)
    prewarm(lambda: mk(False), prompt_lens=(4, shared_len + suffix_lens[1]))
    warm = mk(True)
    try:
        a = warm.submit(list(range(1, shared_len + 3)), 2)
        warm.run_until_drained()
        b = warm.submit(list(range(1, shared_len + 3)) + [7], 2)
        warm.run_until_drained()
        warm.result(a), warm.result(b)
        assert warm.server.cow_forks >= 1, "CoW prewarm drew no fork"
    finally:
        warm.close()

    def med(vals):
        return round(float(np.median(np.asarray(vals, np.float64))), 3)

    mixes = []
    for frac in (0.0, 0.5, 0.9):
        pairs = []
        for rep in range(reps):
            pair = {}
            for arm, on in (("off", False), ("on", True)):
                sched = mk(on)
                try:
                    pair[arm] = run_closed_loop(
                        sched, clients, reqs_per_client,
                        vocab_size=c["vocab"], prompt_lens=suffix_lens,
                        max_new=new_tokens, seed=workload["seed"],
                        shared_prefix_len=shared_len,
                        shared_fraction=frac)
                finally:
                    sched.server.allocator.assert_drained()
                    sched.close()
            pairs.append(pair)
        ident = all(p["off"]["tokens_sha256"] == p["on"]["tokens_sha256"]
                    for p in pairs)
        ttft_key = ("ttft_ms_p50_shared" if frac > 0 else "ttft_ms_p50")
        cold = [p["off"][ttft_key] for p in pairs]
        cached = [p["on"][ttft_key] for p in pairs]
        row = {
            "shared_fraction": frac,
            "tokens_identical": ident,
            # the 0.0 mix has no shared class: its columns fall back to
            # the all-requests TTFT (a no-sharing baseline, not the
            # same population as the >0 mixes' shared-class numbers)
            "ttft_population": ("shared_class" if frac > 0
                                else "all_requests"),
            "ttft_ms_p50_shared_cold": med(cold),
            "ttft_ms_p50_shared_cached": med(cached),
            "ttft_cached_over_cold": round(
                med(cached) / max(1e-9, med(cold)), 4),
            "tokens_per_sec_off": med(
                [p["off"]["tokens_per_sec"] for p in pairs]),
            "tokens_per_sec_on": med(
                [p["on"]["tokens_per_sec"] for p in pairs]),
            "blocks_in_use_mean_off": med(
                [p["off"]["blocks_in_use_mean"] for p in pairs]),
            "blocks_in_use_mean_on": med(
                [p["on"]["blocks_in_use_mean"] for p in pairs]),
            "blocks_in_use_peak_off": max(
                p["off"]["blocks_in_use_peak"] for p in pairs),
            "blocks_in_use_peak_on": max(
                p["on"]["blocks_in_use_peak"] for p in pairs),
            "ticks_off": med([p["off"]["ticks"] for p in pairs]),
            "ticks_on": med([p["on"]["ticks"] for p in pairs]),
            "prefix_cache_stats": pairs[-1]["on"].get("prefix_cache"),
        }
        mixes.append(row)
        log(f"[prefix-cache] frac={frac}: TTFT "
            f"{row['ttft_ms_p50_shared_cold']} -> "
            f"{row['ttft_ms_p50_shared_cached']} ms, "
            f"blocks {row['blocks_in_use_mean_off']} -> "
            f"{row['blocks_in_use_mean_on']}, identical={ident}")

    shared_mix = next(m for m in mixes if m["shared_fraction"] >= 0.5)
    results = {
        "model": {k: c[k] for k in ("vocab", "seq", "d_model", "n_layers")},
        "serve_config": base,
        "workload": workload,
        "mixes": mixes,
        "acceptance": {
            "tokens_bitwise_identical_all_mixes": all(
                m["tokens_identical"] for m in mixes),
            "cached_ttft_below_cold_at_50pct_mix": (
                shared_mix["ttft_ms_p50_shared_cached"]
                < shared_mix["ttft_ms_p50_shared_cold"]),
            "blocks_in_use_drop_at_50pct_mix": (
                shared_mix["blocks_in_use_mean_on"]
                < shared_mix["blocks_in_use_mean_off"]),
        },
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_devices": len(devices),
        "note": ("interleaved OFF/ON pairs per mix; the parity evidence "
                 "(tokens_identical) and the CURVES (cached vs cold "
                 "TTFT, blocks-in-use vs shared fraction) are platform-"
                 "independent; absolute tokens/s on the CPU fallback is "
                 "a mechanism check at tiny shapes"),
    }
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, results)
    log(f"prefix-cache bench -> {out_path}")
    return out_path


def bench_rl(out_path: str = "BENCH_RL.json") -> str:
    """The RL-workload bench (rl/): Anakin actor-learner throughput —
    env frames/s and updates/s of the fused rollout+GAE+PPO step at >= 2
    env counts on the full device mesh — plus a steps-to-reward probe:
    train gridworld PPO from scratch and record how many updates (and
    env frames) the EMA return needs to clear the target, against a
    measured random-policy (lr=0) baseline.  On the CPU fallback the
    absolute frames/s are mechanism checks at tiny shapes; the
    steps-to-reward numbers are platform-independent evidence the
    workload actually learns."""
    import jax

    from neural_networks_parallel_training_with_mpi_tpu.config import (
        MeshConfig, ModelConfig,
    )
    from neural_networks_parallel_training_with_mpi_tpu.models.registry import (
        build_model,
    )
    from neural_networks_parallel_training_with_mpi_tpu.ops import optim
    from neural_networks_parallel_training_with_mpi_tpu.parallel import (
        mesh as mesh_lib,
    )
    from neural_networks_parallel_training_with_mpi_tpu.rl import (
        anakin, envs,
    )

    devices = jax.devices()
    n_dev = len(devices)
    on_tpu = devices[0].platform not in ("cpu",)
    env = envs.make_env("gridworld")
    T, ppo_epochs, hidden = 32, 4, (64, 64)
    model = build_model(ModelConfig(
        arch="mlp", in_features=env.obs_dim, hidden=hidden,
        out_features=env.n_actions + 1))
    results: dict = {
        "env": "gridworld", "rollout_steps": T, "ppo_epochs": ppo_epochs,
        "policy_hidden": list(hidden),
        "flops_per_frame": anakin.anakin_step_flops(model, env.obs_dim,
                                                    T, ppo_epochs),
    }
    mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev))

    # --- throughput at >= 2 env counts ---------------------------------
    env_counts = [8 * n_dev, 32 * n_dev]
    if on_tpu:
        env_counts.append(128 * n_dev)
    timed_steps = 10
    rows = []
    for n_envs in env_counts:
        opt = optim.adam(lr=3e-3)
        state = anakin.place_rl_state(
            anakin.init_rl_state(env, model, opt, n_envs, seed=0), mesh)
        step = anakin.make_anakin_step(
            env, model, opt, mesh, rollout_steps=T, ppo_epochs=ppo_epochs)
        state, out = step(state)            # compile + warm
        jax.block_until_ready(out)
        best = None
        for _rep in range(1 if on_tpu else _CPU_TIMING_REPS):
            t0 = time.perf_counter()
            for _ in range(timed_steps):
                state, out = step(state)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        frames = timed_steps * T * n_envs
        rows.append({
            "n_envs": n_envs,
            "frames_per_update": T * n_envs,
            "env_frames_per_sec": round(frames / best, 1),
            "updates_per_sec": round(timed_steps / best, 3),
            "step_ms": round(best / timed_steps * 1e3, 3),
        })
        log(f"[rl] {n_envs} envs: "
            f"{rows[-1]['env_frames_per_sec']:,.0f} frames/s, "
            f"{rows[-1]['updates_per_sec']:.2f} updates/s")
    results["throughput"] = rows

    # --- steps-to-reward (learning evidence, platform-independent) ------
    def run_returns(lr: float, n_updates: int, n_envs: int = 8 * n_dev):
        opt = optim.adam(lr=lr)
        state = anakin.place_rl_state(
            anakin.init_rl_state(env, model, opt, n_envs, seed=1), mesh)
        step = anakin.make_anakin_step(
            env, model, opt, mesh, rollout_steps=T, ppo_epochs=ppo_epochs)
        ema = None
        trace = []
        for _ in range(n_updates):
            state, out = step(state)
            ret = float(jax.device_get(out)["return_mean"])
            if np.isfinite(ret):
                ema = ret if ema is None else 0.9 * ema + 0.1 * ret
            trace.append(ema)
        return trace

    baseline_trace = run_returns(lr=0.0, n_updates=15)
    baseline = baseline_trace[-1]
    target = 0.85
    max_updates = 150
    trace = run_returns(lr=3e-3, n_updates=max_updates)
    to_target = next((i + 1 for i, v in enumerate(trace)
                      if v is not None and v >= target), None)
    results["steps_to_reward"] = {
        "random_policy_return_ema": (round(baseline, 4)
                                     if baseline is not None else None),
        "target_return_ema": target,
        "updates_to_target": to_target,
        "env_frames_to_target": (to_target * T * 8 * n_dev
                                 if to_target else None),
        "final_return_ema": (round(trace[-1], 4)
                             if trace[-1] is not None else None),
        "budget_updates": max_updates,
    }
    log(f"[rl] steps-to-reward: random baseline EMA {baseline}, target "
        f"{target} reached after {to_target} update(s)")

    results["platform"] = devices[0].platform
    results["device_kind"] = devices[0].device_kind
    results["n_devices"] = n_dev
    if not on_tpu:
        results["note"] = ("CPU fallback mechanism check: tiny policy MLP "
                           "on virtual devices — absolute frames/s not "
                           "meaningful; the steps-to-reward numbers are "
                           "the platform-independent evidence")
    out_path = _divert_cpu_overwrite(out_path, on_tpu)
    _emit_artifact(out_path, results)
    log(f"rl bench -> {out_path}")
    return out_path


def resolve_platform(requested: str) -> tuple[str, list]:
    """Return ('cpu'|'accel', probe_history) after hang-proof spaced probes.

    Each attempt runs in a fresh subprocess with a timeout; failed attempts
    back off linearly (attempt i sleeps i * PROBE_BACKOFF_S) so a tunnel
    that recovers mid-capture is still caught.  The per-attempt history
    (wall-clock timestamps + outcomes) is returned so the fallback JSON can
    prove the probing actually happened (VERDICT r2 item 1)."""
    if requested == "cpu":
        return "cpu", []
    history = []
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        t0 = time.time()
        info = plat.probe(timeout_s=PROBE_TIMEOUT_S, attempts=1, log=log)
        rec = {"attempt": attempt, "t_unix": round(t0, 1),
               "elapsed_s": round(time.time() - t0, 1)}
        if info and info["platform"] != "cpu":
            rec["outcome"] = f"ok:{info['platform']}:{info['device_kind']}"
            history.append(rec)
            log(f"probe: accelerator available: {info}")
            plat.unpin_cpu()  # stray JAX_PLATFORMS=cpu must not override
            return "accel", history
        rec["outcome"] = ("cpu_only" if info else "timeout_or_error")
        history.append(rec)
        # a cpu answer is definitive ("accelerator-less machine, stop
        # probing") ONLY when no TPU-tunnel plugin is configured in the
        # environment; with a tunnel configured, a fast cpu answer means
        # the plugin errored at init (tunnel endpoint restarting) and may
        # recover within the backoff window
        tunnel_configured = ("PALLAS_AXON_POOL_IPS" in os.environ
                             or "axon" in os.environ.get("JAX_PLATFORMS", ""))
        if info is not None and not tunnel_configured:
            break
        if attempt < PROBE_ATTEMPTS:
            pause = attempt * PROBE_BACKOFF_S
            log(f"probe attempt {attempt}/{PROBE_ATTEMPTS} failed; retrying "
                f"in {pause:.0f}s")
            time.sleep(pause)
    if requested == "tpu":
        log("WARNING: --platform tpu requested but the accelerator probe "
            "failed; falling back to cpu")
    else:
        log("probe: no accelerator; using cpu")
    return "cpu", history


def save_tpu_latest(records: list) -> None:
    """Persist every successful real-chip run, merged by metric, with
    capture provenance — the round's evidence if the tunnel later wedges."""
    tpu_recs = [r for r in records
                if r.get("platform") not in (None, "cpu") and r.get("value")
                # ablated (collectives-removed) runs are measurement
                # scaffolding, never the canonical real-chip record
                and r.get("grad_reduction") in (None, "global_mean")]
    if not tpu_recs:
        return
    merged = {}
    try:
        with open(TPU_LATEST_PATH) as f:
            merged = {r["metric"]: r for r in json.load(f).get("records", [])}
    except (OSError, ValueError, KeyError):
        pass
    for r in tpu_recs:
        merged[r["metric"]] = r
    doc = {
        "note": "latest successful real-accelerator bench runs (merged by "
                "metric); written opportunistically by bench.py",
        "captured_unix": round(time.time(), 1),
        "captured_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "device_kind": tpu_recs[0].get("device_kind"),
        "records": sorted(merged.values(), key=lambda r: r["metric"]),
    }
    _emit_artifact(TPU_LATEST_PATH, doc)
    log(f"TPU provenance record -> {TPU_LATEST_PATH}")


# shapes the pre-"config"-stamp BIGLM_SWEEP.json rows were measured at
# (round-4 windows); consulted wherever a stamped row is required so a
# stale row cannot masquerade as the current config after _BIG changes
LEGACY_SWEEP_SHAPES = dict(vocab=32768, seq=1024, d_model=1024,
                           n_layers=12, n_heads=16, d_ff=4096)


def merge_artifact_rows(path: str, new_rows: list, key: str = "label"
                        ) -> list:
    """Label-keyed merge of measurement rows across scarce tunnel windows
    (shared by tools/big_lm_sweep.py and tools/big_lm_attrib.py): a new
    successful row replaces the prior one; an ERROR row never clobbers a
    prior success (those take a rare window to reproduce); prior rows for
    labels not re-run this window are kept."""
    prior = {}
    try:
        with open(path) as f:
            for row in json.load(f).get("results", []):
                if row.get(key):
                    prior[row[key]] = row
    except (OSError, ValueError):
        pass
    merged = []
    for row in new_rows:
        if "error" in row and "error" not in prior.get(row[key],
                                                       {"error": 1}):
            row = prior[row[key]]
        merged.append(row)
        prior.pop(row[key], None)
    merged.extend(prior.values())
    return merged


def committed_big_lm_sweep_row(mc, batch: int,
                               return_doc: bool = False):
    """The BIGLM_SWEEP.json TPU row measured at EXACTLY the committed
    big_lm configuration (shapes + batch + remat/attention/ce_chunk/
    scan_layers + kernel-tile overrides), or None.  Shared by the
    preflight's chip_validated gate and the CPU-fallback headline: a row
    only speaks for the committed config if every knob matches.
    ``return_doc=True`` returns ``(row, parsed_doc)`` so the caller can
    read capture timestamps without re-parsing the artifact."""
    sweep_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BIGLM_SWEEP.json")
    try:
        with open(sweep_path) as f:
            doc = json.load(f)
        rows = doc.get("results", [])
    except (OSError, ValueError):
        return (None, None) if return_doc else None
    match = None
    for row in rows:
        if ("error" not in row
                and row.get("platform") == "tpu"
                and row.get("config", LEGACY_SWEEP_SHAPES) == _BIG
                and row.get("batch") == batch
                and row.get("remat") == mc.remat
                and (not mc.remat or row.get("policy") == mc.remat_policy)
                and row.get("attention") == mc.attention
                and row.get("ce_chunk", 0) == mc.ce_chunk
                and row.get("scan_layers", True) == mc.scan_layers
                and row.get("tf_overrides", {}).get(
                    "flash_block_q", 128) == mc.flash_block_q
                and row.get("tf_overrides", {}).get(
                    "flash_block_k", 128) == mc.flash_block_k):
            match = row
            break
    return (match, doc) if return_doc else match


def load_tpu_latest() -> dict | None:
    try:
        with open(TPU_LATEST_PATH) as f:
            doc = json.load(f)
        doc["age_hours"] = round((time.time() - doc["captured_unix"]) / 3600,
                                 2)
        return doc
    except (OSError, ValueError, KeyError, TypeError):
        return None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", choices=sorted(METRIC_NAMES), default="wide")
    ap.add_argument("--batch", type=int, default=0,
                    help="override the config's global batch size "
                         "(weak-scaling children use this)")
    ap.add_argument("--platform", choices=["auto", "cpu", "tpu"], default="auto")
    ap.add_argument("--all", action="store_true",
                    help="bench every config (BASELINE.json's five + the "
                         "moe extra), write BENCH_FULL.json")
    ap.add_argument("--scaling", action="store_true",
                    help="weak-scaling sweep (fixed per-device batch, 1..8 "
                         "virtual devices), write BENCH_SCALING.json")
    ap.add_argument("--attention", action="store_true",
                    help="flash vs dense and ring vs ring_flash step-time "
                         "comparison, write BENCH_ATTENTION.json")
    ap.add_argument("--attention-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--decode", action="store_true",
                    help="serving decode tokens/sec comparison (dense vs "
                         "batch-sharded vs tensor-parallel), write "
                         "BENCH_DECODE.json")
    ap.add_argument("--decode-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--serve", action="store_true",
                    help="serving-subsystem bench (serve/): closed-loop "
                         "load sweep of the paged-KV continuous-batching "
                         "scheduler (tokens/s, p50/p99 TTFT/ITL vs. "
                         "offered load), paged-vs-dense capacity at "
                         "equal memory, host-sync-fix delta; write "
                         "BENCH_SERVE.json")
    ap.add_argument("--serve-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--serve-fleet", action="store_true",
                    help="serving-fleet bench (serve/fleet.py): "
                         "aggregate tokens/s vs replica count (1/2/4 "
                         "subprocess replicas under the group "
                         "supervisor + SLO-aware router) at saturating "
                         "load, per-class TTFT percentiles, router "
                         "overload rejection; write BENCH_FLEET.json")
    ap.add_argument("--serve-fleet-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--serve-disagg", action="store_true",
                    help="disaggregated prefill/decode bench "
                         "(serve/fleet.py role pools + handoff "
                         "ledger): unified-vs-disagg decode-ITL A/B "
                         "under a long-prompt mix, degraded single-"
                         "pool arm, one chaos arm per fleet fault "
                         "kind, byte-identical tokens across every "
                         "arm; write BENCH_DISAGG.json")
    ap.add_argument("--serve-disagg-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--ctrlplane", action="store_true",
                    help="durable-control-plane bench (serve/wal.py + "
                         "router recovery): WAL-off-vs-on steady-state "
                         "overhead, SIGKILL of the router process and "
                         "of the whole fleet mid-load with relaunch-"
                         "and-replay, exactly-once delivery pinned by "
                         "one tokens_sha256 across crash and no-crash "
                         "arms; write BENCH_CTRLPLANE.json")
    ap.add_argument("--ctrlplane-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--autopilot", action="store_true",
                    help="fleet-autopilot bench (serve/autopilot.py): "
                         "steady-state control-loop overhead vs "
                         "BENCH_FLEET, scale-out reaction time under "
                         "a ramp, no-drop scale-in drain cost, zero-"
                         "downtime weight-rollout wall time with per-"
                         "generation attribution; write "
                         "BENCH_AUTOPILOT.json")
    ap.add_argument("--autopilot-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--chaos", action="store_true",
                    help="chaos-campaign bench (utils/chaos.py): run "
                         "the 'full' deterministic failure plan twice "
                         "— crash-vs-notice stub A/B plus SIGKILL / "
                         "advance-notice drain / health-eviction "
                         "against a real subprocess fleet — gate on "
                         "every invariant and the cross-pass canonical "
                         "digest; write BENCH_CHAOS.json")
    ap.add_argument("--chaos-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--serve-attn-impl", choices=["gathered", "fused"],
                    default="gathered",
                    help="attention dispatch for the --serve sweep: "
                         "'gathered' (pool[table] materialization, the "
                         "parity reference) or 'fused' (Pallas paged-"
                         "attention kernel)")
    ap.add_argument("--paged-attn", action="store_true",
                    help="fused paged-attention bench: gathered-vs-fused "
                         "decode A/B at ragged stream lengths (token-"
                         "identity asserted) + attended-keys accounting "
                         "sweep; write BENCH_PAGED_ATTN.json")
    ap.add_argument("--paged-attn-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--prefix-cache", action="store_true",
                    help="prefix-cache bench (serve/ prefix_cache): "
                         "interleaved cache-off/on A/B of the service "
                         "loop at 0/50/90%% shared-prefix traffic — "
                         "cached vs cold TTFT, blocks-in-use, tokens/s, "
                         "bitwise token-identity pin; write "
                         "BENCH_PREFIX_CACHE.json")
    ap.add_argument("--prefix-cache-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--rl", action="store_true",
                    help="RL-workload bench (rl/): Anakin actor-learner "
                         "env frames/s + updates/s at >= 2 env counts, "
                         "plus gridworld PPO steps-to-reward vs a "
                         "random-policy baseline; write BENCH_RL.json")
    ap.add_argument("--rl-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--update-sharding-ab", action="store_true",
                    help="interleaved A/B of replicated vs automatic-"
                         "sharded weight update (update_sharding="
                         "'sharded', parallel.update_sharding) at the "
                         "CPU-bench transformer scale: step_ms, per-"
                         "device opt-state bytes (~1/N), compiled-HLO "
                         "overlap evidence, donation audit, bf16 "
                         "master-weight arm; write "
                         "BENCH_UPDATE_SHARDING.json")
    ap.add_argument("--update-sharding-ab-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--quant-ab", action="store_true",
                    help="quantized-matmul seam A/B (ops.qmm, ROADMAP "
                         "item 5): bf16 vs fp8 vs int8 train step "
                         "(interleaved pairs + loss-curve parity "
                         "bounds) and int8 PTQ vs int8-compute greedy "
                         "decode (tokens/s + exactness) -> "
                         "BENCH_QUANT.json")
    ap.add_argument("--quant-ab-inproc", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--trace-overhead", action="store_true",
                    help="interleaved A/B of span tracing + compile "
                         "ledger OFF vs ON (train/trace.py) at the "
                         "CPU-bench transformer scale, with the params "
                         "bitwise pin embedded; write BENCH_TRACE.json")
    ap.add_argument("--obs-overhead", action="store_true",
                    help="interleaved A/B of the fleet observability "
                         "plane OFF vs ON (with_metrics step + lag-2 "
                         "fetch + sketch feeds + rollup serialization + "
                         "per-role heartbeat) at the CPU-bench "
                         "transformer scale; params-bitwise pin "
                         "embedded; write BENCH_OBS.json")
    ap.add_argument("--obs-overhead-inproc", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--trace-overhead-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--goodput", action="store_true",
                    help="goodput-accounting A/B (utils/goodput.py): "
                         "interleaved meter OFF/ON pairs on the traced "
                         "CPU-bench transformer chain, a supervised "
                         "2-process chaos run (injected crash -> "
                         "relaunch_gap + rollback, categories sum to "
                         "covered wall-clock), per-role fraction "
                         "through the Prometheus export, serve tokens "
                         "bitwise pin; write BENCH_GOODPUT.json")
    ap.add_argument("--goodput-inproc", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child entry
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the torch reference baseline (vs_baseline=null)")
    ap.add_argument("--grad-reduction", choices=["global_mean", "local"],
                    default="global_mean",
                    help="'local' drops every gradient collective "
                         "(measurement-only ablation — replicas diverge); "
                         "the scaling sweep differences the two to "
                         "attribute allreduce cost")
    ap.add_argument("--preflight", action="store_true",
                    help="no-chip de-risking of --config: state byte budget "
                         "vs v5e HBM, eval_shape + CPU lower/compile of the "
                         "real train step, same-shape-class CPU smoke; "
                         "writes BENCH_PREFLIGHT.json (runs CPU-pinned, "
                         "never touches the TPU tunnel)")
    args = ap.parse_args()

    if args.preflight:
        plat.pin("cpu")
        rec = preflight_config(args.config)
        print(json.dumps(rec))
        return 0 if rec["ok"] else 1

    if args.scaling:
        run_scaling_sweep()
        # fall through: still print the standard single-chip JSON line

    choice, probe_history = resolve_platform(args.platform)
    if choice == "cpu":
        plat.pin("cpu")

    if args.attention_inproc:  # child entry: write the artifact and exit
        print(json.dumps({"attention_artifact": bench_attention()}))
        return 0
    if args.decode_inproc:
        print(json.dumps({"decode_artifact": bench_decode()}))
        return 0
    if args.serve_inproc:
        print(json.dumps({"serve_artifact":
                          bench_serve(attn_impl=args.serve_attn_impl)}))
        return 0
    if args.serve_fleet_inproc:
        print(json.dumps({"serve_fleet_artifact": bench_serve_fleet()}))
        return 0
    if args.serve_disagg_inproc:
        print(json.dumps({"serve_disagg_artifact":
                          bench_serve_disagg()}))
        return 0
    if args.ctrlplane_inproc:
        print(json.dumps({"ctrlplane_artifact": bench_ctrlplane()}))
        return 0
    if args.autopilot_inproc:
        print(json.dumps({"autopilot_artifact": bench_autopilot()}))
        return 0
    if args.chaos_inproc:
        print(json.dumps({"chaos_artifact": bench_chaos()}))
        return 0
    if args.paged_attn_inproc:
        print(json.dumps({"paged_attn_artifact": bench_paged_attn()}))
        return 0
    if args.prefix_cache_inproc:
        print(json.dumps({"prefix_cache_artifact": bench_prefix_cache()}))
        return 0
    if args.rl_inproc:
        print(json.dumps({"rl_artifact": bench_rl()}))
        return 0
    if args.update_sharding_ab_inproc:
        print(json.dumps({"update_sharding_artifact":
                          bench_update_sharding()}))
        return 0
    if args.trace_overhead_inproc:
        print(json.dumps({"trace_artifact": bench_trace_overhead()}))
        return 0
    if args.obs_overhead_inproc:
        print(json.dumps({"obs_artifact": bench_obs_overhead()}))
        return 0
    if args.quant_ab_inproc:
        print(json.dumps({"quant_artifact": bench_quant_ab()}))
        return 0
    if args.goodput_inproc:
        print(json.dumps({"goodput_artifact": bench_goodput()}))
        return 0

    if (args.attention or args.decode or args.serve or args.rl
            or args.serve_fleet or args.serve_disagg
            or args.ctrlplane or args.autopilot or args.chaos
            or args.paged_attn or args.prefix_cache
            or args.update_sharding_ab or args.trace_overhead
            or args.obs_overhead or args.quant_ab or args.goodput):
        # standalone artifact runs: do NOT fall through into the default
        # config bench — on the exclusive tunnel that would spend extra
        # minutes of a flapping window re-measuring `wide` (+ its torch
        # baseline), and callers checking the last JSON line would read
        # that trailing record instead of the artifact they asked for
        if args.attention:  # after platform resolution: touches the backend
            if choice == "cpu":
                # the fallback parent has ONE device; ring needs a 'seq' axis
                path = _run_flag_cpu_child("--attention-inproc", 4)
            else:
                path = bench_attention()
            print(json.dumps({"attention_artifact": path}))
        if args.decode:
            if choice == "cpu":
                path = _run_flag_cpu_child("--decode-inproc", 8)
            else:
                path = bench_decode()
            print(json.dumps({"decode_artifact": path}))
        if args.serve:
            if choice == "cpu":
                # single-device is the serve bench's natural CPU shape
                path = _run_flag_cpu_child(
                    "--serve-inproc", 1,
                    extra=["--serve-attn-impl", args.serve_attn_impl])
            else:
                path = bench_serve(attn_impl=args.serve_attn_impl)
            print(json.dumps({"serve_artifact": path}))
        if args.serve_fleet:
            # always the CPU-child shape: the fleet IS subprocess
            # replicas (each pins its own cpu backend); an exclusive
            # single-chip tunnel cannot host 4 replica runtimes anyway
            path = _run_flag_cpu_child("--serve-fleet-inproc", 1,
                                       timeout=3000)
            print(json.dumps({"serve_fleet_artifact": path}))
        if args.serve_disagg:
            # subprocess-replica shape like --serve-fleet: the role
            # pools ARE cpu-pinned worker processes
            path = _run_flag_cpu_child("--serve-disagg-inproc", 1,
                                       timeout=3000)
            print(json.dumps({"serve_disagg_artifact": path}))
        if args.ctrlplane:
            # subprocess-replica shape like --serve-disagg, one level
            # deeper: the bench's subject is itself a killable driver
            # subprocess owning the router and its workers
            path = _run_flag_cpu_child("--ctrlplane-inproc", 1,
                                       timeout=3000)
            print(json.dumps({"ctrlplane_artifact": path}))
        if args.autopilot:
            # subprocess-replica shape like --serve-fleet: the control
            # loop's subjects are worker processes with their own cpu
            # backends, so the parent always runs CPU-pinned
            path = _run_flag_cpu_child("--autopilot-inproc", 1,
                                       timeout=3000)
            print(json.dumps({"autopilot_artifact": path}))
        if args.chaos:
            # subprocess-replica shape like --autopilot: the fleet
            # scenarios spawn cpu-pinned worker processes, and the
            # stub scenarios never touch jax at all
            path = _run_flag_cpu_child("--chaos-inproc", 1,
                                       timeout=3000)
            print(json.dumps({"chaos_artifact": path}))
        if args.paged_attn:
            if choice == "cpu":
                path = _run_flag_cpu_child("--paged-attn-inproc", 1)
            else:
                path = bench_paged_attn()
            print(json.dumps({"paged_attn_artifact": path}))
        if args.prefix_cache:
            if choice == "cpu":
                # host-side sharing over one device, like --serve
                path = _run_flag_cpu_child("--prefix-cache-inproc", 1)
            else:
                path = bench_prefix_cache()
            print(json.dumps({"prefix_cache_artifact": path}))
        if args.rl:
            if choice == "cpu":
                # env sharding needs a data axis: 8 virtual devices
                path = _run_flag_cpu_child("--rl-inproc", 8)
            else:
                path = bench_rl()
            print(json.dumps({"rl_artifact": path}))
        if args.update_sharding_ab:
            if choice == "cpu":
                # the A/B needs a real data axis: 8 virtual devices
                path = _run_flag_cpu_child("--update-sharding-ab-inproc", 8)
            else:
                path = bench_update_sharding()
            print(json.dumps({"update_sharding_artifact": path}))
        if args.trace_overhead:
            if choice == "cpu":
                # same 8-virtual-device DP mesh as the telemetry/update-
                # sharding overhead measurements
                path = _run_flag_cpu_child("--trace-overhead-inproc", 8)
            else:
                path = bench_trace_overhead()
            print(json.dumps({"trace_artifact": path}))
        if args.obs_overhead:
            if choice == "cpu":
                # same 8-virtual-device DP mesh as the sibling overhead
                # measurements
                path = _run_flag_cpu_child("--obs-overhead-inproc", 8)
            else:
                path = bench_obs_overhead()
            print(json.dumps({"obs_artifact": path}))
        if args.quant_ab:
            if choice == "cpu":
                # the train A/B needs a real data axis: 8 virtual devices
                path = _run_flag_cpu_child("--quant-ab-inproc", 8)
            else:
                path = bench_quant_ab()
            print(json.dumps({"quant_artifact": path}))
        if args.goodput:
            if choice == "cpu":
                # same 8-virtual-device DP mesh as the sibling overhead
                # measurements; the chaos half spawns its own stdlib
                # children regardless of backend
                path = _run_flag_cpu_child("--goodput-inproc", 8,
                                           timeout=3000)
            else:
                path = bench_goodput()
            print(json.dumps({"goodput_artifact": path}))
        return 0

    configs = sorted(METRIC_NAMES) if args.all else [args.config]
    if args.all and choice == "cpu":
        # MXU-oriented extras take minutes/step on the CPU fallback — keep
        # the fallback's turnaround honest (run them explicitly if wanted)
        for name in ("moe", "big_lm"):
            if name in configs:
                log(f"[{name}] skipped on the cpu fallback (TPU-oriented "
                    f"extra; run `bench.py --config {name}` explicitly to "
                    "measure it here)")
                configs.remove(name)
    records = []
    for name in configs:
        try:
            fw = bench_framework(name, batch_override=args.batch or None,
                                 grad_reduction=args.grad_reduction)
        except Exception as e:  # noqa: BLE001 — keep the harness alive
            log(f"[{name}] framework bench FAILED: {type(e).__name__}: {e}")
            if name == "moe":
                # same reason as the upfront skip: the routed-dispatch
                # einsums take minutes/step on CPU — don't stall the sweep
                log("[moe] not retried on the cpu fallback")
                records.append({"metric": METRIC_NAMES[name], "value": None,
                                "unit": "samples/sec",
                                "error": f"{type(e).__name__}: {e}"})
                continue
            # A process whose backend initialized cannot switch platforms;
            # retry the config in a CPU-pinned subprocess instead.  ONLY
            # when this run actually bound the accelerator: a run that
            # already resolved to CPU (explicit --platform cpu, a
            # scaling-sweep/ablation child, or an auto probe that fell
            # back) must fail loudly — a CPU child retry could only fail
            # the same way, and a 1-device default-parameter retry would
            # silently substitute a DIFFERENT measurement.
            if choice == "cpu":
                if not args.all:
                    raise
                records.append({"metric": METRIC_NAMES[name], "value": None,
                                "unit": "samples/sec",
                                "error": f"{type(e).__name__}: {e}"})
                continue
            rec = _run_child_cpu(name, n_devices=1,
                                 baseline=not args.no_baseline,
                                 batch=args.batch or None,
                                 grad_reduction=(args.grad_reduction
                                                 if args.grad_reduction
                                                 != "global_mean" else None))
            if rec is None:
                if not args.all:
                    raise
                # --all: record the failure, keep the remaining configs
                records.append({"metric": METRIC_NAMES[name], "value": None,
                                "unit": "samples/sec",
                                "error": f"{type(e).__name__}: {e}"})
                continue
            log(f"[{name}] cpu-subprocess fallback: {rec['value']:,.0f} "
                "samples/sec")
            records.append(rec)
            continue
        baseline_sps = None
        if (not args.no_baseline and _make_config(name)["baseline_steps"]
                and args.grad_reduction == "global_mean"):
            # an ablated (collectives-free) run must never be ratioed
            # against the real torch baseline
            baseline_sps = bench_reference_baseline(
                name, batch_override=args.batch or None)
        rec = {
            "metric": METRIC_NAMES[name],
            "value": round(fw["samples_per_sec"], 1),
            "unit": "samples/sec",
            "vs_baseline": (None if baseline_sps is None
                            else round(fw["samples_per_sec"] / baseline_sps, 3)),
            "platform": fw["platform"],
            "device_kind": fw["device_kind"],
            "n_devices": fw["n_devices"],
            "mfu": fw["mfu"],
            "step_ms": round(fw["step_ms"], 3),
            "batch": fw["batch"],
            "param_bytes": fw["param_bytes"],
            **({"grad_reduction": args.grad_reduction}
               if args.grad_reduction != "global_mean" else {}),
        }
        if name == "toy":
            # 16 samples x 13 params: the step is pure dispatch overhead
            # (sub-ms of compute).  Through the tunneled single-chip
            # backend each step pays a ~2 ms RPC, so torch-CPU "wins" the
            # race to do nothing — mark the row machine-readably so no
            # artifact carries an unexplained sub-1.0 vs_baseline
            # (VERDICT r3 item 6 hygiene; the row measures step overhead,
            # which IS its purpose — see _make_config)
            rec["dispatch_bound"] = True
            rec["role"] = "step_overhead_probe"
        records.append(rec)

    if args.all:
        out = "BENCH_FULL.json"
        # every cpu row is a mechanism check on the shared fallback host,
        # never a framework performance claim — stamp the rows themselves
        # so no artifact carries an unmarked sub-1.0 vs_baseline
        for r in records:
            if r.get("platform") == "cpu":
                r["role"] = "mechanism_check_on_fallback_host"
                r["platform_fallback"] = True
        # error records carry no 'platform' key — treat them as cpu-like,
        # or a sweep with one failed config would bypass the guard
        if all(r.get("platform") in (None, "cpu") for r in records):
            try:  # never clobber a real-chip sweep with fallback rows
                with open(out) as f:
                    prior = json.load(f)
                if (isinstance(prior, list)
                        and any(isinstance(r, dict)
                                and r.get("platform") not in (None, "cpu")
                                for r in prior)):
                    out = "BENCH_FULL_CPU.json"
                    log("existing BENCH_FULL.json holds a real-chip "
                        "sweep; cpu fallback writes " + out)
            except (OSError, ValueError):
                pass
        _emit_artifact(out, records)
        log(f"all configs -> {out}")

    save_tpu_latest(records)

    primary = dict(next((r for r in records
                         if r["metric"] == METRIC_NAMES[args.config]),
                        records[0]))
    if primary.get("platform") == "cpu" and args.platform != "cpu":
        # Capture-time probing failed.  The canonical artifact must not
        # headline a fallback-host ratio as if it were the framework's
        # number (VERDICT r3 item 6): when a same-repo real-chip record
        # exists for this metric, IT is the headline — explicitly stamped
        # as cached provenance — and this run's CPU row is demoted to a
        # machine-readable mechanism check.  Proof-of-probing rides along
        # either way.
        probe_rec = {
            "attempts": len(probe_history), "timeout_s": PROBE_TIMEOUT_S,
            "backoff_s": PROBE_BACKOFF_S, "history": probe_history,
        }
        cached = load_tpu_latest()
        row = None
        if cached:
            row = next((r for r in cached.get("records", [])
                        if r.get("metric") == primary["metric"]), None)
        if args.config == "big_lm":
            # BENCH_TPU_LATEST's big_lm row may predate a config flip
            # (it does not record scan_layers/ce_chunk); a BIGLM_SWEEP
            # chip row matched against EVERY committed knob is the
            # stronger cached evidence — synthesize the headline from it
            # with explicit source provenance.
            import jax.numpy as _jnp

            big_cfg = _make_config("big_lm")
            srow, sweep_doc = committed_big_lm_sweep_row(
                big_cfg["make_model"](_jnp.bfloat16).cfg,
                big_cfg["batch"], return_doc=True)
            if srow is not None:
                try:
                    sweep_iso = sweep_doc.get("captured_iso")
                    sweep_age = round(
                        (time.time() - sweep_doc["captured_unix"]) / 3600,
                        2)
                except (KeyError, TypeError):
                    sweep_iso, sweep_age = None, None
                row = {
                    "captured_iso": sweep_iso, "age_hours": sweep_age,
                    "metric": primary["metric"],
                    "value": srow.get("samples_per_sec"),
                    "unit": "samples/sec", "vs_baseline": None,
                    "platform": "tpu",
                    "device_kind": srow.get("device_kind"),
                    "n_devices": 1, "mfu": srow.get("mfu"),
                    "step_ms": srow.get("step_ms"),
                    "batch": srow.get("batch"),
                    "source": "BIGLM_SWEEP.json",
                    "source_label": srow.get("label"),
                    "source_note": (
                        "sweep row measured on-chip at exactly the "
                        "committed config (every knob matched by "
                        "committed_big_lm_sweep_row); preferred over "
                        "BENCH_TPU_LATEST's row, which does not record "
                        "config flags and may predate a config flip"),
                }
        if row:
            demoted = dict(primary)
            demoted["role"] = "mechanism_check_on_fallback_host"
            primary = dict(row)
            primary["measurement"] = "cached_tpu"
            primary["platform_fallback"] = True
            if "captured_iso" not in primary:
                primary["captured_iso"] = (cached or {}).get("captured_iso")
                primary["age_hours"] = (cached or {}).get("age_hours")
            primary["note"] = (
                "capture-time probe failed (history in 'probe'); headline "
                "is a prior successful real-chip measurement from this "
                f"repo ({primary.get('source', 'BENCH_TPU_LATEST.json')}); "
                "'cpu_fallback_run' is THIS run's mechanism check on the "
                "single-core fallback host, not a framework performance "
                "claim")
            primary["cpu_fallback_run"] = demoted
            primary["probe"] = probe_rec
        else:
            primary["platform_fallback"] = True
            primary["role"] = "mechanism_check_on_fallback_host"
            primary["probe"] = probe_rec
            if cached:
                primary["tpu_latest_cached"] = {
                    "note": "prior successful real-chip run from this repo "
                            "(no row for this metric); not this run's "
                            "measurement",
                    "captured_iso": cached.get("captured_iso"),
                    "age_hours": cached.get("age_hours"),
                    "device_kind": cached.get("device_kind"),
                    "records": cached.get("records"),
                }
    print(json.dumps(primary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
