// Native data-loading runtime: threaded shuffle + row-gather + prefetch.
//
// TPU-native equivalent of the host-side data path the reference delegates
// to torch's DataLoader (dataParallelTraining_NN_MPI.py:146) — but built for
// the TPU regime where the accelerator must never wait on the host: batches
// are assembled by a worker pool *ahead* of consumption into a bounded
// ready-queue, so the Python thread only memcpy-wraps a finished buffer
// while workers gather the next batches in parallel with device compute.
//
// Fields are opaque byte rows (any dtype/shape), so one permutation is
// shared by every field of a dataset — the row pairing (x[i], y[i]) is
// preserved by construction, unlike per-field shuffles.
//
// Determinism: Fisher-Yates driven by splitmix64 seeded with (seed, epoch),
// identical across hosts for a given config — the property the reference's
// rank-0-only torch.manual_seed (bug B5, SURVEY.md §2.5) was meant to have.
//
// C ABI (ctypes-friendly); all functions are thread-compatible per handle.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Field {
  const uint8_t* data;
  uint64_t row_bytes;
};

struct Batch {
  std::vector<std::vector<uint8_t>> buffers;  // one per field
  uint64_t rows = 0;
};

static inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Loader {
  uint64_t n_rows = 0;
  uint64_t seed = 0;
  bool shuffle = true;
  std::vector<Field> fields;

  // epoch state
  std::vector<uint64_t> order;
  uint64_t batch_size = 0;
  uint64_t n_batches = 0;
  std::atomic<uint64_t> next_claim{0};

  // prefetch machinery
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for next_deliver
  std::condition_variable cv_space;   // workers wait for queue space
  std::map<uint64_t, Batch> ready;
  uint64_t next_deliver = 0;
  uint64_t max_ready = 4;
  bool stopping = false;

  Batch current;  // last delivered batch; alive until the next delivery

  void reset_epoch_order(uint64_t epoch) {
    order.resize(n_rows);
    for (uint64_t i = 0; i < n_rows; ++i) order[i] = i;
    if (shuffle) {
      uint64_t s = seed * 0x9e3779b97f4a7c15ULL + epoch + 1;
      for (uint64_t i = n_rows; i > 1; --i) {
        uint64_t j = splitmix64(s) % i;
        std::swap(order[i - 1], order[j]);
      }
    }
  }

  void gather(uint64_t batch_idx, Batch& out) const {
    const uint64_t start = batch_idx * batch_size;
    const uint64_t rows = std::min(batch_size, n_rows - start);
    out.rows = rows;
    out.buffers.resize(fields.size());
    for (size_t f = 0; f < fields.size(); ++f) {
      const Field& fld = fields[f];
      out.buffers[f].resize(rows * fld.row_bytes);
      uint8_t* dst = out.buffers[f].data();
      for (uint64_t r = 0; r < rows; ++r) {
        std::memcpy(dst + r * fld.row_bytes,
                    fld.data + order[start + r] * fld.row_bytes,
                    fld.row_bytes);
      }
    }
  }

  void worker_main() {
    for (;;) {
      const uint64_t idx = next_claim.fetch_add(1);
      if (idx >= n_batches) return;
      Batch b;
      gather(idx, b);
      std::unique_lock<std::mutex> lk(mu);
      // bound memory: don't run more than max_ready ahead of delivery
      cv_space.wait(lk, [&] {
        return stopping || idx < next_deliver + max_ready;
      });
      if (stopping) return;
      ready.emplace(idx, std::move(b));
      cv_ready.notify_all();
    }
  }

  void stop_workers() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) {
      if (t.joinable()) t.join();
    }
    workers.clear();
    ready.clear();
    stopping = false;
  }
};

}  // namespace

extern "C" {

void* dl_create(uint64_t n_rows, uint64_t seed, int shuffle) {
  auto* l = new Loader();
  l->n_rows = n_rows;
  l->seed = seed;
  l->shuffle = shuffle != 0;
  return l;
}

// data must stay valid for the loader's lifetime (numpy array owned by
// the Python wrapper).  Returns the field index.
int dl_add_field(void* handle, const void* data, uint64_t row_bytes) {
  auto* l = static_cast<Loader*>(handle);
  l->fields.push_back(Field{static_cast<const uint8_t*>(data), row_bytes});
  return static_cast<int>(l->fields.size()) - 1;
}

// Returns the number of batches this epoch will deliver.
uint64_t dl_start_epoch(void* handle, uint64_t epoch, uint64_t batch_size,
                        int drop_remainder, uint64_t start_batch,
                        int n_threads, uint64_t prefetch_depth) {
  auto* l = static_cast<Loader*>(handle);
  l->stop_workers();
  l->reset_epoch_order(epoch);
  l->batch_size = batch_size == 0 ? l->n_rows : batch_size;
  uint64_t nb = l->n_rows / l->batch_size;
  if (!drop_remainder && l->n_rows % l->batch_size) nb += 1;
  if (nb == 0) nb = 1;
  l->n_batches = nb;
  l->next_claim.store(start_batch);
  l->next_deliver = start_batch;
  l->max_ready = prefetch_depth == 0 ? 4 : prefetch_depth;
  if (n_threads < 1) n_threads = 1;
  for (int i = 0; i < n_threads; ++i) {
    l->workers.emplace_back([l] { l->worker_main(); });
  }
  return nb - std::min(start_batch, nb);
}

// Blocks until the next in-order batch is ready.  Returns rows in the
// batch (0 = epoch exhausted).  out_ptrs[f] receives the field buffers,
// valid until the next dl_next_batch/dl_start_epoch/dl_destroy call.
uint64_t dl_next_batch(void* handle, void** out_ptrs) {
  auto* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->mu);
  if (l->next_deliver >= l->n_batches) return 0;
  const uint64_t want = l->next_deliver;
  l->cv_ready.wait(lk, [&] { return l->ready.count(want) != 0; });
  l->current = std::move(l->ready[want]);
  l->ready.erase(want);
  l->next_deliver = want + 1;
  l->cv_space.notify_all();
  for (size_t f = 0; f < l->current.buffers.size(); ++f) {
    out_ptrs[f] = l->current.buffers[f].data();
  }
  return l->current.rows;
}

void dl_destroy(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  l->stop_workers();
  delete l;
}

}  // extern "C"
