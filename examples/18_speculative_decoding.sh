#!/usr/bin/env bash
# Greedy speculative decoding: train a tiny byte-LM, then decode the
# checkpoint with a draft proposing k=4 tokens per round and the target
# verifying them in ONE chunked pass.  With a TRAINED model the logit
# margins are real, so the self-draft accept rate is ~1 and the target
# runs ~N/(k+1) passes instead of N — while the output stays
# token-for-token identical to plain generate() (asserted below).
set -euo pipefail
CKPT="$(mktemp -d)"
trap 'rm -rf "$CKPT"' EXIT

python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset text --text_file README.md --no-full-batch --batch_size 32 \
    --nepochs 2 --optimizer adam --lr 3e-3 --seq_len 64 \
    --checkpoint_dir "$CKPT"

python - "$CKPT" <<'EOF'
import sys

from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

plat.pin("cpu", num_devices=1)
import jax.numpy as jnp
import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig, generate, speculative_generate,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import (
    checkpoint as ckpt,
)

restored = ckpt.restore(sys.argv[1], template=None)
model = Transformer(TransformerConfig(
    vocab_size=256, max_seq_len=512, n_layers=2, d_model=128, n_heads=4,
    d_ff=512))  # CLI defaults for --dataset text at --seq_len 64
params = restored.params

prompt = jnp.asarray([[ord(c) for c in "The reference "]], jnp.int32)
n = 48
plain = generate(model, params, prompt, n)
spec, stats = speculative_generate(model, params, model, params, prompt,
                                   n, k=4)
assert np.array_equal(np.asarray(spec), np.asarray(plain)), \
    "speculative output diverged from plain greedy decode"
text = "".join(chr(t) for t in np.asarray(spec)[0] if 0 < t < 127)
print(f"decoded: {text!r}")
print(f"accept rate {stats['accept_rate']:.2f}; target ran "
      f"{stats['target_passes']} passes for {n} tokens "
      f"(plain decode: {n} steps) — tokens identical")
EOF
