#!/usr/bin/env bash
# Multi-step dispatch (--steps_per_dispatch, round 5): k optimizer steps
# run inside ONE jitted lax.scan over a device-staged stack of k batches,
# so small (dispatch-bound) models stop paying a host round trip per step
# — the TPU-first answer to the reference's per-step gather-average-send
# loop (dataParallelTraining_NN_MPI.py:149-211).  The scan replays the
# identical batches in the identical order, so the loss trajectory is the
# k=1 trajectory; this script runs the same job both ways and diffs the
# final loss.
set -euo pipefail

run() {
    python -m neural_networks_parallel_training_with_mpi_tpu \
        --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
        --nepochs 4 --no-full-batch --batch_size 4 \
        --steps_per_dispatch "$1" 2>&1 | tail -3
}

echo "== per-step dispatch (k=1) =="
L1=$(run 1 | grep -o 'loss [0-9.]*' | tail -1)
echo "$L1"
echo "== 8 steps per dispatch (k=8) =="
L8=$(run 8 | grep -o 'loss [0-9.]*' | tail -1)
echo "$L8"

[ "$L1" = "$L8" ] || { echo "trajectory mismatch: '$L1' vs '$L8'"; exit 1; }
echo "OK: k=8 dispatch trajectory identical to k=1 ($L1)"
