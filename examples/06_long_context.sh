#!/usr/bin/env bash
# Sequence/context parallelism: the sequence dimension is sharded over the
# 'seq' axis and attention runs as a ring (K/V blocks rotate by ppermute),
# so context length scales with the number of chips.  ATTENTION picks the
# impl: ring (default here), ring_flash (Pallas kernel per block),
# striped/striped_flash (round-robin token stripes — balanced causal
# blocks, ~2x causal ring throughput at scale), or ulysses (all_to_all).
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --seq_len 256 --no-full-batch --batch_size 8 --nepochs 1 \
    --optimizer adam --lr 1e-3 --dp 4 --sp 2 \
    --attention "${ATTENTION:-ring}"
