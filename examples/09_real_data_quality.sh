#!/usr/bin/env bash
# Real data, real quality bar: sklearn's bundled load_digits (1797 actual
# 8x8 handwritten digit images — zero egress) trained to >95% held-out
# accuracy with periodic validation.  `python quality.py` runs this plus
# the reference-workload convergence-parity check and writes QUALITY.json.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset digits --no-full-batch --batch_size 128 --nepochs 30 \
    --optimizer adam --lr 3e-3 --val_fraction 0.2 --eval_every 10
