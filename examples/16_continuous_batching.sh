#!/usr/bin/env bash
# Continuous batching (in-flight batching): models.serve.DecodeServer
# decodes a fixed slot pool as ONE batched jitted step per token while
# requests join and leave mid-flight — the serving schedule TPUs want,
# because throughput comes from batching but real traffic arrives
# ragged.  Each request's tokens are EXACTLY what the single-stream
# generate() would emit (greedy), batching with strangers changes
# nothing.  The reference has no serving story at all (its eval blocks
# are dead code, dataParallelTraining_NN_MPI.py:227-236).
set -euo pipefail

python - <<'EOF'
from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

plat.pin("cpu", num_devices=1)
import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.models import (
    DecodeServer, Transformer, TransformerConfig, generate,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

model = Transformer(TransformerConfig(
    vocab_size=256, max_seq_len=64, n_layers=2, d_model=64, n_heads=4,
    d_ff=128))
params = model.init(prng.init_key(0))
srv = DecodeServer(model, params, slots=4)

# requests arrive staggered, with different prompts and budgets
import jax.numpy as jnp

arrivals = [([10, 20, 30], 12), ([7, 8], 6), ([5, 9, 11, 13], 9)]
rids = {}
rids[srv.submit(*arrivals[0])] = arrivals[0]
srv.step(); srv.step()                      # first request is mid-flight
rids[srv.submit(*arrivals[1])] = arrivals[1]
srv.step()
rids[srv.submit(*arrivals[2])] = arrivals[2]
print(f"in flight: {srv.live()} requests sharing one batched step")
while any(not srv.done(r) for r in rids):
    srv.step()
for rid, (prompt, n) in rids.items():
    got = srv.result(rid)
    want = [int(t) for t in np.asarray(
        generate(model, params, jnp.asarray([prompt], jnp.int32), n))[0]]
    assert got == want, (got, want)
    print(f"req {rid}: prompt {prompt} -> {got[len(prompt):]}")
print("continuous-batched tokens == single-stream generate() for all requests")
EOF
