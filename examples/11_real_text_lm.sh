#!/usr/bin/env bash
# Byte-level LM on REAL text — any local file (here: this repo's README).
# Zero-egress real-language training; `python quality.py` trains the full
# documentation corpus to a held-out perplexity below the corpus's unigram
# entropy bar (QUALITY.json).
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset text --text_file README.md --seq_len 128 \
    --no-full-batch --batch_size 32 --nepochs 2 \
    --optimizer adam --lr 3e-3 --val_fraction 0.1 --eval_every 2
