#!/usr/bin/env bash
# Prefix-cached serving: a shared-system-prompt traffic mix through the
# continuous-batching scheduler with prefix_cache=True (serve/paged_kv).
# Every request carries the same 72-token system prompt plus its own
# user suffix; the FIRST request prefills and registers its blocks in
# the prefix index, and every later admission longest-matches the index
# and points its block table at the EXISTING blocks — the matched
# prefill chunks are skipped outright, so cached TTFT collapses to the
# remaining-suffix prefill.  A "regenerated turn" (identical prompt)
# full-hits and exercises the copy-on-write fork: the partial tail block
# is copied on-device before the stream's first write, so no stream ever
# writes a block another stream can read.  Greedy tokens are asserted
# identical to (1) the cache-OFF scheduler serving the same requests and
# (2) the single-stream generate() reference; refcounts drain to zero.
set -euo pipefail

python - <<'EOF'
from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

plat.pin("cpu", num_devices=1)
import jax.numpy as jnp
import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig, generate,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    Scheduler, ServeConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

model = Transformer(TransformerConfig(
    vocab_size=256, max_seq_len=128, n_layers=2, d_model=64, n_heads=4,
    d_ff=128))
params = model.init(prng.init_key(0))

cfg = dict(slots=8, num_blocks=65, block_size=16, prefill_chunk=16,
           queue_depth=16)

# warmup: pay the (cached) prefill-bucket + decode + CoW-fork compiles
# once, so printed TTFTs are steady-state serving numbers, not XLA
# compile time (the jitted programs are shared across schedulers)
warm = Scheduler(model, params, ServeConfig(**cfg, prefix_cache=True))
for plen in (3, 12, 24, 75):
    warm.submit(list(range(1, plen + 1)), 2)
warm.run_until_drained()
warm.submit(list(range(1, 76)), 2)        # regen: forces the CoW compile
warm.run_until_drained()
assert warm.server.cow_forks >= 1
warm.close()

# one 72-token system prompt (4.5 blocks: it ends MID-block, so a
# regenerated turn forks copy-on-write) + per-request user suffixes
rng = np.random.default_rng(7)
system = rng.integers(0, 256, (72,)).tolist()
requests = [
    (system + [10, 20, 30], 16),        # cold: prefills + registers
    (system + [40, 41], 12),            # hit: shares 4 full blocks
    (system, 12),                       # regenerated turn: full hit + CoW
    (system + [50, 51, 52, 53], 12),    # hit
    ([7, 8, 9], 8),                     # unique: misses, unaffected
]

results = {}
for label, on in (("off", False), ("on", True)):
    sched = Scheduler(model, params, ServeConfig(**cfg, prefix_cache=on))
    rids = [sched.submit(p, n) for p, n in requests]
    assert all(r is not None for r in rids)
    sched.run_until_drained()
    toks, ttfts = [], []
    for rid in rids:
        toks.append(sched.result(rid))
        ttfts.append(sched.stats(rid).ttft_ms)
    sched.server.allocator.assert_drained()   # refcounts all zero
    stats = sched.server.prefix_stats()
    results[label] = (toks, ttfts, sched.tick_no, stats)
    sched.close()

toks_off, ttft_off, ticks_off, _ = results["off"]
toks_on, ttft_on, ticks_on, stats = results["on"]

assert toks_on == toks_off, "prefix cache changed tokens!"
for (prompt, n), got in zip(requests, toks_on):
    want = [int(t) for t in np.asarray(
        generate(model, params, jnp.asarray([prompt], jnp.int32), n))[0]]
    assert got == want, (prompt, got, want)
print("tokens: cache on == cache off == generate() for all "
      f"{len(requests)} requests")

for i, ((prompt, n), t0, t1) in enumerate(zip(requests, ttft_off,
                                              ttft_on)):
    tag = ("cold " if i == 0 else
           "uniq " if len(prompt) < 10 else "hit  ")
    print(f"req {i} [{tag}] prompt {len(prompt):>2} tok:  "
          f"TTFT off {t0:7.1f} ms   on {t1:7.1f} ms")

hit_rate = stats["prefix_hit_tokens"] / stats["prompt_tokens_admitted"]
print(f"prefix cache: {stats['prefix_hits']} hits, "
      f"{stats['prefix_hit_tokens']} prompt tokens from cache "
      f"(hit rate {hit_rate:.2f}), {stats['cow_forks']} CoW fork(s), "
      f"{stats['blocks_saved']} block prefills saved")
print(f"drained in {ticks_on} ticks cached vs {ticks_off} cold")
assert stats["prefix_hits"] >= 3          # every shared follower hit
assert stats["cow_forks"] >= 1            # the regenerated turn forked
assert ticks_on < ticks_off               # skipped prefill ticks
# the hit requests' first tokens arrived no later than cache-off served
# the same requests (tick-for-tick the cached arm strictly skips work)
print("prefix-cached serving: near-zero-TTFT admission verified, "
      "block pool fully drained")
EOF
