#!/usr/bin/env bash
# 3D mesh: data x sequence x tensor.  Megatron column/row-parallel block
# matmuls (attention heads + FFN hidden units sharded over 'tensor') with
# ring attention over 'seq' — one shard_map program; the Megatron-LM
# TP + context-parallelism composition.  Trajectory parity with plain DP
# is pinned by tests/test_composition.py::TestSeqTensor.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --seq_len 128 --no-full-batch --batch_size 8 --nepochs 1 \
    --optimizer adam --lr 1e-3 --dp 2 --sp 2 --tp 2 --grad_clip 1.0
