#!/usr/bin/env bash
# Interleaved virtual-stage pipeline: 2 stage-slices per pipeline device
# (--pp_interleave 2), so each microbatch circles the ppermute ring twice
# and the warmup/drain bubble shrinks from (S-1)/(M+S-1) to
# (S-1)/(2M+S-1) at the same microbatch count.  n_layers must divide by
# pp * pp_interleave (here 4 = 2 * 2).
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 \
    --n_layers 4 --dp 4 --pp 2 --pp_interleave 2
