#!/usr/bin/env bash
# 3D mesh: data x expert x tensor — GShard's expert + model parallelism.
# Megatron attention (heads sharded over 'tensor') with MoE expert FFNs
# sharded over BOTH 'expert' (whole experts, all_to_all slot exchange) and
# 'tensor' (each expert's hidden dim, psum combine).  One-step parity with
# the dense MoE model is pinned by
# tests/test_moe.py::test_expert_tensor_parallel_matches_dense.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --dp 2 --ep 2 --tp 2 --moe_experts 4 \
    --grad_clip 1.0
