#!/usr/bin/env bash
# Checkpoint mid-training, then resume at the exact next step (the
# reference has no save/load at all — SURVEY.md §5.4).
set -euo pipefail
CKPT=$(mktemp -d)
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --n_samples 1024 --no-full-batch --batch_size 64 --nepochs 2 \
    --checkpoint_dir "$CKPT" --checkpoint_every 8
echo "--- resuming ---"
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --n_samples 1024 --no-full-batch --batch_size 64 --nepochs 4 \
    --checkpoint_dir "$CKPT" --resume
