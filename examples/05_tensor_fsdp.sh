#!/usr/bin/env bash
# Megatron-style tensor parallelism x ZeRO-style parameter/optimizer
# sharding, expressed as GSPMD sharding annotations — XLA inserts the
# all-gathers/reduce-scatters.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 16 --nepochs 1 \
    --optimizer adam --lr 1e-3 --dp 2 --tp 2 --fsdp 2
