#!/usr/bin/env bash
# 4D model-axis mesh: seq x expert x tensor (round 4) — ring attention
# over 'seq', all_to_all expert dispatch over 'expert', Megatron head and
# expert-hidden sharding over 'tensor', in ONE shard_map program.  The
# same step builder with the expert axis at 1 gives SP x TP MoE (experts
# whole per rank).  Parity pins:
# tests/test_moe.py::test_seq_expert_tensor_parallel_matches_dense.
# The full pipe x seq x expert x tensor composition (16 devices) is
# exercised by
# tests/test_pipeline.py::test_pipeline_four_axis_pp_sp_ep_tp_subprocess.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --sp 2 --ep 2 --tp 2 --moe_experts 4 \
    --seq_len 32 --attention ring --grad_clip 1.0
