#!/usr/bin/env bash
# Preemption-aware fleet: the advance-notice drain vs the SIGKILL it
# replaces (utils/chaos.py, train/resilience.py, serve/fleet.py).
#
# Real platforms announce most capacity loss — a maintenance event or
# spot preemption carries a grace window before the hard kill.  This
# example runs the SAME failure against the same 2-replica subprocess
# fleet under the same seeded closed-loop traffic, twice:
#
# 1. SIGKILL arm — one replica is killed mid-load with no warning.
#    The router's request ledger requeues every in-flight request
#    (their already-decoded tokens are redone elsewhere: that is the
#    price of an unannounced death), and the supervisor relaunches
#    the replica — MTTR is SIGKILL -> relaunch -> jax import ->
#    accepting again.
#
# 2. NOTICE arm — the same replica instead receives the advance
#    notice (SIGUSR1 + notice file, GroupSupervisor.notify_preempt).
#    It stops accepting, finishes its in-flight requests, and exits
#    47 (decommission, terminal — no relaunch onto the doomed node)
#    while the autopilot backfills a replacement BEFORE the victim
#    dies.  The assertion that matters: ZERO requeued requests — no
#    work is redone anywhere in the notice arm.
#
# Both arms serve bitwise-identical traffic (the tokens hash is
# asserted equal across arms), so the requeue/MTTR delta is the
# failure's price, not the workload's noise.
set -euo pipefail

OUT=/tmp/nnpt_preemption_example
rm -rf "$OUT" && mkdir -p "$OUT"
export OUT

JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os

from neural_networks_parallel_training_with_mpi_tpu.utils import chaos

out = os.environ["OUT"]

print("== arm 1: SIGKILL mid-load (no warning) ==")
kill = chaos.run_scenario(
    {"name": "fleet_crash", "kind": "fleet", "mode": "kill",
     "replicas": 2, "clients": 8, "rpc": 5, "after_completed": 4},
    seed=0, log=print)

print("== arm 2: advance-notice drain (same failure, announced) ==")
notice = chaos.run_scenario(
    {"name": "fleet_preempt_notice", "kind": "fleet", "mode": "notice",
     "replicas": 2, "clients": 8, "rpc": 5, "after_completed": 4,
     "grace_s": 30.0, "backfill": True},
    seed=0, log=print)

for arm in (kill, notice):
    assert not arm["problems"], arm["problems"]
    assert arm["invariants"]["ledger_exact"], arm["invariants"]
    assert arm["invariants"]["no_duplicate_deliveries"], arm["invariants"]
km, nm = kill["metrics"], notice["metrics"]
# identical traffic: the A/B is apples-to-apples by construction
assert km["tokens_sha256"] == nm["tokens_sha256"], \
    (km["tokens_sha256"], nm["tokens_sha256"])
# the SIGKILL arm pays: every in-flight request requeued + redecoded
assert km["requeued"] > 0 and km["tokens_lost"] > 0, km
# the notice arm does not: zero requeues, exit 47, backfill decided
assert nm["requeued"] == 0 and nm["tokens_lost"] == 0, nm
assert notice["invariants"]["zero_requeue_on_notice"]
assert notice["invariants"]["victim_exited_47"]
assert notice["invariants"]["backfill_decided"]
assert notice["invariants"]["retired_stays_down"]

with open(os.path.join(out, "ab.json"), "w") as f:
    json.dump({"kill": km, "notice": nm}, f, indent=1, sort_keys=True,
              default=str)

print(f"SIGKILL arm: {km['requeued']} requests requeued, "
      f"{km['tokens_lost']} decoded tokens redone, "
      f"MTTR {km['mttr_s']}s (relaunch + import + prewarm)")
print(f"notice arm: zero requeued requests, victim drained to exit 47, "
      f"backfill reacted in {nm['reaction_s']}s")
print(f"identical traffic both arms: tokens sha256 "
      f"{km['tokens_sha256'][:16]}...")
EOF
echo "preemption drain example done"
