#!/usr/bin/env bash
# Mixture-of-experts transformer: expert weights sharded over the 'expert'
# axis, token slots exchanged by all_to_all (GShard arrangement).
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --dp 4 --ep 2 --moe_experts 4
