#!/usr/bin/env bash
# The reference's job, verbatim semantics (its four flags, now typed):
#   mpiexec -n N python dataParallelTraining_NN_MPI.py --lr 0.001 \
#       --momentum 0.9 --batch_size 4 --nepochs 3        (README.md:12)
# Parallelism comes from the device mesh instead of mpiexec.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --lr 0.001 --momentum 0.9 --batch_size 4 --nepochs 3
