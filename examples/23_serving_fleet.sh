#!/usr/bin/env bash
# Serving fleet: N replica PROCESSES behind one SLO-aware router, under
# the process-group supervisor (serve/fleet.py + train/resilience.py
# GroupSupervisor) — the repo's first many-cooperating-programs runtime.
#
# Two subprocess replicas (each its own jax runtime serving a paged
# continuous-batching scheduler, both built from the same init seed so
# their params are bit-identical) come up under the supervisor; the
# router load-balances a closed-loop mix of interactive (2 s SLO) and
# bulk (no SLO) clients across them using each replica's LIVE load
# report — the same serialized quantile-sketch rollup record
# tools/obs_agg.py merges.  Mid-load, replica 0 is SIGKILLed: its
# in-flight requests requeue at the router and complete on replica 1
# (greedy decode is deterministic, so the tokens are byte-identical to
# an undisturbed run — asserted below against a single-scheduler
# reference), and the supervisor relaunches it while the sibling keeps
# serving.  The merged per-replica fleet view prints at the end.
set -euo pipefail

python - <<'EOF'
import os, signal, time
from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

plat.pin("cpu", num_devices=1)

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    Scheduler, ServeConfig, launch_fleet, make_requests,
    run_fleet_closed_loop,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

MODEL = dict(vocab=64, seq=64, layers=2, d_model=32, heads=4, d_ff=64,
             init_seed=0)
SERVE = dict(slots=4, num_blocks=17, block_size=16, prefill_chunk=16,
             queue_depth=16)
CLIENTS, PER_CLIENT = 6, 3
TELE = "/tmp/nnpt_fleet_example"
os.system(f"rm -rf {TELE}")

# ---- undisturbed greedy reference (one in-process scheduler) ---------
model = Transformer(TransformerConfig(
    vocab_size=64, max_seq_len=64, n_layers=2, d_model=32, n_heads=4,
    d_ff=64))
params = model.init(prng.init_key(0))
plan = make_requests(CLIENTS, PER_CLIENT, vocab_size=64,
                     prompt_lens=(3, 10), max_new=(6, 10), seed=5)
ref_sched = Scheduler(model, params, ServeConfig(
    slots=4, num_blocks=64, block_size=16, prefill_chunk=16,
    queue_depth=64))
ref = {}
rids = {(ci, i): ref_sched.submit(r["prompt"], r["max_new"])
        for ci, reqs in enumerate(plan) for i, r in enumerate(reqs)}
ref_sched.run_until_drained()
for key, rid in rids.items():
    ref[key] = ref_sched.result(rid)
ref_sched.close()

# ---- the fleet: 2 supervised subprocess replicas + router ------------
# heartbeat_timeout catches the LIVE-but-stuck replica (wedged device,
# deadlocked loop) whose pipes stay open; dead processes are caught
# instantly by pipe-EOF regardless, as the SIGKILL below demonstrates
fleet = launch_fleet(2, model=MODEL, serve=SERVE, telemetry_root=TELE,
                     backoff=0.3, backoff_cap=1.0,
                     heartbeat_timeout=30.0,
                     log=lambda m: print(m))
try:
    fleet.wait_ready()
    print("fleet: 2 replicas ready")

    import threading
    killed = {}

    def chaos():
        time.sleep(2.0)
        proc = fleet.supervisor.proc("replica-0")
        killed["pid"] = proc.pid
        print(f"chaos: SIGKILL replica-0 (pid {proc.pid}) mid-load")
        os.kill(proc.pid, signal.SIGKILL)

    threading.Thread(target=chaos, daemon=True).start()
    row = run_fleet_closed_loop(
        fleet, CLIENTS, PER_CLIENT, vocab_size=64,
        prompt_lens=(3, 10), max_new=(6, 10), seed=5,
        classes=[{"name": "interactive", "slo_ms": 2000.0},
                 {"name": "bulk", "slo_ms": None}])
    assert "pid" in killed, "kill thread never fired"
    assert row["requests"] == CLIENTS * PER_CLIENT

    # byte-identical tokens across the death/requeue — the ledger holds
    # results by fleet rid; compare the digest the loadgen computed
    import hashlib
    h = hashlib.sha256()
    for key in sorted(ref):
        h.update(repr((key[0], key[1], ref[key])).encode())
    assert row["tokens_sha256"] == h.hexdigest(), \
        "fleet tokens diverged from the undisturbed reference"
    print(f"tokens byte-identical across the kill: "
          f"{row['requests']} requests, {row['tokens_out']} tokens, "
          f"{row['requeued']} requeued")
    print(f"interactive TTFT p50/p99 = "
          f"{row['ttft_ms_p50_interactive']:.1f}/"
          f"{row['ttft_ms_p99_interactive']:.1f} ms   "
          f"bulk p50 = {row['ttft_ms_p50_bulk']:.1f} ms")
    print(f"per-replica completions: {row['per_replica_completed']}")

    # the supervisor relaunched replica-0 without touching replica-1
    t0 = time.time()
    while time.time() - t0 < 30:
        fleet.pump()
        if any(e["child"] == "replica-0" and e["event"] == "relaunch"
               for e in fleet.events):
            break
        time.sleep(0.05)
    evs = [(e["child"], e["event"]) for e in fleet.events]
    assert ("replica-0", "relaunch") in evs, evs
    assert ("replica-1", "relaunch") not in evs
    print("supervisor: replica-0 relaunched; replica-1 undisturbed")
finally:
    fleet.close()
EOF

# ---- merged fleet view: router vs per-replica breakdown ---------------
python tools/obs_agg.py /tmp/nnpt_fleet_example/replica-* \
    /tmp/nnpt_fleet_example/router | sed -n '1,30p'
echo "fleet example done"
